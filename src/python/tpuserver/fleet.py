"""Fleet supervisor: process-level replica healing and elastic scaling.

PR 5 made the scheduler self-heal *inside* a process (supervised decode
loop) and the fleet router routes *around* a dead replica — but nothing
brought a replica *back*: a SIGKILL'd server process was gone forever
and the router's replica set was frozen at construction.
:class:`FleetSupervisor` lifts the supervised-restart pattern from
thread granularity (``DecodeScheduler._supervise``) to **process**
granularity — the reference survey's multi-process coordination role
(SURVEY §2.2/§5) applied to the serving tier — so the *fleet* becomes
the unit that survives, not any single replica:

1. **Ownership.**  The supervisor spawns N replica server processes
   from one command template (per-replica port, fault scope, index),
   fronts them with a :class:`~tpuserver.router.FleetRouter`, and keeps
   the router's live membership in sync: a replica joins the routing
   set only once its ``/v2/health/stats`` probe reports ready, and
   leaves it *before* the supervisor touches the process.
2. **Liveness.**  Two signals, both necessary: process exit (SIGKILL,
   crash, OOM) restarts immediately; an alive-but-unhealthy process —
   tripped scheduler (restart budget exhausted inside the process) or
   a wedge (consecutive probe failures while the process runs) — gets
   a **SIGTERM drain first** (the replica's ``install_sigterm_drain``
   path finishes in-flight generations; the router's cross-replica
   splice absorbs the rest), then SIGKILL past the grace window.
3. **Restart budget.**  Restarts per replica are bounded by
   ``max_restarts`` inside ``restart_window_s`` with exponential
   backoff between attempts; a replica that exhausts the budget is
   **retired** — the fleet degrades deterministically instead of
   flapping, exactly like the in-process scheduler's sticky trip.
4. **Elastic scaling.**  The supervisor reads each replica's scheduler
   utilization from the same health snapshot the router probes
   (``pending/max_pending``, ``live_streams/max_slots``) and scales the
   replica count between ``min_replicas``/``max_replicas`` with
   hysteresis: only *sustained* spill pressure scales up, only
   *sustained* idleness drains one replica down, a middle-band reading
   resets both streaks, and a cooldown follows every action — a single
   noisy window can never flap the fleet.
5. **A supervised front tier** (``router_command=``).  Every guarantee
   above flows through the router — so the router process itself gets
   the same treatment the replicas do: the supervisor spawns it (with
   ``--journal`` for crash-durable sticky state), probes it, heals a
   wedge drain-first under the same restart budget, and retires it on
   exhaustion.  With ``router_standby=True`` a second router process
   tails the same journal as a warm standby; on active-router death
   the supervisor PROMOTES the standby (``POST /router/promote`` — one
   reconnect for clients carrying both urls, never a lost stream) and
   respawns the casualty as the new standby.  Without a standby the
   active respawns on its own port with ``--journal``, recovering the
   sticky registry from disk.  Router ports are stable across every
   restart and role swap, so a client's url list never goes stale.

``tools/fleet.py`` is the CLI (and the default replica entry point);
``tools/chaos_smoke.py --fleet`` / ``--router-kill`` soak
SIGKILL-mid-traffic healing of replicas and the front tier;
docs/resilience.md "Fleet supervisor & elastic scaling" and "Router HA
& state durability" have the full semantics.
"""

import http.client
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque

from tpuserver import fleetmanifest
from tpuserver.router import FleetRouter

__all__ = ["FleetSupervisor", "ReplicaProcess", "RouterProcess"]


def _free_port(host):
    """Ask the kernel for a free port.  The tiny bind-to-spawn race is
    accepted: replica servers fail fast on a taken port and the restart
    budget absorbs the retry."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.bind((host, 0))
        return sock.getsockname()[1]
    finally:
        sock.close()


def _fetch_health(host, port, timeout_s):
    """One ``/v2/health/stats`` probe, or None when unreachable —
    the same snapshot (and the same cheapness argument) as the
    router's prober."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", "/v2/health/stats")
        resp = conn.getresponse()
        if resp.status != 200:
            return None
        return json.loads(resp.read())
    except (OSError, ValueError, http.client.HTTPException):
        return None
    finally:
        conn.close()


def _snapshot_utilization(snap):
    """A replica's load factor in ``[0, 1]`` from its health snapshot:
    the max of every scheduler's slot and admission-queue occupancy
    (sustained ``pending`` pressure == spill — the scale-up signal),
    falling back to the server-wide in-flight ratio for replicas with
    no scheduler-backed model."""
    if not isinstance(snap, dict):
        return 0.0
    util = 0.0
    seen_scheduler = False
    for stats in (snap.get("models") or {}).values():
        if not isinstance(stats, dict):
            continue
        seen_scheduler = True
        slots = stats.get("max_slots") or 0
        if slots:
            util = max(util, float(stats.get("live_streams") or 0) / slots)
        pending_cap = stats.get("max_pending") or 0
        if pending_cap:
            util = max(
                util, float(stats.get("pending") or 0) / pending_cap)
    if not seen_scheduler:
        cap = snap.get("max_inflight") or 0
        if cap:
            util = float(snap.get("inflight") or 0) / cap
    return min(1.0, util)


def _snapshot_tripped(snap):
    """Whether any model's scheduler reports a sticky trip (in-process
    restart budget exhausted): the replica is alive but will never
    serve again without a process restart."""
    if not isinstance(snap, dict):
        return False
    return any(
        isinstance(stats, dict) and stats.get("tripped")
        for stats in (snap.get("models") or {}).values()
    )


class ReplicaProcess:
    """One supervised replica: the OS process, its address, and the
    healing state machine (``starting`` → ``up`` → ``stopping`` /
    ``backoff`` → … → ``retired``).  All mutable state is owned by the
    supervisor's monitor thread; readers go through :meth:`stats`."""

    def __init__(self, index, host, port, scope, role=None):
        self.index = index
        self.host = host
        self.port = port
        self.scope = scope
        # phase role ("prefill"/"decode") or None for a fused replica;
        # immutable for the handle's lifetime — healing respawns the
        # process with the same role, so a phase pool never shrinks
        # because one of its members crashed
        self.role = role
        self.url = "{}:{}".format(host, port)
        self._lock = threading.Lock()
        self.proc = None           # guarded-by: _lock
        self.state = "starting"    # guarded-by: _lock
        self.in_router = False     # guarded-by: _lock
        self.restarts = 0          # guarded-by: _lock
        self.started_at = 0.0      # guarded-by: _lock
        self.stop_deadline = 0.0   # guarded-by: _lock
        self.spawn_at = 0.0        # guarded-by: _lock
        self.probe_failures = 0    # guarded-by: _lock
        self.last_util = 0.0       # guarded-by: _lock
        self.scale_down = False    # guarded-by: _lock
        # the spawn nonce the live child advertises (manifest mode)
        self.nonce = None          # guarded-by: _lock
        # restart timestamps inside the sliding budget window
        self.restart_times = deque()  # guarded-by: _lock
        # manifest row describing a predecessor's child to try
        # adopting at start(); consumed (set to None) by start()
        self.adopt_row = None

    def pid(self):
        with self._lock:
            return self.proc.pid if self.proc is not None else None

    def stats(self):
        with self._lock:
            return {
                "index": self.index,
                "url": self.url,
                "scope": self.scope,
                "role": self.role,
                "state": self.state,
                "pid": self.proc.pid if self.proc is not None else None,
                "restarts": self.restarts,
                "in_router": self.in_router,
                "utilization": round(self.last_util, 4),
            }


class RouterProcess:
    """One supervised router process — the ACTIVE front tier or its
    warm STANDBY.  Same healing state machine as a replica
    (``starting`` → ``up`` → ``stopping``/``backoff`` → … →
    ``retired``); the ``role`` swaps on takeover while the port stays
    stable, so a client's url list never goes stale."""

    def __init__(self, role, host, port, partition=None):
        self.host = host
        self.port = port
        self.url = "{}:{}".format(host, port)
        self._lock = threading.Lock()
        self.role = role           # guarded-by: _lock
        # the generation-id partition this router OWNS (multi-active
        # tier); moves with the role on takeover, None for the standby
        self.partition = partition  # guarded-by: _lock
        self.proc = None           # guarded-by: _lock
        self.state = "starting"    # guarded-by: _lock
        self.restarts = 0          # guarded-by: _lock
        self.started_at = 0.0      # guarded-by: _lock
        self.stop_deadline = 0.0   # guarded-by: _lock
        self.spawn_at = 0.0        # guarded-by: _lock
        self.probe_failures = 0    # guarded-by: _lock
        self.nonce = None          # guarded-by: _lock
        self.restart_times = deque()  # guarded-by: _lock
        self.adopt_row = None      # predecessor's row (see ReplicaProcess)

    def stats(self):
        with self._lock:
            return {
                "role": self.role,
                "url": self.url,
                "state": self.state,
                "pid": self.proc.pid if self.proc is not None else None,
                "restarts": self.restarts,
                "partition": self.partition,
            }


class _RouterAdminClient:
    """The in-process :class:`~tpuserver.router.FleetRouter` surface
    the supervisor (and its tests/tools) use, spoken over HTTP to
    supervised router PROCESSES: ``url`` tracks the active router
    across takeovers, membership mutations broadcast to active and
    standby (the standby keeps its membership live too), reads go to
    the active."""

    def __init__(self, supervisor):
        self._sup = supervisor

    @property
    def url(self):
        return self._sup.active_router_url()

    @property
    def port(self):
        return int(self.url.rpartition(":")[2])

    def start(self):
        return self  # the supervisor owns the processes

    def stop(self):
        pass

    def attach_supervisor(self, stats_fn):
        pass  # cross-process: /router/stats cannot call back in-process

    def _get(self, path):
        host, _, port = self.url.rpartition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=5)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return json.loads(resp.read())
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            conn.close()

    def stats(self):
        return self._get("/router/stats") or {}

    def membership(self):
        got = self._get("/router/replicas")
        return (got or {}).get("replicas", [])

    def add_replica(self, url):
        self._sup._router_membership_post("add", url)

    def remove_replica(self, url):
        self._sup._router_membership_post("remove", url)


class FleetSupervisor:
    """Own N replica server processes end-to-end and front them with a
    dynamically-membered :class:`~tpuserver.router.FleetRouter`.

    Parameters
    ----------
    command : list[str]
        argv template for one replica process; ``{port}``, ``{scope}``
        and ``{index}`` are substituted per spawn (see
        ``tools/fleet.py --serve-replica`` for the default server).
    replicas / min_replicas / max_replicas
        Initial process count and the elastic-scaling bounds.  With
        role pools the bounds apply PER POOL (each phase scales
        between them independently).
    prefill_replicas / decode_replicas
        Opt-in disaggregated prefill/decode: spawn this many replicas
        per phase role (both must be >= 1 when either is set).  Each
        role-tagged replica gets ``--role <role>`` appended to its
        argv, advertises the role in its health snapshot, and is
        healed/scaled within its own pool; ``replicas`` then only adds
        extra fused capacity on top (its default is ignored).
    probe_interval_s / probe_timeout_s
        Monitor cadence and per-probe timeout.
    start_timeout_s
        How long a spawned replica may stay not-ready (warmup compiles
        included) before the start counts as a failed restart.
    drain_grace_s
        SIGTERM-to-SIGKILL window for planned restarts and scale-down
        (the replica's ``install_sigterm_drain`` drains inside it).
    max_restarts / restart_window_s / restart_backoff_s
        Per-replica restart budget (sliding window) and the exponential
        backoff base between attempts; budget exhausted ⇒ retired.
    unhealthy_after
        Consecutive failed probes of a live process that count as a
        wedge (a booted replica that stops answering without exiting).
    scale_high / scale_low
        Fleet-mean utilization thresholds (hysteresis band edges).
    scale_up_windows / scale_down_windows
        Consecutive monitor ticks the signal must persist before a
        scaling action fires; a middle-band tick resets both streaks.
    scale_cooldown_s
        Dead time after any scaling action (and any restart) before the
        next one may fire — boot transients never read as pressure.
    router_kwargs
        Extra :class:`FleetRouter` construction kwargs (e.g.
        ``probe_interval_s``, ``max_inflight``, ``port``) — the
        in-process router mode.
    router_command
        Opt-in SUPERVISED FRONT TIER: an argv template for a router
        *process* (``{port}``, ``{backends}``, ``{journal}``
        substituted per spawn — see ``tools/fleet.py
        --router-processes`` for the default built on
        ``tools/router.py``).  The supervisor spawns, probes, and
        heals the router under the same drain-first, restart-budgeted,
        retire-on-exhaustion policy replicas get; ``self.router``
        becomes an HTTP admin shim with the same surface.  None
        (default) keeps the in-process FleetRouter.
    router_standby
        With ``router_command``: also run a warm-standby router
        process tailing the same journal; on active death the standby
        is PROMOTED (and the casualty respawns as the new standby).
    router_journal
        The journal directory both router processes share.  Default: a
        fresh temporary directory owned (and removed) by the
        supervisor.
    router_port / standby_port
        Stable listen ports for the two router processes (0 = pick a
        free one at construction; the port then stays stable across
        restarts and role swaps).
    active_routers
        Horizontal front tier (requires ``router_command``): run N
        SIMULTANEOUSLY-ACTIVE routers, each owning a stable partition
        of the generation-id space with its own journal subdirectory
        (``p<index>`` under ``router_journal`` — single-writer stays
        an invariant per partition) and peer-forwarding requests that
        hash to a sibling.  On an active's death the standby promotes
        INTO the dead router's partition; partition-map changes
        broadcast to every router under a monotonically-bumped epoch.
        1 (default) keeps the PR-15 single-active tier byte-identical.
    env
        Extra environment for replica processes (merged over
        ``os.environ``).
    manifest_dir
        Opt-in SUPERVISOR CRASH DURABILITY: the fleet-state manifest
        directory (``tpuserver.fleetmanifest``).  Every spawn /
        restart / retire / scale / promote is recorded off the hot
        path; a successor supervisor started with the SAME directory
        replays it and ADOPTS still-live children (pid + start-time
        token + spawn-nonce echo all required) instead of respawning
        a healthy fleet.  An exclusive ``flock`` on the directory
        enforces single-writer discipline — a second concurrent
        supervisor gets a typed :class:`fleetmanifest.ManifestLocked`
        refusal.
    takeover / takeover_timeout_s
        With ``manifest_dir``: wait (bounded) for the incumbent
        supervisor's lock instead of refusing — the supervised
        handover path.
    heartbeat_file
        Stamp a monotonic heartbeat (seq + adoption/healing counters
        + per-replica state) to this path every monitor tick, written
        atomically — an external watchdog or chaos harness detects a
        wedged/killed supervisor by the seq going stale.
    """

    #: manifest records between compacting checkpoints
    _CHECKPOINT_EVERY = 256

    def __init__(self, command, replicas=2, min_replicas=1,
                 max_replicas=None, host="127.0.0.1",
                 probe_interval_s=0.5, probe_timeout_s=2.0,
                 start_timeout_s=120.0, drain_grace_s=10.0,
                 max_restarts=5, restart_window_s=60.0,
                 restart_backoff_s=0.2, unhealthy_after=3,
                 scale_high=0.85, scale_low=0.10,
                 scale_up_windows=3, scale_down_windows=6,
                 scale_cooldown_s=2.0, scope_prefix="fleet-r",
                 router_kwargs=None, env=None, verbose=False,
                 router_command=None, router_standby=False,
                 router_journal=None, router_port=0, standby_port=0,
                 active_routers=1,
                 prefill_replicas=0, decode_replicas=0,
                 manifest_dir=None, takeover=False,
                 takeover_timeout_s=30.0, heartbeat_file=None):
        prefill_replicas = int(prefill_replicas)
        decode_replicas = int(decode_replicas)
        role_mode = prefill_replicas > 0 or decode_replicas > 0
        if role_mode and (prefill_replicas < 1 or decode_replicas < 1):
            raise ValueError(
                "a phase-split fleet needs at least one replica of "
                "EACH role (got prefill={}, decode={}) — a missing "
                "pool would silently serve every request fused"
                .format(prefill_replicas, decode_replicas))
        if role_mode:
            # role mode: the per-role targets ARE the fleet; 'replicas'
            # only adds extra fused capacity on top when given
            replicas = max(0, int(replicas)) if replicas != 2 else 0
        if replicas < 1 and not role_mode:
            raise ValueError("a fleet needs at least one replica")
        if min_replicas < 1 or (max_replicas is not None
                                and max_replicas < min_replicas):
            raise ValueError(
                "need 1 <= min_replicas <= max_replicas (got {}..{})"
                .format(min_replicas, max_replicas))
        if not (0.0 <= scale_low < scale_high <= 1.0):
            raise ValueError(
                "hysteresis band must satisfy 0 <= scale_low < "
                "scale_high <= 1 (got {}..{})".format(
                    scale_low, scale_high))
        self._command = list(command)
        self._host = host
        self._min_replicas = int(min_replicas)
        self._max_replicas = (int(max_replicas)
                              if max_replicas is not None else None)
        self._probe_interval_s = float(probe_interval_s)
        self._probe_timeout_s = float(probe_timeout_s)
        self._start_timeout_s = float(start_timeout_s)
        self._drain_grace_s = float(drain_grace_s)
        self._max_restarts = int(max_restarts)
        self._restart_window_s = float(restart_window_s)
        self._restart_backoff_s = float(restart_backoff_s)
        self._unhealthy_after = int(unhealthy_after)
        self._scale_high = float(scale_high)
        self._scale_low = float(scale_low)
        self._scale_up_windows = int(scale_up_windows)
        self._scale_down_windows = int(scale_down_windows)
        self._scale_cooldown_s = float(scale_cooldown_s)
        self._scope_prefix = scope_prefix
        self._env = dict(env or {})
        self._verbose = verbose
        self._lock = threading.Lock()
        # the managed set; retired handles stay (visible in stats) but
        # are skipped by every healing/scaling path
        # guarded-by: _lock
        self._handles = []
        self._next_index = 0       # guarded-by: _lock
        self._restarts_total = 0   # guarded-by: _lock
        self._scale_ups = 0        # guarded-by: _lock
        self._scale_downs = 0      # guarded-by: _lock
        self._retired = 0          # guarded-by: _lock
        # front-tier healing counters (router_command mode)
        self._router_restarts = 0  # guarded-by: _lock
        self._router_takeovers = 0  # guarded-by: _lock
        self._router_retired = 0   # guarded-by: _lock
        self._cooldown_until = 0.0
        self._stop = threading.Event()
        self._monitor = None
        # per-role scaling streaks (keys: None/"prefill"/"decode") —
        # each phase pool accumulates pressure independently, so a
        # decode-heavy workload grows decode capacity without touching
        # the prefill pool (and vice versa)
        self._role_up_streaks = {}
        self._role_down_streaks = {}
        # -- crash durability (manifest mode) -----------------------------
        self._heartbeat_file = heartbeat_file
        self._heartbeat_seq = 0          # guarded-by: _lock
        self._adoptions = 0              # guarded-by: _lock
        self._clean_handovers = 0        # guarded-by: _lock
        self._stale_reaped = 0           # guarded-by: _lock
        self._manifest_records = 0       # guarded-by: _lock
        self._records_since_checkpoint = 0  # guarded-by: _lock
        self._manifest = None
        self._manifest_lock_fd = None
        self._argv_hash = fleetmanifest.argv_template_hash(self._command)
        recovered = None
        if manifest_dir is not None:
            # single-writer discipline FIRST: the lock must be held
            # before we read state another supervisor may be writing
            self._manifest_lock_fd = fleetmanifest.acquire_manifest_lock(
                manifest_dir, takeover=takeover,
                timeout_s=takeover_timeout_s)
            records, _torn = fleetmanifest.read_manifest(manifest_dir)
            if records:
                recovered = fleetmanifest.fold_manifest(records)
            self._manifest = fleetmanifest.ManifestWriter(manifest_dir)
        if recovered is not None:
            counters = recovered["counters"]
            self._restarts_total = counters["replica_restarts"]
            self._scale_ups = counters["scale_up_events"]
            self._scale_downs = counters["scale_down_events"]
            self._retired = counters["retired_replicas"]
            self._router_restarts = counters["router_restarts"]
            self._router_takeovers = counters["router_takeovers"]
            self._router_retired = counters["router_retired"]
            self._adoptions = counters["adoptions"]
            self._clean_handovers = counters["clean_handovers"]
            self._stale_reaped = counters["stale_children_reaped"]
            self._manifest_records = counters["manifest_records"]
        if recovered is not None and recovered["replicas"]:
            # the manifest IS the fleet: rebuild handles with their
            # ports, roles, and restart-budget windows intact; start()
            # decides adopt-vs-respawn per child
            for index in sorted(recovered["replicas"]):
                row = recovered["replicas"][index]
                handle = ReplicaProcess(
                    index, host, int(row["port"]),
                    row.get("scope")
                    or "{}{}".format(scope_prefix, index),
                    role=row.get("role"))
                handle.restarts = int(row.get("restarts") or 0)
                handle.restart_times = deque(
                    row.get("restart_times") or [])
                if row.get("retired"):
                    handle.state = "retired"
                handle.adopt_row = dict(row)
                with self._lock:
                    self._handles.append(handle)
            with self._lock:
                self._next_index = max(
                    int(recovered["next_index"] or 0),
                    max(recovered["replicas"]) + 1)
            role_mode = role_mode or any(
                row.get("role")
                for row in recovered["replicas"].values())
        else:
            for _ in range(int(replicas)):
                self._register_handle()
            for _ in range(prefill_replicas):
                self._register_handle(role="prefill")
            for _ in range(decode_replicas):
                self._register_handle(role="decode")
        self._role_mode = role_mode
        self._router_command = (list(router_command)
                                if router_command else None)
        self._router_standby = bool(router_standby)
        self._active_routers = max(1, int(active_routers))
        if self._active_routers > 1 and self._router_command is None:
            raise ValueError(
                "active_routers > 1 needs router_command — only "
                "supervised router PROCESSES can partition the "
                "generation-id space (the in-process router is one "
                "object)")
        # partition-map epoch: bumps on every map change (takeover,
        # member coming up); routers adopt only strictly newer maps,
        # so a late broadcast can never roll ownership backwards.
        # Recovery: the epoch only ever bumps alongside a broadcast,
        # and takeovers are the floor — 1 + takeovers is >= any epoch
        # a predecessor pushed for those takeovers, and the adopting
        # supervisor re-broadcasts (bumping again) before it matters.
        self._partition_epoch = 1 + self._router_takeovers  # guarded-by: _lock
        self._journal_tmp = None
        self._router_journal = router_journal
        # router PROCESS handles (router_command mode); role swaps on
        # takeover, the list itself is fixed at construction
        # guarded-by: _lock
        self._router_handles = []
        if self._router_command is not None:
            # the supervised front tier: router processes sharing one
            # crash journal, fronted to callers by the admin shim
            if self._router_journal is None:
                if recovered is not None and recovered["router_journal"]:
                    # RE-ATTACH the predecessor's journal: the live
                    # (or respawning) routers' sticky state lives
                    # there, and ownership of a temp directory
                    # transfers to the adopting supervisor
                    self._router_journal = recovered["router_journal"]
                    if recovered["journal_owned"]:
                        self._journal_tmp = self._router_journal
                else:
                    self._journal_tmp = tempfile.mkdtemp(
                        prefix="tpu-router-journal-")
                    self._router_journal = self._journal_tmp
            if recovered is not None and recovered["routers"]:
                handles = []
                for port in sorted(
                        recovered["routers"],
                        key=lambda p: (recovered["routers"][p].get(
                            "role") != "active", p)):
                    row = recovered["routers"][port]
                    rhandle = RouterProcess(
                        row.get("role") or "active", host, port,
                        partition=row.get("partition"))
                    rhandle.restarts = int(row.get("restarts") or 0)
                    rhandle.restart_times = deque(
                        row.get("restart_times") or [])
                    if row.get("retired"):
                        rhandle.state = "retired"
                    rhandle.adopt_row = dict(row)
                    handles.append(rhandle)
                parts = [h.partition for h in handles
                         if h.partition is not None]
                if parts:
                    # the manifest IS the partition map too: the
                    # active-set width is however many partitions the
                    # predecessor ran, whatever this process was told
                    self._active_routers = max(
                        self._active_routers, max(parts) + 1)
                actives = sum(
                    1 for h in handles if h.role == "active")
                self._router_standby = (self._router_standby
                                        or len(handles) > actives)
            else:
                handles = [RouterProcess(
                    "active", host,
                    int(router_port) or _free_port(host),
                    partition=0 if self._active_routers > 1 else None)]
                for part in range(1, self._active_routers):
                    handles.append(RouterProcess(
                        "active", host, _free_port(host),
                        partition=part))
                if self._router_standby:
                    handles.append(RouterProcess(
                        "standby", host,
                        int(standby_port) or _free_port(host)))
            with self._lock:
                self._router_handles = handles
            self.router = _RouterAdminClient(self)
            self._manifest_append({
                "type": "config",
                "router_journal": self._router_journal,
                "journal_owned": self._journal_tmp is not None,
            })
        else:
            self.router = FleetRouter(
                [h.url for h in self._handles_snapshot()],
                **dict(router_kwargs or {}))
            self.router.attach_supervisor(self.stats)
        # the initial handles ARE the router's constructed membership
        # (in-process construction list / the spawned router's
        # --backends); record that so a replica dying before its first
        # ready probe still leaves the routing set instead of
        # lingering as a stale member
        for handle in self._handles_snapshot():
            with handle._lock:
                handle.in_router = True

    # -- lifecycle ---------------------------------------------------------

    def _register_handle(self, role=None):
        """Allocate a port + scope and register a fresh handle (called
        from __init__ and scale-up; ``role`` tags a phase-pool
        member)."""
        port = _free_port(self._host)
        with self._lock:
            index = self._next_index
            self._next_index += 1
            handle = ReplicaProcess(
                index, self._host, port,
                "{}{}".format(self._scope_prefix, index), role=role)
            self._handles.append(handle)
        return handle

    def _handles_snapshot(self):
        with self._lock:
            return list(self._handles)

    def start(self):
        now = time.monotonic()
        for handle in self._handles_snapshot():
            row, handle.adopt_row = handle.adopt_row, None
            if handle.stats()["state"] == "retired":
                continue
            if row is not None:
                if self._try_adopt_replica(handle, row):
                    continue
                # adoption refused (dead/stale/unreachable child): the
                # normal budget path charges the restart and schedules
                # the respawn with backoff — a crash-looping replica
                # must not dodge retirement by crashing the supervisor
                self._finish_stop(handle, now)
                continue
            self._spawn(handle)
        for rhandle in self._router_handles_snapshot():
            row, rhandle.adopt_row = rhandle.adopt_row, None
            if rhandle.stats()["state"] == "retired":
                continue
            if row is not None:
                if self._try_adopt_router(rhandle, row):
                    continue
                self._finish_router_stop(rhandle, now)
                continue
            self._spawn_router(rhandle)
        self.router.start()
        if self._manifest is not None:
            self._checkpoint_manifest()
        self._stamp_heartbeat()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-supervisor",
            daemon=True)
        self._monitor.start()
        return self

    def stop(self, drain_timeout_s=None):
        """Stop the fleet: SIGTERM every live replica AND router
        process (drain-first — the router flushes its journal inside
        the grace window), SIGKILL whatever outlives it."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10)
            self._monitor = None
        grace = (self._drain_grace_s if drain_timeout_s is None
                 else drain_timeout_s)
        handles = self._handles_snapshot() + self._router_handles_snapshot()
        for handle in handles:
            self._signal(handle, signal.SIGTERM)
        deadline = time.monotonic() + grace
        for handle in handles:
            self._reap(handle, deadline - time.monotonic())
        for handle in self._router_handles_snapshot():
            # past-grace stragglers: the reap's kill covered them, but
            # an unkillable process must not wedge shutdown
            self._signal(handle, signal.SIGKILL)
        self.router.stop()
        # the final checkpoint records the fleet's last known shape;
        # the children are dead, so a successor respawns everything
        self._close_manifest(checkpoint=True)
        if self._journal_tmp is not None:
            shutil.rmtree(self._journal_tmp, ignore_errors=True)

    def handover(self, timeout_s=10.0):
        """Graceful supervisor handover (the manifest-mode SIGTERM
        disposition): checkpoint the manifest, release the writer
        lock, and exit WITHOUT touching the children — they keep
        serving unsupervised until a successor adopts them.  The
        in-process router (no router_command) cannot outlive this
        process, so it still stops; supervised router PROCESSES keep
        serving like the replicas."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=timeout_s)
            self._monitor = None
        with self._lock:
            self._clean_handovers += 1
        self._stamp_heartbeat()
        self._close_manifest(checkpoint=True)
        if not self._router_handles_snapshot():
            self.router.stop()

    def crash(self):
        """Die like SIGKILL (test/chaos hook): no checkpoint, no child
        signals, no journal cleanup — only what the kernel would do
        anyway (release the flock when the process vanishes), plus
        stopping the in-process router this process hosts."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10)
            self._monitor = None
        if self._manifest is not None:
            self._manifest.close()
            self._manifest = None
        if self._manifest_lock_fd is not None:
            fleetmanifest.release_manifest_lock(self._manifest_lock_fd)
            self._manifest_lock_fd = None
        if not self._router_handles_snapshot():
            self.router.stop()

    def wait_ready(self, count=None, timeout_s=60.0):
        """Block until ``count`` replicas (default: every non-retired
        one) are up and routed; returns True on success."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            stats = self.stats()
            want = count if count is not None else sum(
                1 for r in stats["replicas"] if r["state"] != "retired")
            if sum(1 for r in stats["replicas"]
                   if r["state"] == "up") >= want:
                return True
            if self._stop.wait(0.05):
                return False
        return False

    # -- process plumbing --------------------------------------------------

    def _log(self, msg):
        if self._verbose:
            print("[fleet-supervisor] " + msg, file=sys.stderr,
                  flush=True)

    def _spawn(self, handle):
        argv = [
            t.format(port=handle.port, scope=handle.scope,
                     index=handle.index)
            for t in self._command
        ]
        if handle.role:
            # phase-pool member: the replica advertises its role in
            # /v2/health/stats so the router's prober can partition
            # the fleet into prefill/decode pools
            argv += ["--role", handle.role]
        nonce = None
        if self._manifest is not None:
            # the adoption contract's third identity: a successor only
            # adopts a pid whose /v2/health/stats echoes THIS nonce
            nonce = fleetmanifest.new_spawn_nonce()
            argv += ["--spawn-nonce", nonce]
        env = dict(os.environ)
        env.update(self._env)
        try:
            proc = subprocess.Popen(argv, env=env)
        except OSError as e:
            self._log("spawn of replica {} failed: {}".format(
                handle.url, e))
            proc = None
        now = time.monotonic()
        with handle._lock:
            handle.proc = proc
            handle.state = "starting"
            handle.started_at = now
            handle.probe_failures = 0
            handle.nonce = nonce
        if proc is not None and self._manifest is not None:
            self._manifest_append({
                "type": "spawn",
                "index": handle.index,
                "role": handle.role,
                "port": handle.port,
                "scope": handle.scope,
                "pid": proc.pid,
                "start_token": fleetmanifest.process_start_token(
                    proc.pid),
                "nonce": nonce,
                "argv_hash": self._argv_hash,
            })
        self._log("spawned replica {} (pid {})".format(
            handle.url, proc.pid if proc else "-"))

    def _signal(self, handle, signum):
        with handle._lock:
            proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.send_signal(signum)
        except (ProcessLookupError, OSError):
            pass

    def _reap(self, handle, timeout_s):
        with handle._lock:
            proc = handle.proc
        if proc is None:
            return
        try:
            proc.wait(timeout=max(0.0, timeout_s))
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
            except (ProcessLookupError, OSError):
                pass
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass

    def _leave_router(self, handle):
        with handle._lock:
            was_member = handle.in_router
            handle.in_router = False
        if not was_member:
            return
        try:
            self.router.remove_replica(handle.url)
        except KeyError:
            pass

    def _join_router(self, handle):
        with handle._lock:
            if handle.in_router:
                return
            handle.in_router = True
        try:
            self.router.add_replica(handle.url)
        except ValueError:
            pass  # already a member (initial membership)

    # -- the supervised front tier (router_command mode) -------------------

    def _router_handles_snapshot(self):
        with self._lock:
            return list(self._router_handles)

    def active_router_url(self):
        """The ACTIVE router's stable address (in-process mode: the
        embedded router's).  When no handle holds the active role —
        e.g. the active retired while its standby was down — prefer a
        LIVE handle over list order: admin reads against a corpse
        would answer nothing forever while a serving peer exists."""
        handles = self._router_handles_snapshot()
        if not handles:
            return self.router.url  # in-process FleetRouter
        rows = [(h, h.stats()) for h in handles]
        for handle, st in rows:
            if st["role"] == "active" and st["state"] != "retired":
                return handle.url
        for handle, st in rows:
            if st["state"] == "up":
                return handle.url
        return handles[0].url

    def router_urls(self):
        """Every router address, active first — the url list clients
        carry so a takeover costs one reconnect (the auto-resume
        helpers' ``fallback_urls``)."""
        handles = self._router_handles_snapshot()
        if not handles:
            return [self.router.url]
        ordered = sorted(
            handles, key=lambda h: h.stats()["role"] != "active")
        return [h.url for h in ordered]

    def _partition_map_snapshot(self):
        """url-by-partition for the active set ("" for a partition
        with no live owner — retired, or mid-takeover)."""
        urls = [""] * self._active_routers
        for handle in self._router_handles_snapshot():
            st = handle.stats()
            part = st.get("partition")
            if part is not None and st["state"] != "retired":
                urls[int(part)] = handle.url
        return urls

    def _router_argv(self, handle):
        backends = ",".join(
            h.url for h in self._handles_snapshot()
            if h.stats()["state"] != "retired")
        argv = [
            t.format(port=handle.port, backends=backends,
                     journal=self._router_journal)
            for t in self._router_command
        ]
        st = handle.stats()
        if st["role"] == "standby":
            argv.append("--standby")
        if self._active_routers > 1:
            # the partitioned tier: actives get their stable partition
            # index, the standby only the count (it tails EVERY
            # partition's journal until promoted into one); all carry
            # the current map + epoch so a respawn rejoins current
            argv += ["--partition-count", str(self._active_routers)]
            if st.get("partition") is not None:
                argv += ["--partition-index", str(st["partition"])]
            with self._lock:
                epoch = self._partition_epoch
            argv += ["--peers", ",".join(self._partition_map_snapshot()),
                     "--epoch", str(epoch)]
        return argv

    def _spawn_router(self, handle):
        argv = self._router_argv(handle)
        nonce = None
        if self._manifest is not None:
            nonce = fleetmanifest.new_spawn_nonce()
            argv += ["--spawn-nonce", nonce]
        env = dict(os.environ)
        env.update(self._env)
        try:
            proc = subprocess.Popen(argv, env=env)
        except OSError as e:
            self._log("spawn of router {} failed: {}".format(
                handle.url, e))
            proc = None
        now = time.monotonic()
        with handle._lock:
            role = handle.role
            partition = handle.partition
            handle.proc = proc
            handle.state = "starting"
            handle.started_at = now
            handle.probe_failures = 0
            handle.nonce = nonce
        if proc is not None and self._manifest is not None:
            self._manifest_append({
                "type": "router_spawn",
                "role": role,
                "partition": partition,
                "port": handle.port,
                "pid": proc.pid,
                "start_token": fleetmanifest.process_start_token(
                    proc.pid),
                "nonce": nonce,
            })
        self._log("spawned {} router {} (pid {})".format(
            role, handle.url, proc.pid if proc else "-"))

    def _router_membership_post(self, action, url):
        """Apply one membership mutation to EVERY live router process
        (the standby keeps its membership warm too).  A router that is
        down simply misses the post — its respawn rebuilds
        ``--backends`` from the current handle set."""
        body = json.dumps({"action": action, "url": url})
        for handle in self._router_handles_snapshot():
            if handle.stats()["state"] not in ("up", "starting"):
                continue
            conn = http.client.HTTPConnection(
                handle.host, handle.port, timeout=self._probe_timeout_s)
            try:
                conn.request("POST", "/router/replicas", body,
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                pass
            finally:
                conn.close()

    def _promote_standby(self, handle, payload=None):
        """POST the takeover signal to a standby router; True when the
        promotion was acknowledged.  ``payload`` (partitioned tier)
        names the partition the standby promotes INTO plus the new
        map + epoch it should serve."""
        body = (json.dumps(payload).encode("utf-8")
                if payload else b"{}")
        conn = http.client.HTTPConnection(
            handle.host, handle.port, timeout=self._probe_timeout_s)
        try:
            conn.request("POST", "/router/promote", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            # a 200 from an already-active router counts too: the
            # takeover's goal state (an active on that address) holds
            return resp.status == 200
        except (OSError, http.client.HTTPException):
            return False
        finally:
            conn.close()

    def _bump_partition_epoch(self):
        """Mint the next partition-map epoch (bumped eagerly — a
        broadcast/promote that then fails just skips a value; epochs
        only need monotonicity, not density)."""
        with self._lock:
            self._partition_epoch += 1
            return self._partition_epoch

    def _broadcast_partition_map(self):
        """Push the current partition map under a FRESH epoch to every
        live router: actives peer-forward by it, the standby keeps it
        warm for promotion.  Routers adopt only strictly newer epochs,
        so a reordered/late post can never roll ownership backwards; a
        router that is down simply misses the post — its respawn argv
        carries the then-current map."""
        if self._active_routers <= 1:
            return
        body = json.dumps({
            "action": "set_map",
            "map": self._partition_map_snapshot(),
            "epoch": self._bump_partition_epoch(),
        })
        for handle in self._router_handles_snapshot():
            if handle.stats()["state"] not in ("up", "starting"):
                continue
            conn = http.client.HTTPConnection(
                handle.host, handle.port, timeout=self._probe_timeout_s)
            try:
                conn.request("POST", "/router/partition", body,
                             {"Content-Type": "application/json"})
                conn.getresponse().read()
            except (OSError, http.client.HTTPException):
                pass
            finally:
                conn.close()

    def _router_takeover(self, casualty, alive):
        """The active router died (or wedged): promote the warm
        standby when one is up — clients carrying both urls reconnect
        once and resume against journal-recovered state — and re-roll
        the casualty as the NEW standby; otherwise the casualty simply
        respawns active with ``--journal`` and recovers from disk."""
        standby = None
        for handle in self._router_handles_snapshot():
            if handle is casualty:
                continue
            st = handle.stats()
            if st["role"] == "standby" and st["state"] == "up":
                standby = handle
                break
        if standby is not None and alive:
            # single-writer discipline: a wedged-but-RUNNING active
            # may still be appending to the journal, and the promoted
            # standby is about to open its own writer — draining the
            # casualty here would interleave two writers in one
            # directory.  A wedged router's streams are already lost
            # to their clients (that is what the probe failures mean);
            # resuming them through the new active IS the recovery
            # path, so the casualty goes down hard, and the promote
            # only fires once its process is provably gone.
            self._begin_router_restart(casualty, "wedged", drain=False)
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                with casualty._lock:
                    proc = casualty.proc
                if proc is None or proc.poll() is not None:
                    break
                time.sleep(0.02)
        payload = None
        part = None
        if standby is not None:
            with casualty._lock:
                part = casualty.partition
            if part is not None:
                # partitioned tier: the standby promotes INTO the
                # casualty's partition — scoped journal re-attach plus
                # the map rebind, under a fresh epoch
                peers = self._partition_map_snapshot()
                peers[part] = standby.url
                payload = {"partition": part, "peers": peers,
                           "epoch": self._bump_partition_epoch()}
        if standby is not None and self._promote_standby(standby,
                                                         payload):
            with standby._lock:
                standby.role = "active"
                standby.partition = part
            with casualty._lock:
                casualty.role = "standby"
                casualty.partition = None
            with self._lock:
                self._router_takeovers += 1
            self._manifest_append({
                "type": "promote",
                "active_port": standby.port,
                "standby_port": casualty.port,
                "partition": part,
            })
            if part is not None:
                # siblings (and the demoted slot, once respawned)
                # learn the rebind; clients chase it via the epoch in
                # /router/stats and resume answers
                self._broadcast_partition_map()
            self._log(
                "router takeover: standby {} promoted to active"
                "{}; {} will respawn as the new standby".format(
                    standby.url,
                    " (partition {})".format(part)
                    if part is not None else "",
                    casualty.url))
        if alive:
            if standby is None:
                # no standby to protect: drain first (the router
                # flushes its journal on SIGTERM), SIGKILL past the
                # grace window — there is no second writer to race
                self._begin_router_restart(casualty, "wedged",
                                           drain=True)
        else:
            self._finish_router_stop(casualty, time.monotonic())

    def _begin_router_restart(self, handle, reason, drain):
        self._log("restarting router {} ({}{})".format(
            handle.url, reason, ", drain-first" if drain else ""))
        now = time.monotonic()
        with handle._lock:
            handle.state = "stopping"
            handle.stop_deadline = now + (self._drain_grace_s
                                          if drain else 0.0)
        self._signal(handle,
                     signal.SIGTERM if drain else signal.SIGKILL)

    def _finish_router_stop(self, handle, now):
        """The router process is gone: retire on an exhausted budget,
        else schedule the respawn with backoff (same sliding-window
        policy the replicas get)."""
        with handle._lock:
            window = handle.restart_times
            while window and now - window[0] > self._restart_window_s:
                window.popleft()
            if len(window) >= self._max_restarts:
                handle.state = "retired"
                retired = True
            else:
                window.append(now)
                handle.restarts += 1
                handle.state = "backoff"
                handle.spawn_at = now + self._restart_backoff_s * (
                    2 ** max(0, len(window) - 1))
                retired = False
            restarts = handle.restarts
            window_copy = list(window)
        with self._lock:
            if retired:
                self._router_retired += 1
            else:
                self._router_restarts += 1
        self._manifest_append({
            "type": "router_retire" if retired else "router_restart",
            "port": handle.port,
            "restarts": restarts, "restart_times": window_copy,
        })
        if retired:
            self._log(
                "router {} exhausted its restart budget ({} in {}s) — "
                "retired; the front tier degrades to its peer".format(
                    handle.url, self._max_restarts,
                    self._restart_window_s))

    def _tick_routers(self, now):
        for handle in self._router_handles_snapshot():
            with handle._lock:
                state = handle.state
                role = handle.role
                proc = handle.proc
                stop_deadline = handle.stop_deadline
                spawn_at = handle.spawn_at
                started_at = handle.started_at
            if state == "retired":
                continue
            exited = proc is None or proc.poll() is not None
            if state == "stopping":
                if exited:
                    self._finish_router_stop(handle, now)
                elif now >= stop_deadline:
                    self._signal(handle, signal.SIGKILL)
                continue
            if state == "backoff":
                if now >= spawn_at:
                    self._spawn_router(handle)
                continue
            if exited:
                # unplanned death (SIGKILL/crash): an active's standby
                # promotes NOW — the takeover, the whole point of the
                # warm copy — and the casualty respawns as standby
                if role == "active":
                    self._router_takeover(handle, alive=False)
                else:
                    self._finish_router_stop(handle, now)
                continue
            snap = _fetch_health(handle.host, handle.port,
                                 self._probe_timeout_s)
            if snap is None:
                with handle._lock:
                    handle.probe_failures += 1
                    failures = handle.probe_failures
                if state == "starting":
                    if now - started_at > self._start_timeout_s:
                        self._begin_router_restart(
                            handle, "never came up", drain=False)
                elif failures >= self._unhealthy_after:
                    # alive but not answering: a wedged front tier is
                    # a total outage — fail over to the standby first,
                    # then drain-replace the process
                    if role == "active":
                        self._router_takeover(handle, alive=True)
                    else:
                        self._begin_router_restart(
                            handle, "wedged", drain=True)
                continue
            with handle._lock:
                handle.probe_failures = 0
                if handle.state == "starting":
                    handle.state = "up"
                    came_up = True
                else:
                    came_up = False
            if came_up:
                self._log("{} router {} is up".format(role, handle.url))
                # partitioned tier: a member coming up (respawned
                # casualty, healed active) re-syncs everyone's map —
                # its own argv carried the spawn-time map, but siblings
                # may have learned it is back only just now
                self._broadcast_partition_map()

    # -- healing -----------------------------------------------------------

    def _begin_restart(self, handle, reason, drain):
        """Take a replica out of rotation and (drain-)stop its process;
        the monitor finishes the restart once the process exits."""
        self._log("restarting replica {} ({}{})".format(
            handle.url, reason, ", drain-first" if drain else ""))
        self._leave_router(handle)
        now = time.monotonic()
        with handle._lock:
            handle.state = "stopping"
            handle.stop_deadline = now + (self._drain_grace_s
                                          if drain else 0.0)
        if drain:
            self._signal(handle, signal.SIGTERM)
        else:
            self._signal(handle, signal.SIGKILL)

    def _finish_stop(self, handle, now):
        """The process is gone: either drop it (scale-down), retire it
        (budget exhausted), or schedule the respawn with backoff."""
        with handle._lock:
            scale_down = handle.scale_down
        if scale_down:
            with self._lock:
                if handle in self._handles:
                    self._handles.remove(handle)
            self._manifest_append({
                "type": "scale", "action": "down",
                "index": handle.index,
            })
            self._log("scale-down of replica {} complete".format(
                handle.url))
            return
        with handle._lock:
            window = handle.restart_times
            while window and now - window[0] > self._restart_window_s:
                window.popleft()
            if len(window) >= self._max_restarts:
                handle.state = "retired"
                retired = True
            else:
                window.append(now)
                handle.restarts += 1
                handle.state = "backoff"
                handle.spawn_at = now + self._restart_backoff_s * (
                    2 ** max(0, len(window) - 1))
                retired = False
            restarts = handle.restarts
            window_copy = list(window)
        with self._lock:
            if retired:
                self._retired += 1
            else:
                self._restarts_total += 1
        # CLOCK_MONOTONIC is system-wide: the recorded window stays
        # comparable in a successor supervisor, so an adopted replica
        # cannot dodge retirement across a supervisor restart
        if retired:
            self._manifest_append({
                "type": "retire", "index": handle.index,
                "restart_times": window_copy,
            })
        else:
            self._manifest_append({
                "type": "restart", "index": handle.index,
                "restarts": restarts, "restart_times": window_copy,
            })
        if retired:
            self._log(
                "replica {} exhausted its restart budget ({} in {}s) — "
                "retired; the fleet degrades, it does not flap".format(
                    handle.url, self._max_restarts,
                    self._restart_window_s))

    # -- crash durability (manifest mode) ----------------------------------

    def _manifest_append(self, record):
        """Record one fleet-state mutation (no-op without a manifest);
        the enqueue is lock-free, so healing never blocks on I/O."""
        if self._manifest is None:
            return
        self._manifest.append(record)
        with self._lock:
            self._manifest_records += 1
            self._records_since_checkpoint += 1

    def _checkpoint_manifest(self):
        if self._manifest is None:
            return
        self._manifest.checkpoint(self._manifest_state())
        with self._lock:
            self._records_since_checkpoint = 0

    def _handle_start_token(self, handle):
        """The recorded/observable start token for a handle's process:
        an adopted child carries its own, a spawned child's is read
        from /proc."""
        with handle._lock:
            proc = handle.proc
        if proc is None:
            return None
        token = getattr(proc, "start_token", None)
        if token is not None:
            return token
        return fleetmanifest.process_start_token(proc.pid)

    def _manifest_state(self):
        """The checkpoint snapshot: everything ``fold_manifest`` would
        reconstruct from the full record stream, captured live."""
        with self._lock:
            handles = list(self._handles)
            router_handles = list(self._router_handles)
            counters = {
                "replica_restarts": self._restarts_total,
                "scale_up_events": self._scale_ups,
                "scale_down_events": self._scale_downs,
                "retired_replicas": self._retired,
                "router_restarts": self._router_restarts,
                "router_takeovers": self._router_takeovers,
                "router_retired": self._router_retired,
                "adoptions": self._adoptions,
                "clean_handovers": self._clean_handovers,
                "stale_children_reaped": self._stale_reaped,
                "manifest_records": self._manifest_records,
            }
            next_index = self._next_index
        replicas = []
        for handle in handles:
            token = self._handle_start_token(handle)
            with handle._lock:
                replicas.append({
                    "index": handle.index,
                    "role": handle.role,
                    "port": handle.port,
                    "scope": handle.scope,
                    "pid": (handle.proc.pid
                            if handle.proc is not None else None),
                    "start_token": token,
                    "nonce": handle.nonce,
                    "argv_hash": self._argv_hash,
                    "restarts": handle.restarts,
                    "restart_times": list(handle.restart_times),
                    "retired": handle.state == "retired",
                })
        routers = []
        for handle in router_handles:
            token = self._handle_start_token(handle)
            with handle._lock:
                routers.append({
                    "port": handle.port,
                    "role": handle.role,
                    "partition": handle.partition,
                    "pid": (handle.proc.pid
                            if handle.proc is not None else None),
                    "start_token": token,
                    "nonce": handle.nonce,
                    "restarts": handle.restarts,
                    "restart_times": list(handle.restart_times),
                    "retired": handle.state == "retired",
                })
        return {
            "counters": counters,
            "next_index": next_index,
            "router_journal": self._router_journal,
            "journal_owned": self._journal_tmp is not None,
            "replicas": replicas,
            "routers": routers,
        }

    def _stamp_heartbeat(self):
        """Externally observable supervisor liveness + adoption
        counters (tmp + atomic replace; an unwritable path degrades
        observability, never supervision)."""
        if self._heartbeat_file is None:
            return
        with self._lock:
            self._heartbeat_seq += 1
            beat = {
                "seq": self._heartbeat_seq,
                "monotonic": time.monotonic(),
                "pid": os.getpid(),
                "adoptions": self._adoptions,
                "clean_handovers": self._clean_handovers,
                "stale_children_reaped": self._stale_reaped,
                "replica_restarts": self._restarts_total,
            }
            handles = list(self._handles)
            router_handles = list(self._router_handles)
        beat["replicas"] = [
            {"index": r["index"], "pid": r["pid"], "url": r["url"],
             "state": r["state"], "restarts": r["restarts"]}
            for r in (h.stats() for h in handles)]
        beat["routers"] = [
            {"role": r["role"], "pid": r["pid"], "url": r["url"],
             "state": r["state"], "restarts": r["restarts"]}
            for r in (h.stats() for h in router_handles)]
        tmp = self._heartbeat_file + ".tmp"
        try:
            with open(tmp, "w") as fh:
                json.dump(beat, fh)
            os.replace(tmp, self._heartbeat_file)
        except OSError:
            pass

    def _close_manifest(self, checkpoint=True):
        if self._manifest is not None:
            if checkpoint:
                self._checkpoint_manifest()
            self._manifest.flush()
            self._manifest.close()
            self._manifest = None
        if self._manifest_lock_fd is not None:
            fleetmanifest.release_manifest_lock(self._manifest_lock_fd)
            self._manifest_lock_fd = None

    def _try_adopt_replica(self, handle, row):
        """Claim a predecessor's live child when all three identities
        agree (pid start token, spawn nonce echo, argv template); a
        live-but-stale child is reaped drain-first, a dead one just
        reports unadoptable — the caller charges the restart budget
        either way."""
        pid = row.get("pid")
        token = row.get("start_token")
        if not pid or token is None or not row.get("nonce"):
            self._log("replica {}: manifest row incomplete — "
                      "respawning".format(handle.url))
            return False
        if fleetmanifest.process_start_token(pid) != token:
            self._log("replica {}: recorded pid {} is gone — "
                      "respawning".format(handle.url, pid))
            return False
        proc = fleetmanifest.AdoptedProcess(pid, token)
        if row.get("argv_hash") != self._argv_hash:
            self._reap_stale(handle, proc,
                             "argv template changed", drain=True)
            return False
        snap = _fetch_health(handle.host, handle.port,
                             self._probe_timeout_s)
        if snap is None:
            self._reap_stale(handle, proc, "unreachable", drain=True)
            return False
        if snap.get("spawn_nonce") != row["nonce"]:
            self._reap_stale(handle, proc,
                             "spawn nonce mismatch", drain=True)
            return False
        now = time.monotonic()
        with handle._lock:
            handle.proc = proc
            handle.state = "up" if snap.get("ready") else "starting"
            handle.started_at = now
            handle.probe_failures = 0
            handle.nonce = row["nonce"]
            handle.in_router = True
        with self._lock:
            self._adoptions += 1
        self._log("adopted replica {} (pid {}, {} restart(s) on the "
                  "books)".format(handle.url, pid, handle.restarts))
        return True

    def _try_adopt_router(self, handle, row):
        """Router twin of :meth:`_try_adopt_replica`.  A stale router
        goes down HARD (SIGKILL): it may still hold the journal
        writer, and the respawn opening its own would interleave two
        writers in one directory."""
        pid = row.get("pid")
        token = row.get("start_token")
        if not pid or token is None or not row.get("nonce"):
            return False
        if fleetmanifest.process_start_token(pid) != token:
            self._log("router {}: recorded pid {} is gone — "
                      "respawning".format(handle.url, pid))
            return False
        proc = fleetmanifest.AdoptedProcess(pid, token)
        snap = _fetch_health(handle.host, handle.port,
                             self._probe_timeout_s)
        if snap is None or snap.get("spawn_nonce") != row["nonce"]:
            self._reap_stale(
                handle, proc,
                "unreachable" if snap is None else "spawn nonce "
                "mismatch", drain=False)
            return False
        now = time.monotonic()
        with handle._lock:
            handle.proc = proc
            handle.state = "up"
            handle.started_at = now
            handle.probe_failures = 0
            handle.nonce = row["nonce"]
        with self._lock:
            self._adoptions += 1
        if self._active_routers > 1:
            # epoch floor: the live router may hold a higher epoch
            # than 1 + takeovers (came-up broadcasts bump it too);
            # adopting its value keeps our next broadcast adoptable
            conn = http.client.HTTPConnection(
                handle.host, handle.port, timeout=self._probe_timeout_s)
            try:
                conn.request("GET", "/router/stats")
                resp = conn.getresponse()
                if resp.status == 200:
                    got = json.loads(resp.read())
                    with self._lock:
                        self._partition_epoch = max(
                            self._partition_epoch,
                            int(got.get("epoch") or 0))
            except (OSError, ValueError, http.client.HTTPException):
                pass
            finally:
                conn.close()
        self._log("adopted {} router {} (pid {})".format(
            handle.role, handle.url, pid))
        return True

    def _reap_stale(self, handle, proc, reason, drain):
        """A live process squats an adoptable slot but fails the
        identity contract: stop it (drain-first for replicas, hard for
        routers) before the slot respawns on its port."""
        self._log("reaping stale child on {} ({})".format(
            handle.url, reason))
        with handle._lock:
            handle.proc = proc
        self._signal(handle,
                     signal.SIGTERM if drain else signal.SIGKILL)
        self._reap(handle, self._drain_grace_s if drain else 5.0)
        with handle._lock:
            handle.proc = None
        with self._lock:
            self._stale_reaped += 1

    # -- the monitor -------------------------------------------------------

    def _monitor_loop(self):
        while not self._stop.wait(self._probe_interval_s):
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the supervisor
                # must outlive any single bad tick (a dying monitor
                # would silently end all healing)
                self._log("monitor tick failed: {}".format(e))

    def _tick(self):
        now = time.monotonic()
        self._stamp_heartbeat()
        if self._manifest is not None:
            with self._lock:
                due = (self._records_since_checkpoint
                       >= self._CHECKPOINT_EVERY)
            if due:
                self._checkpoint_manifest()
        self._tick_routers(now)
        utils = []
        for handle in self._handles_snapshot():
            with handle._lock:
                state = handle.state
                proc = handle.proc
                stop_deadline = handle.stop_deadline
                spawn_at = handle.spawn_at
                started_at = handle.started_at
            if state == "retired":
                continue
            exited = proc is None or proc.poll() is not None
            if state == "stopping":
                if exited:
                    self._finish_stop(handle, now)
                elif now >= stop_deadline:
                    self._signal(handle, signal.SIGKILL)
                continue
            if state == "backoff":
                if now >= spawn_at:
                    self._spawn(handle)
                continue
            if exited:
                # unplanned death (SIGKILL, crash, OOM): there is
                # nothing left to drain — restart immediately
                self._leave_router(handle)
                self._finish_stop(handle, now)
                continue
            snap = _fetch_health(handle.host, handle.port,
                                 self._probe_timeout_s)
            if snap is None:
                with handle._lock:
                    handle.probe_failures += 1
                    failures = handle.probe_failures
                if state == "starting":
                    if now - started_at > self._start_timeout_s:
                        self._begin_restart(
                            handle, "never became ready", drain=False)
                elif failures >= self._unhealthy_after:
                    # alive but not answering: a wedge — drain what can
                    # still drain, then replace the process
                    self._begin_restart(handle, "wedged", drain=True)
                continue
            with handle._lock:
                handle.probe_failures = 0
                handle.last_util = _snapshot_utilization(snap)
                utils.append((handle, handle.last_util))
            if _snapshot_tripped(snap):
                self._begin_restart(
                    handle, "scheduler tripped", drain=True)
                continue
            if snap.get("ready"):
                if state == "starting":
                    with handle._lock:
                        handle.state = "up"
                    self._join_router(handle)
                    self._log("replica {} is up".format(handle.url))
                    # boot is not a utilization signal; let the
                    # cooldown absorb the membership change
                    self._cooldown_until = max(
                        self._cooldown_until,
                        now + self._scale_cooldown_s)
            elif (state == "starting"
                    and now - started_at > self._start_timeout_s):
                # answers probes but never reports ready: the start
                # failed just as surely as a dead socket — without
                # this branch such a replica would sit in 'starting'
                # forever (probes succeed, so neither the timeout-on-
                # unreachable nor the wedge path can fire).  The
                # process is alive: drain what can drain.
                self._begin_restart(
                    handle, "never became ready", drain=True)
        self._evaluate_scaling(
            [(h, u) for h, u in utils if h.stats()["state"] == "up"],
            now)

    # -- elastic scaling ---------------------------------------------------

    def _evaluate_scaling(self, pairs, now):
        """Role-aware elastic scaling: each phase pool (``prefill`` /
        ``decode`` / fused ``None``) accumulates its own hysteresis
        streaks from its own members' utilization and scales between
        ``min_replicas``/``max_replicas`` (interpreted per pool)
        independently — a prompt-heavy workload grows the prefill pool
        without adding idle decode capacity, and vice versa.  Streak
        accounting always runs; at most one scaling ACTION fires per
        tick, and the global cooldown + settling gates cover every
        pool (a booting prefill spawn also defers decode actions — the
        fleet mean is in flux either way)."""
        if not pairs:
            return
        by_role = {}
        for handle, util in pairs:
            by_role.setdefault(handle.role, []).append(util)
        ready = []
        for role in sorted(by_role,
                           key=lambda r: (r is not None, r or "")):
            utils = by_role[role]
            pool_util = sum(utils) / len(utils)
            up = self._role_up_streaks.get(role, 0)
            down = self._role_down_streaks.get(role, 0)
            if pool_util >= self._scale_high:
                up += 1
                down = 0
            elif pool_util <= self._scale_low:
                down += 1
                up = 0
            else:
                # the hysteresis band: a noisy middle window resets
                # both streaks — scaling only fires on SUSTAINED signal
                up = 0
                down = 0
            self._role_up_streaks[role] = up
            self._role_down_streaks[role] = down
            ready.append((role, pool_util, up, down))
        if now < self._cooldown_until:
            return
        states = [h.stats()["state"] for h in self._handles_snapshot()]
        if any(s in ("starting", "backoff", "stopping") for s in states):
            # the fleet is still SETTLING from a previous action (a
            # spawn booting, a drain in flight, a respawn pending):
            # the utilization mean does not yet reflect that decision,
            # so acting again would double-fire — e.g. a scale-up's
            # replica boots slower than the streak re-accumulates
            return
        for role, pool_util, up, down in ready:
            pool = [h for h in self._handles_snapshot()
                    if h.role == role and h.stats()["state"] != "retired"]
            label = role or "fused"
            if (up >= self._scale_up_windows
                    and (self._max_replicas is None
                         or len(pool) < self._max_replicas)):
                self._role_up_streaks[role] = 0
                self._cooldown_until = now + self._scale_cooldown_s
                with self._lock:
                    self._scale_ups += 1
                handle = self._register_handle(role=role)
                self._manifest_append({
                    "type": "scale", "action": "up",
                    "index": handle.index,
                })
                self._log(
                    "scale-up: {} pool utilization {:.2f} sustained — "
                    "spawning replica {}".format(
                        label, pool_util, handle.url))
                self._spawn(handle)
                return
            if (down >= self._scale_down_windows
                    and len(pool) > self._min_replicas):
                self._role_down_streaks[role] = 0
                self._cooldown_until = now + self._scale_cooldown_s
                ups = [h for h in pool if h.stats()["state"] == "up"]
                if not ups:
                    continue
                # drain the least-loaded, youngest replica of the pool
                victim = min(
                    ups,
                    key=lambda h: (h.stats()["utilization"], -h.index))
                with self._lock:
                    self._scale_downs += 1
                with victim._lock:
                    victim.scale_down = True
                self._log(
                    "scale-down: {} pool utilization {:.2f} sustained "
                    "— draining replica {}".format(
                        label, pool_util, victim.url))
                self._begin_restart(victim, "scale-down", drain=True)
                return

    # -- observability -----------------------------------------------------

    def stats(self):
        """Counters + per-replica state; the flat counter names are
        what ``/router/stats`` (and with it the perf tooling's
        ``router_snapshot`` window diffs) carry."""
        with self._lock:
            out = {
                "replica_restarts": self._restarts_total,
                "scale_up_events": self._scale_ups,
                "scale_down_events": self._scale_downs,
                "retired_replicas": self._retired,
                "min_replicas": self._min_replicas,
                "max_replicas": self._max_replicas,
                "adoptions": self._adoptions,
                "clean_handovers": self._clean_handovers,
                "stale_children_reaped": self._stale_reaped,
                "manifest_records": self._manifest_records,
            }
            handles = list(self._handles)
            router_handles = list(self._router_handles)
            router_restarts = self._router_restarts
            router_takeovers = self._router_takeovers
            router_retired = self._router_retired
        out["replicas"] = [h.stats() for h in handles]
        out["up"] = sum(1 for r in out["replicas"] if r["state"] == "up")
        if self._role_mode:
            # phase-pool occupancy: up-replica counts per role (what
            # tests/test_disagg.py asserts on after role-aware scaling
            # and healing)
            phase_up = {}
            for row in out["replicas"]:
                if row["state"] == "up":
                    key = row["role"] or "fused"
                    phase_up[key] = phase_up.get(key, 0) + 1
            out["phase_replicas_up"] = phase_up
        if router_handles:
            # the supervised front tier (router_command mode)
            out["router_restarts"] = router_restarts
            out["router_takeovers"] = router_takeovers
            out["router_retired"] = router_retired
            out["routers"] = [h.stats() for h in router_handles]
            if self._active_routers > 1:
                out["partition_map"] = self._partition_map_snapshot()
                with self._lock:
                    out["partition_epoch"] = self._partition_epoch
        return out
