"""Continuous-batching decode scheduler: interleaved served generation.

The round-5 verdict's own decomposition puts the remaining decode-MBU
lever at *batching across rows*: a single-stream decode step streams the
whole weight set from HBM to produce ONE token, so served throughput
equals single-stream throughput while every concurrent gRPC stream
queues on the model's lock.  This module is the missing subsystem: a
per-model background decode loop that owns a slotted, padded KV cache
(``[n_layers, 2, max_slots, max_seq, n_kv_heads, head_dim]``, kv-head
sharded over the tp mesh when present) and runs **one batched decode
step for all active slots per iteration**, so the weight stream is paid
once per step and amortized over every in-flight generation.

Lifecycle of a request (vLLM-style continuous batching, TPU-shaped):

1. **admit** — between decode steps, a waiting request takes a free
   slot: its prompt prefills into a single-row cache (one batched
   MXU-shaped pass) whose rows are then written into the slot
   (``llama.scheduler_admit``).  A resumed request (``kv_cache_region``
   park/resume) instead copies its parked cache into the slot and
   replays its new prompt tokens through the batched step as *forced*
   tokens (fed, not emitted).
2. **step** — every iteration runs ``llama.scheduler_step``: greedy
   sample per slot from the slot's logits row, then one batched decode
   dispatch writing each row's K/V at its own position with per-row
   length masks.  Steps are software-pipelined one deep: step *i+1* is
   dispatched before step *i*'s tokens are fetched, so the device→host
   fetch overlaps the next step's compute.
3. **retire** — a slot finishes on its max_tokens budget or its
   ``eos_id``; the slot frees immediately, so a waiting request joins
   **mid-flight** while other slots keep decoding.  A finishing request
   that asked for cache parking gets its slot rows extracted
   (``llama.scheduler_extract`` — the same ``[L, 2, 1, S, Hkv, hd]``
   shape the single-stream path parks) and handed to its ``on_finish``
   callback.

Because of the one-deep pipeline, retirement lags its trigger token by
one step: the slot rides one extra "wasted" dispatch whose token is
discarded.  Correctness is preserved by construction — the wasted write
lands beyond the slot's valid prefix (masked on any later resume), rows
with no live request carry the out-of-bounds sentinel position so their
writes drop, and emission matches snapshot state by object identity so
a re-admitted slot can never receive a predecessor's stale token.

Greedy per-row math in the batched step is identical to the
single-stream ``decode_step``'s, so N interleaved streams produce
token-identical output to N sequential single-stream runs
(test-enforced in tests/test_continuous_batching.py).
"""

import threading
import time
from collections import deque

import numpy as np

from tpuserver import faults


class SchedulerClosed(Exception):
    """Raised on submit after the scheduler has been shut down (or while
    it is draining), and into streams the shutdown failed."""


class AdmissionQueueFull(RuntimeError):
    """Raised on submit when the pending queue is at capacity — the
    scheduler-level overload signal (RuntimeError subclass for backward
    compatibility; frontends map it to HTTP 429 / RESOURCE_EXHAUSTED)."""


class DeadlineExceeded(Exception):
    """Raised into a stream whose per-request deadline expired — either
    while waiting for admission (before prefill) or mid-generation (the
    slot retires and frees immediately)."""


class _Stream:
    """One in-flight generation bound to a cache slot."""

    __slots__ = (
        "prompt", "max_tokens", "eos_id", "queue", "forced", "pos",
        "emitted", "on_finish", "resume_cache", "resume_pos", "finished",
        "cancelled", "deadline",
    )

    def __init__(self, prompt, max_tokens, eos_id, resume_cache,
                 resume_pos, on_finish, deadline=None):
        import queue as _queue

        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.queue = _queue.Queue()
        self.forced = deque()
        self.pos = 0
        self.emitted = 0
        self.on_finish = on_finish
        self.resume_cache = resume_cache
        self.resume_pos = resume_pos
        self.finished = False   # terminal queue event delivered
        self.cancelled = False  # consumer abandoned the token iterator
        self.deadline = deadline  # time.monotonic() bound, or None

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


class DecodeScheduler:
    """The per-model continuous-batching loop.

    ``fns`` is the compiled bundle from ``llama.make_scheduler_fns`` and
    ``params`` the (possibly sharded/quantized) weight pytree.  One
    background thread owns ALL device state — the slotted cache and the
    per-slot logits are threaded (and donated) through its dispatches,
    so frontend threads never touch the device: they block on per-stream
    queues that the loop fans tokens into.
    """

    def __init__(self, fns, params, max_slots, max_seq, max_pending=None,
                 fault_scope=None):
        if max_slots < 1:
            raise ValueError(
                "max_slots must be >= 1 (got {})".format(max_slots)
            )
        # replica identity at the shared fault-injection points, so a
        # multi-server chaos harness can fail ONE scheduler's decode
        # loop while its pool siblings keep serving
        self.fault_scope = fault_scope
        self._fns = fns
        self._params = params
        self._max_slots = max_slots
        self._max_seq = max_seq
        # admission backpressure: before continuous batching, decoupled
        # requests serialized (implicit backpressure); an unbounded
        # pending deque would let one client enqueue arbitrarily many
        # generations (each also holding a frontend thread)
        self._max_pending = (
            max_pending if max_pending is not None else max(32, 8 * max_slots)
        )
        self._cond = threading.Condition()
        self._pending = deque()
        self._thread = None
        self._closed = False
        self._draining = False
        self._tripped = False  # decode loop died unexpectedly (watchdog)
        # every live (not yet terminally-delivered) stream, pending or
        # slotted: close() fails exactly this set when the loop cannot
        # (join timeout), and drain() waits on it emptying
        self._streams = set()

    # -- frontend side -----------------------------------------------------

    def submit(self, prompt, max_tokens, eos_id=None, resume_cache=None,
               resume_pos=0, on_finish=None, deadline=None):
        """Enqueue one generation; returns an iterator of
        ``(token, logprob)`` pairs that blocks as the decode loop
        produces them.

        ``resume_cache``/``resume_pos`` continue from a parked KV cache
        (the prompt replays through the batched step without emission);
        ``on_finish(cache_rows)`` receives the slot's final cache copy —
        the park hook.  ``deadline`` is a ``time.monotonic()`` bound:
        past it, a still-pending request fails before prefill and an
        in-flight one retires mid-generation, both with
        :class:`DeadlineExceeded`."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("PROMPT_IDS must be non-empty")
        start = resume_pos if resume_cache is not None else 0
        if start + len(prompt) + max_tokens > self._max_seq:
            raise ValueError(
                "position ({}) + prompt ({}) + max_tokens ({}) exceeds max "
                "sequence {}".format(
                    start, len(prompt), max_tokens, self._max_seq
                )
            )
        stream = _Stream(prompt, int(max_tokens), eos_id,
                         resume_cache, int(resume_pos), on_finish,
                         deadline=deadline)
        with self._cond:
            if self._closed:
                raise SchedulerClosed("scheduler is shut down")
            if self._draining:
                raise SchedulerClosed(
                    "scheduler is draining; not accepting new generations"
                )
            if len(self._pending) >= self._max_pending:
                raise AdmissionQueueFull(
                    "scheduler admission queue is full ({} waiting "
                    "generations); retry later".format(len(self._pending))
                )
            self._pending.append(stream)
            self._streams.add(stream)
            if self._thread is None or not self._thread.is_alive():
                self._tripped = False  # fresh loop, fresh device state
                self._thread = threading.Thread(
                    target=self._run, name="decode-scheduler", daemon=True
                )
                self._thread.start()
            self._cond.notify_all()
        return self._drain(stream)

    @staticmethod
    def _drain(stream):
        try:
            while True:
                kind, a, b = stream.queue.get()
                if kind == "tok":
                    yield a, b
                elif kind == "err":
                    stream.finished = True
                    raise a
                else:  # "done"
                    stream.finished = True
                    return
        finally:
            if not stream.finished:
                # consumer gone mid-generation (client cancel/disconnect
                # closes the generator): flag the stream so the decode
                # loop retires its slot instead of burning batched steps
                # on tokens nobody will read
                stream.cancelled = True

    def close(self, join_timeout=30):
        """Stop the loop; pending and in-flight requests error out.
        Subsequent submits raise SchedulerClosed.

        Deterministic even when the loop thread is wedged (e.g. inside a
        stuck device dispatch): if the join times out, every stream the
        loop did not terminally deliver gets a SchedulerClosed error
        here, so no consumer is left blocked on its queue forever."""
        with self._cond:
            already_closed = self._closed
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None and not already_closed:
            # join once: a second close() (e.g. core.drain's final
            # close after the scheduler already drained) must not spend
            # another join_timeout re-waiting on a wedged thread —
            # the deterministic leftover-fail below still runs
            thread.join(timeout=join_timeout)
        # the loop normally fails every live stream on its way out; after
        # a join timeout (or a loop that never started) do it ourselves
        with self._cond:
            leftover = list(self._streams)
            self._streams.clear()
            self._pending.clear()
            self._cond.notify_all()
        err = SchedulerClosed("scheduler is shut down")
        for stream in leftover:
            stream.queue.put(("err", err, None))

    def drain(self, timeout=30.0):
        """Graceful drain: stop admission immediately, let pending and
        in-flight generations finish within ``timeout`` seconds, then
        close — deterministically failing whatever remains.  Submits
        during and after the drain raise SchedulerClosed."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._streams:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        self.close(join_timeout=max(0.1, deadline - time.monotonic()))

    @property
    def healthy(self):
        """False after the decode loop died unexpectedly (watchdog
        tripped) or the scheduler was closed — readiness probes report
        this through ``ServerReady``/``ModelReady``."""
        return not self._tripped and not self._closed

    def stats(self):
        """Introspection for tests and ops: live stream / pending counts
        and lifecycle flags.  ``live_streams`` counting to zero after
        traffic is the no-leaked-slots invariant chaos tests assert."""
        with self._cond:
            return {
                "live_streams": len(self._streams),
                "pending": len(self._pending),
                "draining": self._draining,
                "closed": self._closed,
                "healthy": self.healthy,
            }

    # -- decode loop -------------------------------------------------------

    def _fail(self, stream, exc):
        self._deliver(stream, ("err", exc, None))

    def _deliver(self, stream, event):
        """Deliver a terminal event and retire the stream from the live
        registry (never call while holding ``_cond`` — it takes it)."""
        with self._cond:
            self._streams.discard(stream)
            self._cond.notify_all()
        stream.queue.put(event)

    def _run(self):
        slots = [None] * self._max_slots  # slot -> _Stream | None
        try:
            self._loop(slots)
        except Exception as e:  # noqa: BLE001 — the loop must not die
            # silently: an unexpected failure (e.g. OOM inside the
            # step-recovery path) would otherwise leave every consumer
            # blocked forever on its queue
            with self._cond:
                self._tripped = True  # watchdog: readiness reports it
                if self._thread is threading.current_thread():
                    # unregister NOW, under the lock: a submit racing
                    # this cleanup must see no live thread and start a
                    # fresh loop, not enqueue into a dying one whose
                    # pending snapshot below would never include it
                    self._thread = None
                pending = list(self._pending)
                self._pending.clear()
            for stream in slots:
                if stream is not None:
                    self._fail(stream, e)
            for stream in pending:
                self._fail(stream, e)

    def _loop(self, slots):
        fns = self._fns
        cache = fns["init_cache"]()
        logits = fns["init_logits"]()
        inflight = None  # (tokens_dev, logps_dev, snapshot)

        def finish(stream, slot):
            if stream.on_finish is not None:
                try:
                    stream.on_finish(fns["extract"](cache, slot))
                except Exception as e:  # noqa: BLE001 — park is per-stream
                    self._fail(stream, e)
                    slots[slot] = None
                    return
            self._deliver(stream, ("done", None, None))
            slots[slot] = None

        while True:
            expired = []
            with self._cond:
                while (
                    not self._closed
                    and not self._draining
                    and not self._pending
                    and inflight is None
                    and not any(s is not None for s in slots)
                ):
                    self._cond.wait()
                if self._closed:
                    pending = list(self._pending)
                    self._pending.clear()
                    break
                if (
                    self._draining
                    and not self._pending
                    and inflight is None
                    and not any(s is not None for s in slots)
                ):
                    # drain complete: every accepted generation finished;
                    # exit cleanly so drain() sees a closed scheduler
                    self._closed = True
                    pending = []
                    break
                # reap cancelled streams first: their consumers are gone,
                # so the slot frees for waiting work (no park — the
                # single-stream path abandoned mid-generation doesn't
                # park either)
                for i, st in enumerate(slots):
                    if st is not None and st.cancelled:
                        self._streams.discard(st)
                        slots[i] = None
                # deadline sweep: a pending request past its deadline
                # fails BEFORE prefill (no slot or compute is ever spent
                # on it); an in-flight one retires mid-generation, its
                # slot freeing for waiting work this same iteration
                now = time.monotonic()
                if self._pending:
                    keep = deque()
                    for st in self._pending:
                        (expired if st.expired(now) else keep).append(st)
                    self._pending = keep
                for i, st in enumerate(slots):
                    if st is not None and st.expired(now):
                        expired.append(st)
                        slots[i] = None
                self._cond.notify_all()
                admissions = []
                free = [i for i, s in enumerate(slots) if s is None]
                while self._pending and free:
                    st = self._pending.popleft()
                    if st.cancelled:
                        self._streams.discard(st)
                        continue  # abandoned while still queued
                    admissions.append((free.pop(0), st))
            # deadline failures deliver OUTSIDE the lock (delivery
            # re-takes it to retire the stream from the live registry)
            for st in expired:
                self._fail(st, DeadlineExceeded(
                    "request deadline exceeded after {} emitted "
                    "tokens".format(st.emitted)))
            # device work runs OUTSIDE the lock: submitters must be able
            # to enqueue while the chip computes
            for slot, stream in admissions:
                try:
                    cache, logits = self._admit(cache, logits, slot, stream)
                except Exception as e:  # noqa: BLE001 — per-request fault
                    self._fail(stream, e)
                    continue
                slots[slot] = stream

            current = None
            active_ids = [i for i, s in enumerate(slots) if s is not None]
            if active_ids:
                # sentinel position max_seq on inert rows: their cache
                # writes drop instead of corrupting a parked slot
                positions = np.full(
                    (self._max_slots,), self._max_seq, np.int32)
                active = np.zeros((self._max_slots,), bool)
                forced_tok = np.zeros((self._max_slots,), np.int32)
                forced_mask = np.zeros((self._max_slots,), bool)
                snapshot = []
                for i in active_ids:
                    st = slots[i]
                    positions[i] = st.pos
                    active[i] = True
                    was_forced = bool(st.forced)
                    if was_forced:
                        forced_tok[i] = st.forced.popleft()
                        forced_mask[i] = True
                    snapshot.append((i, st, was_forced))
                    st.pos += 1
                try:
                    # chaos hook: "scheduler.step" raise = decode-step
                    # failure (exercises the donated-cache recovery
                    # below), sleep = slow step
                    faults.fire("scheduler.step", self.fault_scope)
                    tokens_dev, logps_dev, logits, cache = fns["step"](
                        self._params, cache, logits, positions, active,
                        forced_tok, forced_mask,
                    )
                    current = (tokens_dev, logps_dev, snapshot)
                except Exception as e:  # noqa: BLE001
                    # a failed dispatch may have consumed the donated
                    # cache/logits: fail every live stream and reset
                    for i, st, _ in snapshot:
                        self._fail(st, e)
                        slots[i] = None
                    if inflight is not None:
                        for i, st, _ in inflight[2]:
                            if slots[i] is st:
                                self._fail(st, e)
                                slots[i] = None
                    inflight = None
                    cache = fns["init_cache"]()
                    logits = fns["init_logits"]()
                    continue

            if inflight is not None:
                tokens_dev, logps_dev, snapshot = inflight
                try:
                    # host-transfer chaos
                    faults.fire("scheduler.fetch", self.fault_scope)
                    toks = np.asarray(tokens_dev)
                    lps = np.asarray(logps_dev)
                except Exception as e:  # noqa: BLE001
                    for i, st, _ in snapshot:
                        if slots[i] is st:
                            self._fail(st, e)
                            slots[i] = None
                    inflight = current
                    continue
                for i, st, was_forced in snapshot:
                    if slots[i] is not st:
                        # slot retired (and possibly re-admitted) after
                        # this step was dispatched: its token is the
                        # one-deep pipeline's wasted extra — discard
                        continue
                    if st.cancelled:
                        # consumer gone: free the slot AND retire the
                        # stream from the live registry — every other
                        # retire site discards too; missing it here
                        # left stats()['live_streams'] nonzero and made
                        # drain() wait out its full timeout
                        self._streams.discard(st)
                        slots[i] = None
                        continue
                    if was_forced:
                        continue  # resumed-prompt feed, nothing to emit
                    tok = int(toks[i])
                    if st.emitted < st.max_tokens:
                        st.queue.put(("tok", tok, float(lps[i])))
                        st.emitted += 1
                    if st.emitted >= st.max_tokens or (
                        st.eos_id is not None and tok == st.eos_id
                    ):
                        finish(st, i)
            inflight = current

        # closed: fail whatever is still queued or running
        err = SchedulerClosed("scheduler is shut down")
        if inflight is not None:
            for i, st, _ in inflight[2]:
                if slots[i] is st:
                    slots[i] = None
                    self._fail(st, err)
        for st in slots:
            if st is not None:
                self._fail(st, err)
        for st in pending:
            self._fail(st, err)

    def _admit(self, cache, logits, slot, stream):
        """Prefill-on-admit (or parked-cache restore) into ``slot``."""
        import jax.numpy as jnp

        # admission-failure chaos hook
        faults.fire("scheduler.admit", self.fault_scope)
        fns = self._fns
        if stream.resume_cache is not None:
            # resumed generation: the parked rows become the slot's
            # cache and the new prompt replays as forced tokens (the
            # single-stream resume path feeds them through decode the
            # same way).  The parked array itself is only READ — the
            # region's copy stays valid for the next resume.
            slot_cache = stream.resume_cache
            row = jnp.zeros((1, logits.shape[1]), logits.dtype)
            stream.forced.extend(int(t) for t in stream.prompt)
            stream.pos = stream.resume_pos
        else:
            # prompts pad to power-of-two buckets so admission compiles
            # a handful of prefill shapes, not one per length — a novel
            # length's full-model compile would stall EVERY in-flight
            # stream's token emission.  Causal attention keeps the
            # result exact (prefill_to_length); padding rows' garbage
            # K/V stay masked behind the slot's position.  The model
            # decides the bucket (exact length where padding would flip
            # its prefill kernel choice and with it the greedy tokens).
            true_len = len(stream.prompt)
            bucket = self._fns["prefill_bucket"](true_len)
            padded = np.zeros((bucket,), np.int32)
            padded[:true_len] = stream.prompt
            slot_cache = fns["init_slot_cache"]()
            row, slot_cache = fns["prefill"](
                self._params, slot_cache, jnp.asarray(padded)[None, :],
                true_len,
            )
            stream.pos = true_len
        cache, logits = fns["admit"](cache, logits, slot_cache, row, slot)
        return cache, logits
