"""Continuous-batching decode scheduler: interleaved served generation.

The round-5 verdict's own decomposition puts the remaining decode-MBU
lever at *batching across rows*: a single-stream decode step streams the
whole weight set from HBM to produce ONE token, so served throughput
equals single-stream throughput while every concurrent gRPC stream
queues on the model's lock.  This module is the missing subsystem: a
per-model background decode loop that owns a block-paged KV pool
(``[n_layers, 2, kv_pages, page_size, n_kv_heads, head_dim]``, kv-head
sharded over the tp mesh when present) and runs **one batched decode
step for all active slots per iteration**, so the weight stream is paid
once per step and amortized over every in-flight generation.  Each
generation's KV lives in fixed-size pages named by a per-slot page
table (``tpuserver.paging``): admission is bounded by *free pages*,
not slot count, shared prompt prefixes deduplicate into ref-counted
radix-cache pages (a shared-system-prompt admission prefills only its
unique suffix), and long prefills chunk into bounded steps interleaved
with decode — see docs/resilience.md "Paged KV cache & radix prefix
cache".

Lifecycle of a request (vLLM-style continuous batching, TPU-shaped):

1. **admit** — between decode steps, a waiting request reserves a free
   slot row and its whole page span, matches its prompt against the
   radix prefix cache (shared full pages restore via
   ``llama.paged_gather``; only the unique suffix prefills — in one
   bucketed pass, or chunk-by-chunk interleaved with decode when it
   exceeds ``prefill_chunk_tokens``), and scatters the prefilled
   single-row cache into its physical pages
   (``llama.paged_admit``).  A resumed request (``kv_cache_region``
   park/resume) instead scatters its parked cache into the reserved
   pages and replays its new prompt tokens through the batched step
   as *forced* tokens (fed, not emitted).
2. **step** — every iteration runs ``llama.paged_scheduler_step``:
   greedy sample per slot from the slot's logits row, then one batched
   decode dispatch following the per-slot page tables, writing each
   row's K/V at its own position with per-row length masks.  Steps are
   software-pipelined one deep: step *i+1* is dispatched before step
   *i*'s tokens are fetched, so the device→host fetch overlaps the
   next step's compute.
3. **retire** — a slot finishes on its max_tokens budget or its
   ``eos_id``; the slot (and its pages — full ones donate back to the
   radix cache) frees immediately, so a waiting request joins
   **mid-flight** while other slots keep decoding.  A finishing
   request that asked for cache parking gets its pages gathered
   (``llama.paged_gather`` — the same ``[L, 2, 1, S, Hkv, hd]`` shape
   the single-stream path parks) and handed to its ``on_finish``
   callback.

Because of the one-deep pipeline, retirement lags its trigger token by
one step: the slot rides one extra "wasted" dispatch whose token is
discarded.  Correctness is preserved by construction — the wasted write
lands beyond the slot's valid prefix (masked on any later resume), rows
with no live request carry the out-of-bounds sentinel position so their
writes drop, and emission matches snapshot state by object identity so
a re-admitted slot can never receive a predecessor's stale token.

Greedy per-row math in the batched step is identical to the
single-stream ``decode_step``'s, so N interleaved streams produce
token-identical output to N sequential single-stream runs
(test-enforced in tests/test_continuous_batching.py).

Self-healing (tests/test_self_healing.py, docs/resilience.md):

- **Per-slot quarantine.**  A slot whose own step output is poisoned
  (non-finite logprob — NaN logits from a poison request) retires with
  a typed :class:`SlotQuarantined` while every co-batched slot keeps
  decoding; greedy tokens of the survivors are byte-identical to a
  fault-free run (the batched step's math is row-independent).
- **Supervised restart.**  The decode thread runs under a supervisor:
  an unattributable step/fetch failure kills the loop, and the
  supervisor rebuilds device state and *re-admits* every live stream by
  re-prefilling ``prompt + tokens_emitted_so_far`` (greedy decode is
  deterministic, so the continuation is token-identical), under a
  bounded restart budget with exponential backoff.  A hung-step
  watchdog (``step_timeout_s``) treats a wedged device dispatch the
  same way, demoting the stuck thread via an epoch counter so a waking
  zombie can never double-deliver into re-admitted streams.  Budget
  exhausted ⇒ the scheduler trips permanently: unhealthy to readiness
  probes (pools rotate the replica out), every stream failed typed,
  new submits rejected, drain/close still deterministic.
- **Resumable generations.**  ``submit(generation_id=...)`` records
  every emitted ``(token, logprob)``; a disconnected (or completed)
  generation parks in a bounded, TTL'd replay buffer and
  :meth:`DecodeScheduler.resume` replays ``history[from_seq:]`` then
  splices live tokens from a re-admitted continuation — no duplicated
  or missing tokens.  Replay state is replica-local: resume is
  same-endpoint only.
"""

import math
import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from tpuserver import faults
from tpuserver.paging import PageAllocator, RadixPrefixCache, pages_for
from tpuserver.speculative import NgramDrafter

# The wire-mapped stream failures are the CANONICAL tpuserver.errors
# types (one definition site, tpulint R4-enforced): DeadlineExceeded
# (504) for an expired per-request bound — while waiting for admission
# or mid-generation; SlotQuarantined (422) for a stream whose OWN
# decode output went non-finite (only the offender retires, co-batched
# streams keep decoding); UnknownGeneration (404) for a resume id this
# replica does not hold.  Re-exported here so the historical
# ``from tpuserver.scheduler import SlotQuarantined`` keeps working.
from tpuserver.errors import (  # noqa: F401 — re-exported
    DeadlineExceeded,
    SlotQuarantined,
    UnknownGeneration,
)


class SchedulerClosed(Exception):
    """Raised on submit after the scheduler has been shut down (or while
    it is draining), and into streams the shutdown failed.  Scheduler-
    local (not a ServerError): the core maps it to ShuttingDown (503)."""


class AdmissionQueueFull(RuntimeError):
    """Raised on submit when the pending queue is at capacity (the
    hard ``max_pending`` backstop), when the KV page pool is
    exhausted, or when the adaptive sojourn-time controller sheds —
    the scheduler-level overload signal (RuntimeError subclass for
    backward compatibility; the core maps it to Overloaded — HTTP 429
    / RESOURCE_EXHAUSTED).  ``retry_after`` (seconds, or None for the
    frontend default) rides into the Overloaded's ``Retry-After``
    header: the adaptive controller computes it from its current
    control interval, so clients back off at the pace the queue is
    actually draining."""

    def __init__(self, msg, retry_after=None):
        super().__init__(msg)
        self.retry_after = retry_after


class _CodelShedController:
    """Sojourn-time admission shedding — the CoDel control law applied
    to the scheduler's pending queue (Nichols & Jacobson, "Controlling
    Queue Delay"), replacing the *fixed* ``max_pending`` cliff with an
    adaptive valve.

    A long queue is not the problem — a queue that STAYS long is.  The
    controller watches the admission queue's sojourn (the head
    stream's wait, i.e. exactly what ``tpu_scheduler_queue_wait_-
    seconds`` histograms at admission): once it has exceeded
    ``target_s`` continuously for a full ``interval_s``, the scheduler
    sheds the NEWEST arrival with the existing typed 429 and keeps
    shedding one arrival per control interval, tightening the interval
    as ``interval / sqrt(shed_count)`` while overload persists
    (standard CoDel acceleration) and relaxing the moment sojourn
    drops back under target.  ``Retry-After`` is the ceiling of the
    current control interval — the pace the queue is draining at.

    Plain state machine, no locking of its own: every method runs
    under the scheduler's ``_cond`` (submit holds it to shed; the
    decode loop holds it where it notes sojourn), and all time flows
    in as ``now`` so unit tests drive it clock-free.  With the
    controller off (``target_queue_ms=None``) the submit path is
    byte-identical to the pre-controller scheduler; ``max_pending``
    stays as the hard backstop either way."""

    __slots__ = ("target_s", "interval_s", "above_since", "shedding",
                 "shed_next", "shed_count")

    def __init__(self, target_s, interval_s):
        self.target_s = float(target_s)
        self.interval_s = float(interval_s)
        self.above_since = None  # first instant sojourn exceeded target
        self.shedding = False
        self.shed_next = 0.0     # next shed instant while shedding
        self.shed_count = 0      # sheds in the current overload episode

    def current_interval(self):
        return self.interval_s / math.sqrt(max(1, self.shed_count))

    def note_sojourn(self, sojourn_s, now):
        """One queue-delay observation (the head-of-queue wait: the
        FIFO maximum, so 'head under target' means the whole queue
        is).  Below target ⇒ relax completely; above ⇒ start (or keep)
        the overload clock."""
        if sojourn_s < self.target_s:
            self.above_since = None
            self.shedding = False
            self.shed_count = 0
        elif self.above_since is None:
            self.above_since = now

    def on_arrival(self, now, queue_len):
        """Shed verdict for one new submit: the ``Retry-After``
        seconds to shed with, or None to admit.  Never sheds an empty
        queue (nothing is waiting — sojourn is a stale signal), never
        sheds before the sojourn has been above target for one full
        interval, and while shedding drops one arrival per (shrinking)
        control interval rather than every arrival — the valve sheds
        at the rate that brings sojourn back to target, not to zero
        throughput."""
        if queue_len <= 0 or self.above_since is None:
            return None
        if now - self.above_since < self.interval_s:
            return None
        if not self.shedding:
            self.shedding = True
            self.shed_count = 1
        elif now >= self.shed_next:
            self.shed_count += 1
        else:
            return None
        interval = self.current_interval()
        self.shed_next = now + interval
        return max(1, int(math.ceil(interval)))


class _Stream:
    """One in-flight generation bound to a cache slot."""

    __slots__ = (
        "prompt", "max_tokens", "eos_id", "queue", "forced", "pos",
        "emitted", "on_finish", "resume_cache", "resume_pos", "finished",
        "cancelled", "deadline", "generation_id", "history", "incarnation",
        "enqueued_at",
        # paged-KV state, owned by the decode loop that admitted the
        # stream (reset for re-admission when a loop dies): the np
        # page-table row, the pinned radix path (table[:len(nodes)]
        # are tree pages, the rest up to span_pages are owned), and
        # the reserved span in pages
        "table", "radix_nodes", "span_pages",
        # zero-copy data plane (ISSUE 12): the device-resident prompt
        # view (an XLA-shm segment — cold prefills consume it without
        # host staging), the park-export opt-in, and the attach-resume
        # state a same-host resume scatters instead of re-prefilling
        "prompt_dev", "kv_export", "attach_cache", "attach_pos",
        # disaggregated prefill phase (ISSUE 16): export the KV on
        # FINISH (not just cancel-reap) and keep the export alive past
        # the completed park — a decode-role replica attaches it
        "kv_export_on_finish",
        # speculative decoding (ISSUE 19) per-stream throttle state,
        # owned by the decode loop: consecutive drafted tokens with
        # zero acceptance, and steps left to skip drafting (probe
        # cadence once throttled)
        "spec_miss", "spec_skip",
    )

    def __init__(self, prompt, max_tokens, eos_id, resume_cache,
                 resume_pos, on_finish, deadline=None, generation_id=None,
                 prompt_dev=None, kv_export=False,
                 kv_export_on_finish=False):
        import queue as _queue

        self.prompt = prompt
        self.max_tokens = max_tokens
        self.eos_id = eos_id
        self.queue = _queue.Queue()
        self.forced = deque()
        self.pos = 0
        self.emitted = 0
        self.on_finish = on_finish
        self.resume_cache = resume_cache
        self.resume_pos = resume_pos
        self.finished = False   # terminal queue event delivered
        self.cancelled = False  # consumer abandoned the token iterator
        self.deadline = deadline  # time.monotonic() bound, or None
        self.generation_id = generation_id  # resumable when set
        # every emitted (token, logprob): the replay buffer for
        # client resume AND the re-admission feed for supervised restart
        self.history = []
        # bumped on every admission: step snapshots record it, so a
        # pipelined step dispatched for a PREVIOUS admission of this
        # same stream (cancelled, parked, resumed, re-admitted into the
        # same slot) can never deliver its stale token
        self.incarnation = 0
        # monotonic stamp of the latest (re-)enqueue: the scheduler's
        # queue-wait histogram measures submit -> slot admission
        self.enqueued_at = time.monotonic()
        self.table = None        # np [pages_per_seq] page-table row
        self.radix_nodes = None  # pinned radix path (prefix pages)
        self.span_pages = 0      # reserved logical pages
        self.prompt_dev = prompt_dev  # device prompt view, or None
        self.kv_export = bool(kv_export)
        self.kv_export_on_finish = bool(kv_export_on_finish)
        self.attach_cache = None  # imported KV export (device array)
        self.attach_pos = 0       # its valid-prefix end position
        # speculative-decode throttle (loop-thread only): consecutive
        # drafted tokens with zero acceptance / steps left to skip
        # drafting once throttled (probe cadence)
        self.spec_miss = 0
        self.spec_skip = 0

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


class _HungStep(Exception):
    """Internal: the watchdog's synthesized loop-death cause."""


class _PrefillTask:
    """A chunked admission in progress.

    The stream's slot is reserved (it sits in ``slots`` un-``ready``)
    while its padded prompt prefills ``chunk`` tokens per loop
    iteration — so one 2k-token prompt costs each co-batched decode
    stream a chunk's latency per step, never a whole-prompt stall.
    ``dest`` is the page-scatter vector for the final admit and
    ``full`` the token prefix the radix tree indexes on completion."""

    __slots__ = ("stream", "slot", "slot_cache", "padded", "start",
                 "logits_at", "chunk", "dest", "full", "done", "total")

    def __init__(self, stream, slot, slot_cache, padded, start,
                 logits_at, chunk, dest, full):
        self.stream = stream
        self.slot = slot
        self.slot_cache = slot_cache
        self.padded = padded        # np [pad_len] suffix token ids
        self.start = start          # absolute position of padded[0]
        self.logits_at = logits_at  # pad-relative last-prompt-token
        self.chunk = chunk
        self.dest = dest            # np [pages_per_seq] scatter ids
        self.full = full            # np full token prefix (radix key)
        self.done = 0               # padded positions prefilled
        self.total = len(padded)


class DecodeScheduler:
    """The per-model continuous-batching loop.

    ``fns`` is the compiled bundle from ``llama.make_scheduler_fns`` and
    ``params`` the (possibly sharded/quantized) weight pytree.  One
    background thread owns ALL device state — the slotted cache and the
    per-slot logits are threaded (and donated) through its dispatches,
    so frontend threads never touch the device: they block on per-stream
    queues that the loop fans tokens into.

    A supervisor thread watches the loop: loop death (an unattributable
    step/fetch failure) restarts it with live streams re-admitted
    (``max_restarts`` per ``restart_window_s``, exponential backoff from
    ``restart_backoff_s``); a step stalled past ``step_timeout_s``
    (None = watchdog off; leave it off, or warm up first, where the
    first step's XLA compile could exceed it) is treated the same.
    Budget exhausted ⇒ permanent trip (unhealthy + typed failures).
    """

    def __init__(self, fns, params, max_slots, max_seq, max_pending=None,
                 fault_scope=None, step_timeout_s=None, max_restarts=5,
                 restart_window_s=60.0, restart_backoff_s=0.05,
                 replay_ttl_s=60.0, replay_capacity=256,
                 metrics=None, metric_labels=None,
                 prefill_chunk_tokens=256, prefix_cache=True,
                 kv_export=None, kv_import=None, kv_discard=None,
                 target_queue_ms=None, shed_interval_ms=100.0,
                 spec_tokens=None, spec_throttle_after=16,
                 spec_probe_interval=8):
        if max_slots < 1:
            raise ValueError(
                "max_slots must be >= 1 (got {})".format(max_slots)
            )
        # replica identity at the shared fault-injection points, so a
        # multi-server chaos harness can fail ONE scheduler's decode
        # loop while its pool siblings keep serving
        self.fault_scope = fault_scope
        self._fns = fns
        self._params = params
        self._max_slots = max_slots
        self._max_seq = max_seq
        # admission backpressure: before continuous batching, decoupled
        # requests serialized (implicit backpressure); an unbounded
        # pending deque would let one client enqueue arbitrarily many
        # generations (each also holding a frontend thread)
        self._max_pending = (
            max_pending if max_pending is not None else max(32, 8 * max_slots)
        )
        # adaptive queue shedding (docs/resilience.md "Tail-latency
        # defense"): None = controller off, submit path byte-identical
        # to the fixed-cliff scheduler.  When set, admissions shed
        # (typed 429 + Retry-After from the control interval) once the
        # queue's sojourn exceeds target_queue_ms for a sustained
        # shed_interval_ms — max_pending stays as the hard backstop.
        # State is written by submit and the decode loop, both under
        # _cond (the loop notes sojourn inside its already-held locked
        # region: zero new lock acquisitions).  # guarded-by: _cond
        self._shed_ctl = (
            _CodelShedController(float(target_queue_ms) / 1e3,
                                 float(shed_interval_ms) / 1e3)
            if target_queue_ms else None
        )
        self._codel_sheds = 0  # guarded-by: _cond
        self._step_timeout_s = step_timeout_s
        self._max_restarts = int(max_restarts)
        self._restart_window_s = float(restart_window_s)
        self._restart_backoff_s = float(restart_backoff_s)
        self._replay_ttl_s = float(replay_ttl_s)
        self._replay_capacity = int(replay_capacity)
        self._cond = threading.Condition()
        self._pending = deque()  # guarded-by: _cond
        self._thread = None      # guarded-by: _cond
        self._supervisor = None  # guarded-by: _cond
        self._closed = False     # guarded-by: _cond
        self._draining = False   # guarded-by: _cond
        # restart budget exhausted: permanent  # guarded-by: _cond
        self._tripped = False
        # epoch demotes superseded (wedged) loop threads: every delivery
        # into stream queues checks it under _cond, so a zombie waking
        # after a watchdog restart can never double-emit into a stream
        # the new loop re-admitted  # guarded-by: _cond
        self._epoch = 0
        # (epoch, monotonic start) of the current device op, or None —
        # epoch-tagged so a demoted zombie's stale stamps can neither
        # trip the watchdog against a healthy successor loop nor erase
        # the successor's own beat  # guarded-by: _cond
        self._heartbeat = None
        # set by a dying loop for the supervisor  # guarded-by: _cond
        self._loop_error = None
        self._restarts = 0       # lifetime count (stats/ops)  # guarded-by: _cond
        # timestamps inside the window  # guarded-by: _cond
        self._recent_restarts = deque()
        # lifetime SlotQuarantined count  # guarded-by: _cond
        self._quarantined = 0
        # generation_id -> (stream, completed, expires_monotonic):
        # the bounded, TTL'd replay buffer  # guarded-by: _cond
        self._replay = OrderedDict()
        # every live (not yet terminally-delivered) stream, pending or
        # slotted: close() fails exactly this set when the loop cannot
        # (join timeout), and drain() waits on it  # guarded-by: _cond
        self._streams = set()
        # cumulative observability counters (stats() + /metrics).
        # Written only by the decode loop / resume path with _cond
        # already held where it is held anyway — never a NEW lock
        # acquisition on the hot path (open item 3's regression
        # lesson); they only ever grow, so a racing stats() read can
        # lag one step but never see a decrease.
        self._admitted_total = 0
        self._tokens_total = 0
        self._replay_hits = 0
        # paged-KV knobs: prompts whose padded prefill exceeds
        # ``prefill_chunk_tokens`` prefill in chunks of that many
        # tokens, ONE chunk per loop iteration, so a long prompt never
        # stalls co-batched decode for its whole length (None disables
        # chunking); ``prefix_cache`` enables the radix tree that
        # deduplicates shared prompt prefixes into shared pages.  Both
        # engage only when the model's fns say chunked/span prefill is
        # kernel-choice-safe (``span_safe``) — the same determinism
        # guard prefill_bucket applies to padding.
        self._prefill_chunk_tokens = (
            int(prefill_chunk_tokens) if prefill_chunk_tokens else None
        )
        self._prefix_cache = bool(prefix_cache)
        # prefix-cache accounting in TOKENS (hits = prompt tokens
        # served from shared pages, misses = prompt tokens prefilled)
        # and EVICTIONS in pages.  Same discipline as the counters
        # above: loop-written, only ever grow, racy reads may lag one
        # step but never decrease.
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefix_evictions = 0
        # speculative decoding (ISSUE 19): draft up to ``spec_tokens``
        # candidate continuation tokens per slot per step from the
        # radix prefix cache (tpuserver.speculative.NgramDrafter) and
        # verify them all in ONE batched device step
        # (fns["spec_step"]).  0 keeps today's single-token path
        # byte-identical (the spec branch is never entered); None
        # defers to the TPUSERVER_SPEC_TOKENS environment variable so
        # an unmodified test corpus or fleet can be run with
        # speculation enabled wholesale (default 0).  Throttle knobs:
        # a stream that drafted ``spec_throttle_after`` consecutive
        # tokens with ZERO acceptance stops drafting and probes once
        # every ``spec_probe_interval`` steps until a draft lands.
        if spec_tokens is None:
            spec_tokens = int(os.environ.get("TPUSERVER_SPEC_TOKENS", "0"))
        self._spec_tokens = max(0, int(spec_tokens))
        if self._spec_tokens and "spec_step" not in (fns or {}):
            # bundle has no multi-token verify step (stub fns in
            # tests, older model builds): degrade to the plain path
            # rather than failing construction — speculation is an
            # optimization, never a capability requirement
            self._spec_tokens = 0
        self._spec_throttle_after = int(spec_throttle_after)
        self._spec_probe_interval = int(spec_probe_interval)
        # speculation accounting, same discipline as the counters
        # above: loop-written under _cond, grow-only, racy stats reads
        # may lag one step but never decrease.
        self._spec_steps = 0      # guarded-by: _cond
        self._spec_proposed = 0   # guarded-by: _cond
        self._spec_accepted = 0   # guarded-by: _cond
        self._spec_rollbacks = 0  # guarded-by: _cond
        # park-attach KV export hooks (tentpole 3 of ISSUE 12): a
        # disconnected resumable stream's gathered pages are handed to
        # ``kv_export(generation_id, cache, valid_pos)`` (the server
        # parks them in an XLA-shm region keyed by the id);
        # ``kv_import(generation_id)`` -> (cache, valid_pos) | None is
        # consulted on resume — hit means the re-admission SCATTERS the
        # parked pages and force-feeds one token instead of
        # re-prefilling prompt + history; ``kv_discard(generation_id)``
        # releases the export when its replay entry dies.  All three
        # optional: absent hooks keep the pre-export behavior exactly.
        self._kv_export = kv_export
        self._kv_import = kv_import
        self._kv_discard = kv_discard
        # (allocator, radix) of the CURRENT loop, for stats/gauges
        # (a restart rebuilds both with the device pool)
        self._pager = None  # guarded-by: _cond
        # optional tpuserver.metrics latency histograms: the decode
        # loop is their ONLY writer, so single_writer children observe
        # lock-free (exact, and never a lock acquisition in _loop)
        self._queue_hist = None
        self._step_hist = None
        if metrics is not None:
            labels = dict(metric_labels or {})
            names = tuple(sorted(labels))
            self._queue_hist = metrics.histogram(
                "tpu_scheduler_queue_wait_seconds", labelnames=names,
                single_writer=True,
            ).labels(**labels)
            self._step_hist = metrics.histogram(
                "tpu_scheduler_step_seconds", labelnames=names,
                single_writer=True,
            ).labels(**labels)

    # -- frontend side -----------------------------------------------------

    def submit(self, prompt, max_tokens, eos_id=None, resume_cache=None,
               resume_pos=0, on_finish=None, deadline=None,
               generation_id=None, prompt_dev=None, kv_export=False,
               kv_export_on_finish=False, attach_cache=None,
               attach_pos=0):
        """Enqueue one generation; returns an iterator of
        ``(token, logprob)`` pairs that blocks as the decode loop
        produces them.

        ``resume_cache``/``resume_pos`` continue from a parked KV cache
        (the prompt replays through the batched step without emission);
        ``on_finish(cache_rows)`` receives the slot's final cache copy —
        the park hook.  ``deadline`` is a ``time.monotonic()`` bound:
        past it, a still-pending request fails before prefill and an
        in-flight one retires mid-generation, both with
        :class:`DeadlineExceeded`.  ``generation_id`` makes the
        generation *resumable*: its tokens are retained in the replay
        buffer after disconnect or completion and
        :meth:`resume` continues it with no duplicated or missing
        tokens.

        Disaggregated-serving hooks (ISSUE 16): ``kv_export_on_finish``
        exports the KV through the ``kv_export`` hook when the
        generation FINISHES (the prefill-phase leg completes after one
        token) and keeps the export alive past the completed park so a
        decode-role replica can attach it; ``attach_cache`` /
        ``attach_pos`` admit over an imported KV export — the cache
        scatters into a fresh page span and only ``prompt[attach_pos
        - 1:]`` force-feeds, skipping the re-prefill entirely (the
        decode-phase leg).  An out-of-range ``attach_pos`` falls back
        to the ordinary prefill path, gracefully."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("PROMPT_IDS must be non-empty")
        start = resume_pos if resume_cache is not None else 0
        if start + len(prompt) + max_tokens > self._max_seq:
            raise ValueError(
                "position ({}) + prompt ({}) + max_tokens ({}) exceeds max "
                "sequence {}".format(
                    start, len(prompt), max_tokens, self._max_seq
                )
            )
        stream = _Stream(prompt, int(max_tokens), eos_id,
                         resume_cache, int(resume_pos), on_finish,
                         deadline=deadline, generation_id=generation_id,
                         prompt_dev=prompt_dev,
                         kv_export=kv_export and resume_cache is None,
                         kv_export_on_finish=(
                             kv_export_on_finish and kv_export
                             and resume_cache is None
                             and generation_id is not None))
        if (attach_cache is not None and resume_cache is None
                and 0 < int(attach_pos) <= len(prompt)):
            # phase-split decode admission: scatter the imported export
            # instead of prefilling; an out-of-range position falls
            # back to the prefill path (token-identical, just slower)
            stream.attach_cache = attach_cache
            stream.attach_pos = int(attach_pos)
        with self._cond:
            if self._closed:
                raise SchedulerClosed("scheduler is shut down")
            if self._tripped:
                raise SchedulerClosed(
                    "decode loop restart budget exhausted; the scheduler "
                    "is tripped — drain and restart the replica"
                )
            if self._draining:
                raise SchedulerClosed(
                    "scheduler is draining; not accepting new generations"
                )
            if self._shed_ctl is not None:
                retry_after = self._shed_ctl.on_arrival(
                    time.monotonic(), len(self._pending))
                if retry_after is not None:
                    self._codel_sheds += 1
                    raise AdmissionQueueFull(
                        "admission queue sojourn above target for a "
                        "full control interval ({} waiting "
                        "generations); retry later".format(
                            len(self._pending)),
                        retry_after=retry_after,
                    )
            if len(self._pending) >= self._max_pending:
                raise AdmissionQueueFull(
                    "scheduler admission queue is full ({} waiting "
                    "generations); retry later".format(len(self._pending))
                )
            if generation_id is not None:
                # a reused id supersedes any parked predecessor (and
                # its KV export)
                if self._replay.pop(generation_id, None) is not None \
                        and self._kv_discard is not None:
                    self._kv_discard(generation_id)
            self._pending.append(stream)
            self._streams.add(stream)
            self._ensure_running_locked()
            self._cond.notify_all()
        return self._drain(stream)

    def resume(self, generation_id, from_seq=0, wait_s=5.0,
               deadline=None):
        """Continue a parked generation: replays its buffered
        ``(token, logprob)`` history from ``from_seq`` (the first
        sequence number the caller has NOT seen), then — for an
        interrupted generation — splices live tokens from a re-admitted
        continuation (re-prefilled ``prompt + history``).  Raises
        :class:`UnknownGeneration` when the id was never issued, was
        already resumed, or aged out of the replay buffer.  Replay
        state is replica-local: resume the SAME endpoint that served
        the original request.

        A disconnected stream is only PARKED when the decode loop next
        reaps its cancelled slot, so a fast reconnect can arrive first;
        while the id still names a live stream, resume waits (up to
        ``wait_s``) for the park instead of turning the race into a
        terminal unknown-generation error.  ``deadline`` is the RESUME
        request's own monotonic bound (None lifts any bound): the
        original request's deadline died with its connection — a
        reconnect carrying a fresh timeout must not be killed by the
        stale one."""
        from_seq = int(from_seq)
        # the park-race wait has its own bound; it must not clobber the
        # ``deadline`` parameter, which is the RECONNECT's own request
        # bound (None = unbounded) stamped onto the re-admitted stream
        wait_deadline = time.monotonic() + float(wait_s)
        discard_export = False
        with self._cond:
            while True:
                if self._closed:
                    raise SchedulerClosed("scheduler is shut down")
                self._sweep_replay_locked(time.monotonic())
                entry = self._replay.pop(generation_id, None)
                if entry is not None:
                    break
                live = any(st.generation_id == generation_id
                           for st in self._streams)
                remaining = wait_deadline - time.monotonic()
                if not live or remaining <= 0:
                    raise UnknownGeneration(
                        "unknown or expired generation id '{}' (replay "
                        "entries live {}s after disconnect; resume is "
                        "same-endpoint only)".format(
                            generation_id, self._replay_ttl_s)
                    )
                self._cond.wait(min(0.05, remaining))
            stream, completed, _ = entry
            if from_seq < 0 or from_seq > len(stream.history):
                # put the entry back: a malformed resume must not
                # destroy the (still valid) replay state
                self._replay[generation_id] = entry
                raise UnknownGeneration(
                    "resume point {} is beyond generation '{}' ({} "
                    "tokens emitted)".format(
                        from_seq, generation_id, len(stream.history))
                )
            replay = list(stream.history[from_seq:])
            if completed:
                # a finished generation's tail stays replayable for its
                # whole TTL (the client may lose more than one tail)
                self._replay[generation_id] = entry
            else:
                if self._tripped:
                    self._replay[generation_id] = entry
                    raise SchedulerClosed(
                        "decode loop restart budget exhausted; the "
                        "scheduler is tripped"
                    )
                if self._draining:
                    # same admission gate as submit(): re-admitting an
                    # interrupted generation is NEW decode work and must
                    # not sneak in mid-drain (completed-tail replays
                    # above stay served — they cost no decode)
                    self._replay[generation_id] = entry
                    raise SchedulerClosed(
                        "scheduler is draining; not accepting new "
                        "generations"
                    )
                import queue as _queue

                # fresh queue: the abandoned one may hold tokens the old
                # consumer never took — those are re-delivered from the
                # history snapshot above, never from the stale queue
                stream.queue = _queue.Queue()
                stream.cancelled = False
                stream.finished = False
                stream.deadline = deadline  # the reconnect's own bound
                self._reset_for_readmission(stream)
                if (self._kv_import is not None and stream.kv_export
                        and stream.resume_cache is None):
                    # same-host attach: the park left the generation's
                    # gathered KV in a server-owned XLA-shm region —
                    # re-admission scatters it back and force-feeds one
                    # token instead of re-prefilling prompt + history.
                    # Import is one-shot (the export drops); any
                    # failure below falls back to the re-prefill path.
                    got = self._kv_import(generation_id)
                    if got is not None:
                        cache, valid = got
                        known = len(stream.prompt) + len(stream.history)
                        if 0 < valid <= known:
                            stream.attach_cache = cache
                            stream.attach_pos = int(valid)
                        # one-shot: the region drops AFTER _cond
                        # releases (unlink is syscall work)
                        discard_export = self._kv_discard is not None
                self._pending.append(stream)
                self._streams.add(stream)
                self._ensure_running_locked()
                self._cond.notify_all()
            # counted only once every validation gate passed: a
            # malformed/rejected resume served nothing from the buffer
            self._replay_hits += 1
        if discard_export:
            self._kv_discard(generation_id)

        def gen():
            live = None if completed else self._drain(stream)
            try:
                for tok, lp in replay:
                    yield tok, lp
                if live is not None:
                    for item in live:
                        yield item
            finally:
                if live is not None and not stream.finished:
                    # consumer abandoned during the replay prefix: the
                    # live generator's own cancel hook never ran
                    stream.cancelled = True
                    live.close()

        return gen()

    @staticmethod
    def _drain(stream):
        try:
            while True:
                kind, a, b = stream.queue.get()
                if kind == "tok":
                    yield a, b
                elif kind == "err":
                    stream.finished = True
                    raise a
                else:  # "done"
                    stream.finished = True
                    return
        finally:
            if not stream.finished:
                # consumer gone mid-generation (client cancel/disconnect
                # closes the generator): flag the stream so the decode
                # loop retires its slot instead of burning batched steps
                # on tokens nobody will read (resumable streams park in
                # the replay buffer at that point)
                stream.cancelled = True

    def close(self, join_timeout=30):
        """Stop the loop; pending and in-flight requests error out.
        Subsequent submits raise SchedulerClosed.

        Deterministic even when the loop thread is wedged (e.g. inside a
        stuck device dispatch): if the join times out, every stream the
        loop did not terminally deliver gets a SchedulerClosed error
        here, so no consumer is left blocked on its queue forever."""
        with self._cond:
            already_closed = self._closed
            self._closed = True
            self._cond.notify_all()
            thread = self._thread
            supervisor = self._supervisor
        if thread is not None and not already_closed:
            # join once: a second close() (e.g. core.drain's final
            # close after the scheduler already drained) must not spend
            # another join_timeout re-waiting on a wedged thread —
            # the deterministic leftover-fail below still runs
            thread.join(timeout=join_timeout)
        if supervisor is not None and not already_closed:
            supervisor.join(timeout=5)
        # the loop normally fails every live stream on its way out; after
        # a join timeout (or a loop that never started) do it ourselves
        with self._cond:
            leftover = list(self._streams)
            self._streams.clear()
            self._pending.clear()
            parked_ids = list(self._replay)
            self._replay.clear()
            self._cond.notify_all()
        if self._kv_discard is not None:
            for gid in parked_ids:
                self._kv_discard(gid)
        err = SchedulerClosed("scheduler is shut down")
        for stream in leftover:
            stream.queue.put(("err", err, None))

    def drain(self, timeout=30.0):
        """Graceful drain: stop admission immediately, let pending and
        in-flight generations finish within ``timeout`` seconds, then
        close — deterministically failing whatever remains.  Submits
        during and after the drain raise SchedulerClosed."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._cond.notify_all()
            while self._streams:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        self.close(join_timeout=max(0.1, deadline - time.monotonic()))

    @property
    def healthy(self):
        """False after the decode loop tripped permanently (restart
        budget exhausted) or the scheduler was closed — readiness
        probes report this through ``ServerReady``/``ModelReady`` so
        pools rotate flapping replicas out.  Reads under ``_cond``
        (reentrant — stats() calls this with it held) so a probe never
        sees a half-applied trip."""
        with self._cond:
            return not self._tripped and not self._closed

    def stats(self):
        """Introspection for tests and ops: live stream / pending counts
        and lifecycle flags.  ``live_streams`` counting to zero after
        traffic is the no-leaked-slots invariant chaos tests assert;
        ``restarts`` rising is the flapping signal ops rotate on.  The
        capacity bounds ``max_slots`` / ``max_pending`` ride along so a
        consumer (the fleet router's prober) can turn the counts into a
        utilization signal without extra configuration plumbing."""
        with self._cond:
            pager = self._pager
            if pager is not None:
                alloc, radix = pager
                pages_total = alloc.n_pages
                pages_free = alloc.free_count
                pages_cached = radix.unreferenced if radix is not None else 0
            else:
                # before the first loop start (or after close): the
                # pool is whatever the fns bundle will build
                fns = self._fns
                pages_total = int(fns.get("n_pages", 0) or 0) \
                    if fns is not None else 0
                pages_free = pages_total
                pages_cached = 0
            return {
                "live_streams": len(self._streams),
                "pending": len(self._pending),
                "max_slots": self._max_slots,
                "max_pending": self._max_pending,
                "draining": self._draining,
                "closed": self._closed,
                "healthy": self.healthy,
                "tripped": self._tripped,
                "restarts": self._restarts,
                "quarantined": self._quarantined,
                "replay_entries": len(self._replay),
                "admitted": self._admitted_total,
                "tokens": self._tokens_total,
                "replay_hits": self._replay_hits,
                "codel_sheds": self._codel_sheds,
                "codel_shedding": bool(
                    self._shed_ctl is not None and self._shed_ctl.shedding),
                "prefix_hits": self._prefix_hits,
                "prefix_misses": self._prefix_misses,
                "prefix_evictions": self._prefix_evictions,
                "spec_tokens": self._spec_tokens,
                "spec_steps": self._spec_steps,
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "spec_rollbacks": self._spec_rollbacks,
                "spec_accept_per_step": (
                    (self._spec_steps + self._spec_accepted)
                    / self._spec_steps if self._spec_steps else 0.0),
                "pages_total": pages_total,
                "pages_free": pages_free,
                "pages_cached": pages_cached,
            }

    # -- supervisor --------------------------------------------------------

    def _ensure_running_locked(self):
        """Start (or restart) the supervisor; it owns the loop thread.
        Called with ``_cond`` held."""
        if self._supervisor is None or not self._supervisor.is_alive():
            self._supervisor = threading.Thread(
                target=self._supervise, name="decode-supervisor",
                daemon=True,
            )
            self._supervisor.start()

    def _start_loop_locked(self):
        self._epoch += 1
        self._heartbeat = None
        self._loop_error = None
        self._thread = threading.Thread(
            target=self._run, args=(self._epoch,),
            name="decode-scheduler", daemon=True,
        )
        self._thread.start()

    def _beat(self, epoch, now):
        """Stamp (or clear, ``now=None``) this loop's device-op
        heartbeat.  A superseded loop's clear is dropped so a zombie
        cannot erase the live loop's beat mid-step.  Takes ``_cond``
        (reentrant — the loop's except hook calls this with it held):
        the watchdog compares (epoch, stamp) pairs, and a torn
        read-modify-write against a concurrent supervisor demotion
        could resurrect a cleared beat."""
        with self._cond:
            if now is not None:
                self._heartbeat = (epoch, now)
            else:
                hb = self._heartbeat
                if hb is not None and hb[0] == epoch:
                    self._heartbeat = None

    def _hung_locked(self, now):
        hb = self._heartbeat
        return (
            self._step_timeout_s is not None
            and hb is not None
            and hb[0] == self._epoch  # a zombie's stale stamp is inert
            and now - hb[1] > self._step_timeout_s
        )

    def _supervise(self):
        """Own the decode thread: start it, watch for death or a hung
        step, and restart it (re-admitting live streams) under the
        budget — or trip permanently when the budget is spent."""
        poll = 0.05 if self._step_timeout_s is not None else 0.5
        while True:
            with self._cond:
                if self._closed or self._tripped:
                    return
                if self._thread is None:
                    self._start_loop_locked()
                thread = self._thread
            thread.join(timeout=poll)
            death = None
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                self._sweep_replay_locked(now)
                if self._loop_error is not None:
                    # the loop died; its except hook already salvaged
                    # slotted streams back into _pending
                    death = self._loop_error
                    self._loop_error = None
                elif thread.is_alive() and self._hung_locked(now):
                    # wedged device dispatch: demote the thread (epoch
                    # bump — every delivery it attempts after waking is
                    # dropped) and salvage its streams from the registry
                    death = _HungStep(
                        "decode step exceeded step_timeout_s={}s".format(
                            self._step_timeout_s)
                    )
                    self._epoch += 1
                    self._heartbeat = None
                    self._thread = None
                    pending_set = set(self._pending)
                    for st in [s for s in self._streams
                               if s not in pending_set]:
                        if st.cancelled:
                            self._detach_locked(st)
                        else:
                            self._reset_for_readmission(st)
                            self._pending.appendleft(st)
                if death is None:
                    continue
                # restart budget: a sliding window of restart times
                while (self._recent_restarts
                       and now - self._recent_restarts[0]
                       > self._restart_window_s):
                    self._recent_restarts.popleft()
                if len(self._recent_restarts) >= self._max_restarts:
                    self._tripped = True
                    to_fail = list(self._streams)
                    self._streams.clear()
                    self._pending.clear()
                    self._cond.notify_all()
                else:
                    to_fail = None
                    self._recent_restarts.append(now)
                    self._restarts += 1
                    backoff = min(
                        self._restart_backoff_s
                        * (2 ** (len(self._recent_restarts) - 1)),
                        2.0,
                    )
                    # the FULL backoff must elapse (a transient device
                    # fault needs the pause to clear): every submit /
                    # delivery notify_all would otherwise cut the wait
                    # short and burn the whole restart budget in
                    # milliseconds.  Only close() interrupts.
                    backoff_until = now + backoff
                    while not self._closed:
                        remaining = backoff_until - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                    if self._closed:
                        return
                    if self._thread is None:
                        self._start_loop_locked()
            if to_fail is not None:
                err = SchedulerClosed(
                    "decode loop restart budget exhausted ({} restarts "
                    "in {}s) after: {}".format(
                        self._max_restarts, self._restart_window_s, death)
                )
                for st in to_fail:
                    st.queue.put(("err", err, None))
                return

    def _reset_for_readmission(self, stream):
        """Prepare a salvaged/resumed stream for a fresh admission: the
        new loop re-prefills ``prompt + history`` (or forced-feeds both
        over a parked cache), so emission continues exactly where it
        stopped.  Called with ``_cond`` held."""
        stream.pos = 0
        stream.forced.clear()
        stream.enqueued_at = time.monotonic()
        # paging state belonged to the dead loop's pool: the new loop
        # re-reserves pages (and re-matches the radix tree) on
        # re-admission
        stream.table = None
        stream.radix_nodes = None
        stream.span_pages = 0
        # a pending attach-resume dies with the loop that would have
        # scattered it: the salvage re-admission falls back to the
        # re-prefill path (greedy decode makes both token-identical)
        stream.attach_cache = None
        stream.attach_pos = 0
        # speculation throttle restarts fresh: the acceptance profile
        # under the new loop's (cold) radix cache is unknown
        stream.spec_miss = 0
        stream.spec_skip = 0

    # -- replay buffer -----------------------------------------------------

    def _sweep_replay_locked(self, now):
        expired = [
            gid for gid, (_, _, expires) in self._replay.items()
            if expires <= now
        ]
        for gid in expired:
            self._replay.pop(gid, None)
            if self._kv_discard is not None:
                # the KV export shares the replay entry's lifetime: an
                # id nobody can resume anymore must not pin HBM/shm
                self._kv_discard(gid)

    def _park_locked(self, stream, completed):
        """Retain a resumable generation's history for later resume.
        Called with ``_cond`` held."""
        now = time.monotonic()
        self._sweep_replay_locked(now)
        if completed:
            # a completed park only ever serves history[from_seq:]
            # replays — drop the device state NOW, or up to
            # replay_capacity parked KV-cache copies (resume_cache) and
            # shm-pinning on_finish closures would sit in the buffer
            # for the whole TTL — and any KV export is dead weight (a
            # finished generation never re-decodes)
            stream.resume_cache = None
            stream.on_finish = None
            stream.attach_cache = None
            if (self._kv_discard is not None and stream.kv_export
                    and not stream.kv_export_on_finish):
                # a phase-export (kv_export_on_finish) OUTLIVES the
                # completed park on purpose: the decode-role replica
                # attaches it after this generation's prefill leg
                # finished.  It still dies with the replay entry's TTL
                # sweep (or an explicit drop), so nothing leaks.
                self._kv_discard(stream.generation_id)
        self._replay[stream.generation_id] = (
            stream, completed, now + self._replay_ttl_s
        )
        self._replay.move_to_end(stream.generation_id)
        while len(self._replay) > self._replay_capacity:
            gid, _ = self._replay.popitem(last=False)  # evict oldest
            if self._kv_discard is not None:
                self._kv_discard(gid)

    def _detach_locked(self, stream):
        """Retire a cancelled stream from the live registry; resumable
        ones park in the replay buffer instead of vanishing.  Called
        with ``_cond`` held."""
        self._streams.discard(stream)
        if stream.generation_id is not None and not stream.finished:
            self._park_locked(stream, completed=False)
        self._cond.notify_all()

    # -- decode loop -------------------------------------------------------

    def _fail(self, stream, exc, epoch=None):
        self._deliver(stream, ("err", exc, None), epoch)

    def _deliver(self, stream, event, epoch=None):
        """Deliver a terminal event and retire the stream from the live
        registry (never call while holding ``_cond`` — it takes it).
        With ``epoch``, delivery is dropped when the calling loop has
        been superseded (the new loop owns the stream)."""
        with self._cond:
            if epoch is not None and epoch != self._epoch:
                return
            self._streams.discard(stream)
            if event[0] == "done" and stream.generation_id is not None:
                # completed generations stay resumable for the TTL so a
                # client that lost the tail can replay it
                self._park_locked(stream, completed=True)
            self._cond.notify_all()
            # under the lock: a racing watchdog salvage must either see
            # this terminal delivery or run strictly before it
            stream.queue.put(event)

    def _run(self, epoch):
        slots = [None] * self._max_slots  # slot -> _Stream | None
        try:
            self._loop(slots, epoch)
        except Exception as e:  # noqa: BLE001 — loop death is the
            # supervisor's restart (or trip) signal; swallowing it here
            # would leave every consumer blocked forever on its queue
            with self._cond:
                if self._epoch != epoch:
                    return  # superseded zombie: the new loop owns it all
                self._loop_error = e
                self._beat(epoch, None)
                if self._thread is threading.current_thread():
                    # unregister NOW, under the lock: a submit racing
                    # this cleanup must see no live thread; the
                    # supervisor starts the replacement
                    self._thread = None
                # salvage: slotted streams re-enter the pending queue at
                # the FRONT (they were admitted first) with their state
                # reset for re-prefill of prompt + history
                for st in reversed([s for s in slots if s is not None]):
                    if st not in self._streams:
                        continue  # already terminally delivered
                    if st.cancelled:
                        self._detach_locked(st)
                        continue
                    self._reset_for_readmission(st)
                    self._pending.appendleft(st)
                self._cond.notify_all()
                self._ensure_running_locked()

    def _loop(self, slots, epoch):
        import jax.numpy as jnp

        fns = self._fns
        page = fns["page_size"]
        ppseq = fns["pages_per_seq"]
        n_pages = fns["n_pages"]
        # chunked/shared prefill runs spans through the dense cached
        # path; on a flash-prefill config that could flip a near-tie
        # greedy argmax vs the one-shot kernel, so both fall back to
        # whole-prompt prefill there (the prefill_bucket determinism
        # policy, applied to spans)
        span_safe = fns["span_safe"]
        chunk = self._prefill_chunk_tokens if span_safe else None
        pages = fns["init_cache"]()
        logits = fns["init_logits"]()
        alloc = PageAllocator(n_pages, page)
        radix = (RadixPrefixCache(page)
                 if self._prefix_cache and span_safe else None)
        with self._cond:
            # stats/gauges read the live pool through this reference;
            # a supervised restart rebuilds pool, allocator and radix
            # together (the radix cache restarts cold and re-warms)
            self._pager = (alloc, radix)
        # per-slot page tables, re-scattered to the device each step
        # (sentinel rows are inert); mutated in place as slots turn
        # over — each dispatch converts the then-current content
        tables = np.full((self._max_slots, ppseq), n_pages, np.int32)
        ready = [False] * self._max_slots  # prefill complete
        prefilling = {}                    # slot -> _PrefillTask
        inflight = None  # (tokens_dev, logps_dev, snapshot)
        # speculative decoding: the drafter reads the radix tree (and
        # each stream's own context) — strictly read-only, so it can
        # never change what eviction may reclaim.  max_draft is
        # spec_k + 1 because the drafter's first proposal predicts the
        # step's OWN next token (which the verify step computes
        # exactly); the remaining spec_k feed as candidates.
        spec_k = self._spec_tokens
        drafter = (NgramDrafter(radix, max_draft=spec_k + 1)
                   if spec_k > 0 else None)

        def clear_slot(slot):
            slots[slot] = None
            ready[slot] = False
            tables[slot] = n_pages

        def superseded():
            """True once a watchdog demotion replaced this loop: a
            thread waking from a hung dispatch must stop mutating
            stream state the successor loop now owns (its own pool,
            tables and tasks die with it and need no cleanup)."""
            with self._cond:
                return self._epoch != epoch

        def release_pages(stream, insert=True):
            """Return a stream's pages to the pool.  The pinned radix
            path unrefs; full pages covered by fed tokens donate back
            as unpinned cached entries (a later resume, restart
            re-admission, or sibling prompt hits them instead of
            re-prefilling — content-addressed, so always safe);
            everything else frees.  ``insert=False`` for poisoned or
            failed streams whose written KV must not be cached."""
            with self._cond:
                if self._epoch != epoch:
                    # superseded (watchdog demotion mid-dispatch): the
                    # stream may already be re-admitted by the NEW
                    # loop with paging state from the NEW pool —
                    # touching stream.table/radix_nodes here would
                    # corrupt it (this loop's own pool dies with it)
                    return
            table = stream.table
            nodes = stream.radix_nodes or []
            if table is None:
                # failed before the span reserved: only the matched
                # pins (if any) need returning
                if nodes:
                    radix.release(nodes)
                stream.radix_nodes = None
                return
            path_len = len(nodes)
            owned = [int(table[d])
                     for d in range(path_len, stream.span_pages)]
            if (insert and radix is not None
                    and stream.resume_cache is None):
                known = (list(int(t) for t in stream.prompt)
                         + [t for t, _ in stream.history])
                insertable = min(stream.pos, len(known)) // page
                donate = max(0, insertable - path_len)
                if donate:
                    _, _, dup_ids = radix.insert_tail(
                        nodes, known, path_len, owned[:donate],
                        pin=False)
                    alloc.free(dup_ids)
                    owned = owned[donate:]
            alloc.free(owned)
            if nodes:
                radix.release(nodes)
            stream.table = None
            stream.radix_nodes = None
            stream.span_pages = 0

        def export_kv(stream):
            """Park a reaped resumable stream's gathered KV through the
            ``kv_export`` hook (the server owns it as an XLA-shm region
            keyed by the generation id).  The valid prefix is exactly
            ``prompt + history`` positions: every dispatched-but-
            unfetched step's write lands beyond it, so the export can
            never contain a token the client was not delivered.  Runs
            BEFORE ``release_pages`` — the gather captures the current
            pool value, so later page reuse cannot corrupt it.  Called
            under the loop's ``_cond`` at both reap sites: the cost is
            an async gather dispatch plus a few shm syscalls (the
            export stores the device reference — no copy), paid only
            on the rare cancel reap.  Export is an optimization: any
            failure silently falls back to the re-prefill resume
            path."""
            if (self._kv_export is None or not stream.kv_export
                    or stream.generation_id is None
                    or stream.resume_cache is not None
                    or stream.table is None):
                return
            valid = len(stream.prompt) + len(stream.history)
            if valid <= 0:
                return
            try:
                parked = fns["gather"](pages, stream.table)
                self._kv_export(stream.generation_id, parked, valid)
            except Exception:  # noqa: BLE001 — optimization only
                pass

        def complete_admission(slot, stream, full):
            """Post-admit bookkeeping: donate the prompt's full pages
            to the radix tree NOW (pinned — siblings admitted next
            iteration already share them), publish the page table, and
            count the admission."""
            if superseded():
                return  # zombie: the stream belongs to the new loop
            if (radix is not None and full is not None
                    and stream.resume_cache is None):
                path_len = len(stream.radix_nodes)
                donate = stream.pos // page - path_len
                if donate > 0:
                    owned = [int(stream.table[d])
                             for d in range(path_len, path_len + donate)]
                    appended, dups, dup_ids = radix.insert_tail(
                        stream.radix_nodes, full, path_len, owned,
                        pin=True)
                    for d, existing in dups:
                        # a concurrent sibling already donated this
                        # page's content: the tree copy wins (equal
                        # bytes — content-addressed) and ours frees
                        stream.table[d] = existing
                    alloc.free(dup_ids)
                    stream.radix_nodes.extend(appended)
            tables[slot] = stream.table
            ready[slot] = True
            self._admitted_total += 1
            if self._queue_hist is not None:
                self._queue_hist.observe(
                    time.monotonic() - stream.enqueued_at)

        def start_admission(slot, stream):
            """Reserve the stream's page span and run (or begin) its
            prefill.  The slot is already reserved in ``slots``; on a
            shed or per-request fault it is cleared here."""
            nonlocal pages, logits
            t = self._step_timeout_s
            try:
                if superseded():
                    # a previous admission's hung dispatch demoted this
                    # loop mid-iteration: the remaining admissions are
                    # the NEW loop's to make
                    return
                # admission-failure chaos hook
                faults.fire("scheduler.admit", self.fault_scope)
                # new incarnation: step snapshots taken against a
                # previous admission of this stream object become inert
                stream.incarnation += 1
                if stream.attach_cache is not None:
                    # park-attach resume (tentpole 3): the generation's
                    # exported KV pages scatter straight back into a
                    # fresh page span and ONE token (the last of the
                    # valid prefix, rewritten in place) force-feeds to
                    # regenerate the logits — no re-prefill of
                    # prompt + history.  Token-identical to the
                    # re-prefill path by greedy determinism
                    # (test-pinned in tests/test_shm_data_plane.py).
                    known = [int(t_) for t_ in stream.prompt] + [
                        t_ for t_, _ in stream.history]
                    start = stream.attach_pos - 1
                    span_end = len(stream.prompt) + stream.max_tokens
                    span_pages = pages_for(span_end, page)
                    stream.radix_nodes = []
                    owned = alloc.alloc(span_pages)
                    if owned is None and radix is not None:
                        freed = radix.evict(span_pages - alloc.free_count)
                        self._prefix_evictions += len(freed)
                        alloc.free(freed)
                        owned = alloc.alloc(span_pages)
                    if owned is None:
                        self._fail(stream, AdmissionQueueFull(
                            "kv page pool exhausted: attach-resume "
                            "needs {} pages but only {} are free; "
                            "retry later".format(
                                span_pages, alloc.free_count)), epoch)
                        clear_slot(slot)
                        return
                    table = np.full((ppseq,), n_pages, np.int32)
                    table[:span_pages] = owned
                    stream.table = table
                    stream.span_pages = span_pages
                    t = self._step_timeout_s
                    self._beat(epoch,
                               time.monotonic() + 9 * t if t else None)
                    slot_logits = jnp.zeros(
                        (1, logits.shape[1]), logits.dtype)
                    stream.forced.extend(known[start:])
                    stream.pos = start
                    attach_cache = stream.attach_cache
                    stream.attach_cache = None  # consumed
                    pages, logits = fns["admit"](
                        pages, logits, jnp.asarray(attach_cache),
                        slot_logits, table, slot)
                    complete_admission(slot, stream, None)
                    return
                replayed = [t_ for t_, _ in stream.history]
                start = (stream.resume_pos
                         if stream.resume_cache is not None else 0)
                full = (
                    np.concatenate(
                        [stream.prompt, np.asarray(replayed, np.int32)])
                    if replayed else stream.prompt
                )
                prefill_len = start + len(full)
                # the whole potential span reserves up front, so decode
                # can never run out of pages mid-generation: exhaustion
                # is a typed admission-time shed, not an OOM
                span_end = start + len(stream.prompt) + stream.max_tokens
                span_pages = pages_for(span_end, page)
                matched_nodes = []
                shared_pages = 0
                if radix is not None and stream.resume_cache is None:
                    nodes, _ids = radix.match(full)
                    # cap so the prompt's LAST token always re-runs:
                    # its logits seed the first decode step
                    shared_pages = min(
                        len(nodes), (prefill_len - 1) // page)
                    matched_nodes = nodes[:shared_pages]
                    # recorded on the stream BEFORE anything can fail:
                    # the exception/shed paths unpin via
                    # release_pages(stream), which reads this field
                    stream.radix_nodes = list(matched_nodes)
                    if matched_nodes:
                        # pin BEFORE any eviction can run for this
                        # admission's own allocation
                        radix.acquire(matched_nodes)
                shared_len = shared_pages * page
                needed = span_pages - shared_pages
                owned = alloc.alloc(needed)
                if owned is None and radix is not None:
                    freed = radix.evict(needed - alloc.free_count)
                    self._prefix_evictions += len(freed)
                    alloc.free(freed)
                    owned = alloc.alloc(needed)
                if owned is None:
                    release_pages(stream, insert=False)  # unpin only
                    self._fail(stream, AdmissionQueueFull(
                        "kv page pool exhausted: admission needs {} "
                        "pages but only {} are free and every cached "
                        "page is pinned by a live stream; retry "
                        "later".format(needed, alloc.free_count)), epoch)
                    clear_slot(slot)
                    return
                # counted only once the reservation SUCCEEDED: a shed
                # admission served nothing and prefilled nothing, so it
                # must not skew the hit-rate perfanalyzer window-diffs
                if stream.resume_cache is None:
                    if radix is not None:
                        self._prefix_hits += shared_len
                    self._prefix_misses += prefill_len - shared_len
                table = np.full((ppseq,), n_pages, np.int32)
                for d, node in enumerate(matched_nodes):
                    table[d] = node.page
                table[shared_pages:span_pages] = owned
                stream.table = table
                if stream.radix_nodes is None:
                    stream.radix_nodes = []  # radix off / resume path
                stream.span_pages = span_pages
                # prefill dispatches are watchdogged like steps, with
                # the compile headroom admissions get (future-dated
                # stamp = a 10x deadline: a novel bucket may
                # legitimately compile)
                self._beat(epoch, time.monotonic() + 9 * t if t else None)
                if stream.resume_cache is not None:
                    # parked-cache restore: the parked contiguous row
                    # scatters into the reserved pages (only READ —
                    # the region's copy stays valid for the next
                    # resume) and the prompt (+ history, after a
                    # restart) replays as forced tokens
                    slot_logits = jnp.zeros(
                        (1, logits.shape[1]), logits.dtype)
                    stream.forced.extend(int(t_) for t_ in stream.prompt)
                    stream.forced.extend(int(t_) for t_ in replayed)
                    stream.pos = start
                    pages, logits = fns["admit"](
                        pages, logits, jnp.asarray(stream.resume_cache),
                        slot_logits, table, slot)
                    complete_admission(slot, stream, None)
                    return
                suffix = np.asarray(full[shared_len:], np.int32)
                suffix_len = len(suffix)
                if shared_pages:
                    # restore the shared prefix into the single-row
                    # cache, then prefill only the unique suffix on
                    # top of it — the shared-system-prompt admission
                    # pays for its suffix alone
                    prefix_table = np.full((ppseq,), n_pages, np.int32)
                    prefix_table[:shared_pages] = table[:shared_pages]
                    slot_cache = fns["gather"](pages, prefix_table)
                    dest = table.copy()
                    # shared pages live in the pool already: never
                    # rewrite them from this admission's scatter
                    dest[:shared_pages] = n_pages
                else:
                    slot_cache = None
                    dest = table
                if chunk is not None and suffix_len > chunk:
                    pad_len = min(-(-suffix_len // chunk) * chunk,
                                  self._max_seq - shared_len)
                    padded = np.zeros((pad_len,), np.int32)
                    padded[:suffix_len] = suffix
                    if slot_cache is None:
                        slot_cache = fns["init_slot_cache"]()
                    prefilling[slot] = _PrefillTask(
                        stream, slot, slot_cache, padded, shared_len,
                        suffix_len - 1, chunk, dest, full)
                    return
                if shared_pages:
                    bucket = 8
                    while bucket < suffix_len:
                        bucket <<= 1
                    bucket = min(bucket, self._max_seq - shared_len)
                    padded = np.zeros((bucket,), np.int32)
                    padded[:suffix_len] = suffix
                    slot_logits, slot_cache = fns["prefill_span"](
                        self._params, slot_cache,
                        jnp.asarray(padded)[None, :], shared_len,
                        suffix_len - 1)
                    if superseded():
                        return  # demoted mid-dispatch: mutate nothing
                else:
                    # cold one-shot admission: the pre-paging bucketed
                    # prefill, byte-for-byte (prefill_bucket keeps the
                    # kernel choice, padding rows stay masked)
                    bucket = fns["prefill_bucket"](suffix_len)
                    if (stream.prompt_dev is not None and not replayed
                            and stream.resume_cache is None):
                        # zero-copy data plane: the prompt is already a
                        # device-resident XLA-shm segment view — pad it
                        # on device (zeros + scatter of the view) so
                        # the ids never stage through the host
                        tokens_in = jnp.zeros(
                            (bucket,), jnp.int32
                        ).at[:suffix_len].set(
                            stream.prompt_dev.astype(jnp.int32)
                        )[None, :]
                    else:
                        padded = np.zeros((bucket,), np.int32)
                        padded[:suffix_len] = suffix
                        tokens_in = jnp.asarray(padded)[None, :]
                    slot_cache = fns["init_slot_cache"]()
                    slot_logits, slot_cache = fns["prefill"](
                        self._params, slot_cache, tokens_in, suffix_len)
                    if superseded():
                        return  # demoted mid-dispatch: mutate nothing
                stream.pos = prefill_len
                pages, logits = fns["admit"](
                    pages, logits, slot_cache, slot_logits, dest, slot)
                complete_admission(slot, stream, full)
            except Exception as e:  # noqa: BLE001 — per-request fault
                release_pages(stream, insert=False)
                self._fail(stream, e, epoch)
                clear_slot(slot)
            finally:
                self._beat(epoch, None)

        def run_prefill_chunk():
            """One chunk of the oldest in-progress chunked prefill —
            a single bounded dispatch interleaved with the decode
            step, so co-batched streams keep emitting."""
            nonlocal pages, logits
            if superseded():
                return
            slot, task = next(iter(prefilling.items()))
            stream = task.stream
            n = min(task.chunk, task.total - task.done)
            tok = jnp.asarray(
                task.padded[task.done:task.done + n])[None, :]
            rel = task.logits_at - task.done
            rel = rel if 0 <= rel < n else 0
            t = self._step_timeout_s
            self._beat(epoch, time.monotonic() + 9 * t if t else None)
            try:
                chunk_logits, task.slot_cache = fns["prefill_span"](
                    self._params, task.slot_cache, tok,
                    task.start + task.done, rel)
                if superseded():
                    return  # demoted mid-dispatch: mutate nothing
                task.done += n
                if task.done < task.total:
                    return
                del prefilling[slot]
                stream.pos = task.start + task.logits_at + 1
                pages, logits = fns["admit"](
                    pages, logits, task.slot_cache, chunk_logits,
                    task.dest, slot)
                complete_admission(slot, stream, task.full)
            except Exception as e:  # noqa: BLE001 — per-request fault
                prefilling.pop(slot, None)
                release_pages(stream, insert=False)
                self._fail(stream, e, epoch)
                clear_slot(slot)
            finally:
                self._beat(epoch, None)

        def step_chaos():
            """The ONE registered fire site (R6) for "scheduler.step":
            the pipelined and the speculative step paths are mutually
            exclusive per configuration, and both are the same logical
            injection point — the batched decode dispatch."""
            return faults.fire("scheduler.step", self.fault_scope)

        def fetch_chaos():
            """The ONE registered fire site (R6) for
            "scheduler.fetch" — the step-result host transfer, on
            whichever path (pipelined or speculative) is active."""
            faults.fire("scheduler.fetch", self.fault_scope)

        def finish(stream, slot):
            if stream.on_finish is not None:
                # gather+park is a device dispatch too: under the
                # watchdog, with the same compile headroom admissions
                # get (a future-dated stamp = a 10x deadline)
                t = self._step_timeout_s
                self._beat(epoch,
                           time.monotonic() + 9 * t if t else None)
                try:
                    parked = fns["gather"](pages, stream.table)
                    if superseded():
                        return  # never park a stale copy over the
                        # successor loop's own park
                    stream.on_finish(parked)
                except Exception as e:  # noqa: BLE001 — park is
                    # per-stream
                    self._fail(stream, e, epoch)
                    release_pages(stream)
                    clear_slot(slot)
                    return
                finally:
                    self._beat(epoch, None)
            if stream.kv_export_on_finish:
                # disaggregated prefill leg: the finished generation's
                # KV (prompt + the one emitted token) exports BEFORE
                # its pages free — the decode-role replica attaches
                # this region instead of re-prefilling
                export_kv(stream)
            release_pages(stream)
            self._deliver(stream, ("done", None, None), epoch)
            clear_slot(slot)

        while True:
            expired = []
            with self._cond:
                if self._epoch != epoch:
                    return  # superseded by a watchdog restart
                while (
                    not self._closed
                    and not self._draining
                    and not self._pending
                    and inflight is None
                    and not any(s is not None for s in slots)
                ):
                    self._cond.wait()
                    if self._epoch != epoch:
                        return
                if self._closed:
                    pending = list(self._pending)
                    self._pending.clear()
                    break
                if (
                    self._draining
                    and not self._pending
                    and inflight is None
                    and not any(s is not None for s in slots)
                ):
                    # drain complete: every accepted generation finished;
                    # exit cleanly so drain() sees a closed scheduler
                    self._closed = True
                    pending = []
                    break
                # reap cancelled streams first: their consumers are gone,
                # so the slot (and its pages) free for waiting work (no
                # park of the KV — resumable streams keep only their
                # token history; their full pages donate to the radix
                # cache, so the resume's re-prefill is mostly a hit)
                for i, st in enumerate(slots):
                    if st is not None and st.cancelled:
                        prefilling.pop(i, None)
                        if ready[i]:
                            # park-export before the pages free: the
                            # resumable stream's attach-resume rides it
                            export_kv(st)
                        release_pages(st)
                        self._detach_locked(st)
                        clear_slot(i)
                # deadline sweep: a pending request past its deadline
                # fails BEFORE prefill (no slot or compute is ever spent
                # on it); an in-flight one retires mid-generation, its
                # slot and pages freeing for waiting work this iteration
                now = time.monotonic()
                if self._shed_ctl is not None:
                    # adaptive-shed sojourn signal: the head stream's
                    # wait is the FIFO maximum, so "head under target"
                    # means the whole queue is.  Noted inside the
                    # already-held _cond region — the controller costs
                    # the loop zero new lock acquisitions.
                    self._shed_ctl.note_sojourn(
                        (now - self._pending[0].enqueued_at)
                        if self._pending else 0.0, now)
                if self._pending:
                    keep = deque()
                    for st in self._pending:
                        (expired if st.expired(now) else keep).append(st)
                    self._pending = keep
                for i, st in enumerate(slots):
                    if st is not None and st.expired(now):
                        expired.append(st)
                        prefilling.pop(i, None)
                        release_pages(st)
                        clear_slot(i)
                self._cond.notify_all()
                admissions = []
                free = [i for i, s in enumerate(slots) if s is None]
                while self._pending and free:
                    st = self._pending.popleft()
                    if st.cancelled:
                        self._detach_locked(st)
                        continue  # abandoned while still queued
                    slot = free.pop(0)
                    # reserve NOW, under the lock: the cancel-reap and
                    # the watchdog salvage must see prefilling streams
                    # as slotted
                    slots[slot] = st
                    admissions.append((slot, st))
            # deadline failures deliver OUTSIDE the lock (delivery
            # re-takes it to retire the stream from the live registry)
            for st in expired:
                self._fail(st, DeadlineExceeded(
                    "request deadline exceeded after {} emitted "
                    "tokens".format(st.emitted)), epoch)
            # device work runs OUTSIDE the lock: submitters must be able
            # to enqueue while the chip computes
            for slot, stream in admissions:
                start_admission(slot, stream)
            if prefilling:
                # exactly one bounded chunk per iteration: long
                # prompts trickle in while decode keeps stepping
                run_prefill_chunk()

            current = None
            active_ids = [i for i, s in enumerate(slots)
                          if s is not None and ready[i]]
            if active_ids and spec_k > 0:
                # speculative multi-token step (ISSUE 19): draft up to
                # spec_k candidates per slot from the radix cache, feed
                # them all through ONE batched verify dispatch, keep
                # the longest argmax-matching prefix plus the bonus
                # token.  Variable per-slot advance makes the one-deep
                # pipeline impossible (the NEXT step's positions depend
                # on THIS step's acceptance), so the spec path
                # dispatches and fetches in the same iteration;
                # ``inflight`` stays None.
                positions = np.full(
                    (self._max_slots,), self._max_seq, np.int32)
                active = np.zeros((self._max_slots,), bool)
                forced_tok = np.zeros((self._max_slots,), np.int32)
                forced_mask = np.zeros((self._max_slots,), bool)
                draft = np.zeros((self._max_slots, spec_k), np.int32)
                draft_len = np.zeros((self._max_slots,), np.int32)
                snapshot = []
                for i in active_ids:
                    st = slots[i]
                    positions[i] = st.pos
                    active[i] = True
                    was_forced = bool(st.forced)
                    if was_forced:
                        forced_tok[i] = st.forced.popleft()
                        forced_mask[i] = True
                    k_i = 0
                    if not was_forced:
                        if st.spec_skip > 0:
                            # throttled: this step probes nothing
                            st.spec_skip -= 1
                        else:
                            # never draft past the emission budget:
                            # 1 bonus + k_i accepted must fit
                            budget = min(
                                spec_k, st.max_tokens - st.emitted - 1)
                            if budget > 0:
                                ctx = [int(t) for t in st.prompt]
                                ctx.extend(t for t, _ in st.history)
                                # the drafter's FIRST proposal predicts
                                # this step's own next token — which the
                                # verify step computes exactly — so it
                                # drops and the rest feed as candidates
                                d = drafter.draft(ctx, budget + 1)[1:]
                                k_i = len(d)
                                if k_i:
                                    draft[i, :k_i] = d
                                    draft_len[i] = k_i
                    # pos does NOT advance at snapshot (unlike the
                    # pipelined path): the fetch below advances it by
                    # the tokens actually kept
                    snapshot.append(
                        (i, st, was_forced, st.incarnation, k_i))
                action = step_chaos()
                if action is not None and action[0] == "nan":
                    row = min(max(0, action[1]), self._max_slots - 1)
                    logits = logits.at[row].set(float("nan"))
                step_start = time.monotonic()
                self._beat(epoch, step_start)
                if action is not None and action[0] == "hang":
                    time.sleep(action[1])
                if draft_len.any():
                    toks_dev, lps_dev, acc_dev, logits, pages = fns[
                        "spec_step"](
                        self._params, pages, logits, tables, positions,
                        active, forced_tok, forced_mask, draft,
                        draft_len,
                    )
                else:
                    # nobody drafted (cold caches, all throttled): a
                    # plain sub-step costs spec_k fewer weight passes
                    # and is bitwise-identical for the one token
                    toks_dev, lps_dev, logits, pages = fns["step"](
                        self._params, pages, logits, tables, positions,
                        active, forced_tok, forced_mask,
                    )
                    acc_dev = None
                self._beat(epoch, None)
                if self._step_hist is not None:
                    self._step_hist.observe(
                        time.monotonic() - step_start)
                # host-transfer chaos; a raise is loop death (restart)
                fetch_chaos()
                self._beat(epoch, time.monotonic())
                toks = np.asarray(toks_dev)
                lps = np.asarray(lps_dev)
                accs = (np.asarray(acc_dev) if acc_dev is not None else
                        np.zeros((self._max_slots,), np.int32))
                self._beat(epoch, None)
                if toks.ndim == 1:
                    # plain-step fallback: same emission code below,
                    # one column, zero accepted drafts
                    toks = toks[:, None]
                    lps = lps[:, None]
                quarantined = []
                finished = []
                with self._cond:
                    if self._epoch != epoch:
                        return  # superseded mid-fetch: deliver nothing
                    for i, st, was_forced, inc, k_i in snapshot:
                        if slots[i] is not st or st.incarnation != inc:
                            continue  # slot retired mid-step
                        if st.cancelled:
                            export_kv(st)
                            release_pages(st)
                            self._detach_locked(st)
                            clear_slot(i)
                            continue
                        a = min(int(accs[i]), k_i)
                        if k_i:
                            self._spec_steps += 1
                            self._spec_proposed += k_i
                            self._spec_accepted += a
                            if a < k_i:
                                self._spec_rollbacks += 1
                            if a > 0:
                                st.spec_miss = 0
                            else:
                                st.spec_miss += k_i
                                if (st.spec_miss
                                        >= self._spec_throttle_after):
                                    st.spec_skip = (
                                        self._spec_probe_interval)
                        if was_forced:
                            st.pos += 1
                            continue  # resumed-prompt feed, no emission
                        fed = 0
                        poisoned = False
                        hit_eos = False
                        for j in range(1 + a):
                            tok = int(toks[i, j])
                            lp = float(lps[i, j])
                            if not np.isfinite(lp):
                                poisoned = True
                                break
                            st.history.append((tok, lp))
                            st.queue.put(("tok", tok, lp))
                            st.emitted += 1
                            self._tokens_total += 1
                            fed += 1
                            if (st.eos_id is not None
                                    and tok == st.eos_id):
                                hit_eos = True
                                break
                        if poisoned:
                            # poisoned output: row-independent math, so
                            # co-batched slots are untouched — retire
                            # only the offender, never donating its KV
                            quarantined.append((i, st))
                            release_pages(st, insert=False)
                            clear_slot(i)
                            continue
                        # rejected-position rollback is exactly this
                        # cursor move: the next step re-feeds from
                        # here, overwriting any speculative garbage
                        # beyond it (still inside the reserved span,
                        # and release_pages donates only up to pos —
                        # nothing leaks or double-donates)
                        st.pos += fed
                        if st.emitted >= st.max_tokens or hit_eos:
                            finished.append((st, i))
                for i, st in quarantined:
                    with self._cond:
                        self._quarantined += 1
                    self._fail(st, SlotQuarantined(
                        "generation produced non-finite logits after {} "
                        "emitted tokens; its slot was quarantined (co-"
                        "batched generations are unaffected)".format(
                            st.emitted)), epoch)
                for st, i in finished:
                    finish(st, i)
            elif active_ids:
                # sentinel position max_seq on inert rows: their cache
                # writes drop instead of corrupting a parked slot
                positions = np.full(
                    (self._max_slots,), self._max_seq, np.int32)
                active = np.zeros((self._max_slots,), bool)
                forced_tok = np.zeros((self._max_slots,), np.int32)
                forced_mask = np.zeros((self._max_slots,), bool)
                snapshot = []
                for i in active_ids:
                    st = slots[i]
                    positions[i] = st.pos
                    active[i] = True
                    was_forced = bool(st.forced)
                    if was_forced:
                        forced_tok[i] = st.forced.popleft()
                        forced_mask[i] = True
                    snapshot.append((i, st, was_forced, st.incarnation))
                    st.pos += 1
                # chaos hook: "scheduler.step" raise = loop death (the
                # supervised-restart path), sleep = slow step, nan =
                # poison one slot's logits row (the quarantine path),
                # hang = stall INSIDE the heartbeat window below so the
                # watchdog provably observes it.  A raise here may have
                # left the donated cache consumed — exactly what the
                # restart rebuilds.
                action = step_chaos()
                if action is not None and action[0] == "nan":
                    row = min(max(0, action[1]), self._max_slots - 1)
                    logits = logits.at[row].set(float("nan"))
                step_start = time.monotonic()
                self._beat(epoch, step_start)
                if action is not None and action[0] == "hang":
                    time.sleep(action[1])
                tokens_dev, logps_dev, logits, pages = fns["step"](
                    self._params, pages, logits, tables, positions,
                    active, forced_tok, forced_mask,
                )
                self._beat(epoch, None)
                if self._step_hist is not None:
                    # lock-free observe: the loop must never acquire a
                    # lock per step just to be observable
                    self._step_hist.observe(
                        time.monotonic() - step_start)
                current = (tokens_dev, logps_dev, snapshot)

            if inflight is not None:
                tokens_dev, logps_dev, snapshot = inflight
                # host-transfer chaos; a raise is loop death (restart)
                fetch_chaos()
                self._beat(epoch, time.monotonic())
                toks = np.asarray(tokens_dev)
                lps = np.asarray(logps_dev)
                self._beat(epoch, None)
                quarantined = []
                finished = []
                with self._cond:
                    if self._epoch != epoch:
                        return  # superseded mid-fetch: deliver nothing
                    for i, st, was_forced, inc in snapshot:
                        if slots[i] is not st or st.incarnation != inc:
                            # slot retired (and possibly re-admitted —
                            # even by the SAME stream, resumed after a
                            # disconnect) after this step was
                            # dispatched: its token is the one-deep
                            # pipeline's wasted extra
                            continue
                        if st.cancelled:
                            # consumer gone: free the slot (and its
                            # pages — full ones donate to the radix
                            # cache) AND retire the stream (parking
                            # resumables, with their KV exported for
                            # attach-resume)
                            export_kv(st)
                            release_pages(st)
                            self._detach_locked(st)
                            clear_slot(i)
                            continue
                        if was_forced:
                            continue  # resumed-prompt feed, no emission
                        tok = int(toks[i])
                        lp = float(lps[i])
                        if not np.isfinite(lp):
                            # poisoned output: THIS slot's logits went
                            # non-finite.  The batched step's math is
                            # row-independent, so co-batched slots are
                            # untouched — retire only the offender.
                            quarantined.append((i, st))
                            # poisoned KV must never enter the radix
                            # cache: free without donating
                            release_pages(st, insert=False)
                            clear_slot(i)
                            continue
                        if st.emitted < st.max_tokens:
                            st.history.append((tok, lp))
                            st.queue.put(("tok", tok, lp))
                            st.emitted += 1
                            self._tokens_total += 1
                        if st.emitted >= st.max_tokens or (
                            st.eos_id is not None and tok == st.eos_id
                        ):
                            finished.append((st, i))
                for i, st in quarantined:
                    with self._cond:
                        self._quarantined += 1
                    self._fail(st, SlotQuarantined(
                        "generation produced non-finite logits after {} "
                        "emitted tokens; its slot was quarantined (co-"
                        "batched generations are unaffected)".format(
                            st.emitted)), epoch)
                for st, i in finished:
                    finish(st, i)
            inflight = current

        # closed: fail whatever is still queued or running
        err = SchedulerClosed("scheduler is shut down")
        if inflight is not None:
            for i, st, _, _ in inflight[2]:
                if slots[i] is st:
                    slots[i] = None
                    self._fail(st, err, epoch)
        for st in slots:
            if st is not None:
                self._fail(st, err, epoch)
        for st in pending:
            self._fail(st, err, epoch)
