"""tpuserver — an in-process, TPU-native inference serving runtime.

Plays the role the reference's ``triton_c_api`` backend plays (reference
client_backend/triton_c_api/triton_loader.h:85-115: dlopen'd in-process
``libtritonserver.so``): a full KServe-v2 server the client stack can talk to
— over real HTTP and gRPC frontends or via direct in-process calls — without
any external deployment.  Models execute as jitted JAX computations on
whatever ``jax.devices()`` provides (TPU in production, CPU in tests), so the
same runtime serves both the test suite and the TPU benchmarks.
"""

from tpuserver.core import InferenceServer, JaxModel, Model, TensorSpec

__all__ = ["InferenceServer", "JaxModel", "Model", "TensorSpec"]
