"""tpuserver — an in-process, TPU-native inference serving runtime.

Plays the role the reference's ``triton_c_api`` backend plays (reference
client_backend/triton_c_api/triton_loader.h:85-115: dlopen'd in-process
``libtritonserver.so``): a full KServe-v2 server the client stack can talk to
— over real HTTP and gRPC frontends or via direct in-process calls — without
any external deployment.  Models execute as jitted JAX computations on
whatever ``jax.devices()`` provides (TPU in production, CPU in tests), so the
same runtime serves both the test suite and the TPU benchmarks.
"""

from tpuserver.core import InferenceServer, JaxModel, Model, TensorSpec


def enable_compile_cache(path=None):
    """Point jax's persistent compilation cache at ``path`` (default
    ``~/.cache/tpuserver-xla``).  On a tunneled chip a conv-net compile
    costs minutes; the cache makes every later process start hot.  Safe
    to call before or after jax import, best before first compile."""
    import os

    import jax

    if path is None:
        path = os.environ.get("TPUSERVER_XLA_CACHE") or os.path.join(
            os.path.expanduser("~"), ".cache", "tpuserver-xla"
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path


__all__ = [
    "InferenceServer", "JaxModel", "Model", "TensorSpec",
    "enable_compile_cache",
]
