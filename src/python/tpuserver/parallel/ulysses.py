"""Ulysses-style sequence parallelism: exact attention over a sequence
sharded on the ``sp`` mesh axis via head redistribution.

Where ring attention keeps the sequence sharded and rotates K/V blocks
around the ring (neighbor ICI traffic, O(sp) steps), the all-to-all
strategy re-shards ONCE: an ``all_to_all`` trades the sequence shards
for head shards, every device then runs blockwise online-softmax
attention over the FULL sequence for its subset of heads, and a second
``all_to_all`` restores sequence sharding.  Communication is two
all-to-alls regardless of sequence length — the better trade when heads
divide evenly across the axis and the per-device activations fit in HBM.

The per-shard attention reuses ring's flash-attention fold over fixed
K/V blocks (fp32 accumulation), so the [T, T] score matrix never
materializes here either.

Used inside ``shard_map`` like :func:`ring_attention`; with sp=1 both
all-to-alls are identities and this is plain blockwise attention.
"""

import jax.numpy as jnp
from jax import lax

from tpuserver.parallel.ring import _fold_block


def _blockwise_attention(q, k, v, scale, causal, block_size=512):
    """Full-sequence exact attention via the online-softmax fold.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D].  K/V are folded in
    ``block_size`` chunks so peak memory is O(Tq * block_size), not
    O(Tq * Tk).
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    qf = q.astype(jnp.float32)
    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)
    q_pos = jnp.arange(Tq)
    for start in range(0, Tk, block_size):  # static unroll at trace time
        stop = min(start + block_size, Tk)
        k_pos = start + jnp.arange(stop - start)
        o, m, l = _fold_block(
            qf, k[:, start:stop], v[:, start:stop], o, m, l, q_pos, k_pos,
            scale, causal,
        )
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def _expand_heads(x, repeat):
    """[B, T, Hkv, D] -> [B, T, Hkv*repeat, D] (GQA head replication)."""
    if repeat == 1:
        return x
    b, t, h, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, t, h, repeat, d)
    ).reshape(b, t, h * repeat, d)


def ulysses_attention(
    q, k, v, axis_name=None, causal=True, scale=None, kv_repeat=1,
    block_size=512):
    """Exact attention with q/k/v sequence-sharded on ``axis_name``.

    q: [B, T_local, H, D]; k, v: [B, T_local, H_kv, D] with
    ``H == H_kv * kv_repeat`` (pass ``kv_repeat > 1`` for GQA so the
    all-to-alls move the UNexpanded kv heads — expansion happens after
    redistribution when the kv head count allows it).  H must be
    divisible by the ``axis_name`` axis size.  Outside shard_map
    (axis_name=None) this is plain blockwise attention.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if axis_name is None:
        return _blockwise_attention(
            q, _expand_heads(k, kv_repeat), _expand_heads(v, kv_repeat),
            scale, causal, block_size)
    sp = lax.axis_size(axis_name)
    if sp == 1:
        return _blockwise_attention(
            q, _expand_heads(k, kv_repeat), _expand_heads(v, kv_repeat),
            scale, causal, block_size)
    heads = q.shape[2]
    if heads % sp != 0:
        raise ValueError(
            "ulysses attention needs heads ({}) divisible by the '{}' "
            "axis size ({}); use ring_attention otherwise".format(
                heads, axis_name, sp
            )
        )
    # expand kv heads only as far as divisibility by sp requires; the
    # rest of the GQA replication happens after the all_to_all so the
    # wire carries as few kv copies as possible
    kv_heads = k.shape[2]
    pre = 1
    while (kv_heads * pre) % sp != 0:
        pre += 1
    if pre > kv_repeat:
        raise ValueError(
            "kv heads ({}) times kv_repeat ({}) must be divisible by "
            "the '{}' axis size ({})".format(
                kv_heads, kv_repeat, axis_name, sp
            )
        )
    post = kv_repeat // pre
    if (kv_repeat % pre) != 0:
        # uneven split: fall back to full pre-expansion
        pre, post = kv_repeat, 1
    k = _expand_heads(k, pre)
    v = _expand_heads(v, pre)

    # [B, T/sp, H, D] -> [B, T, H/sp, D]: trade sequence shards for head
    # shards (tiled all_to_all splits dim 2 across the axis and
    # concatenates the received pieces along dim 1)
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    out = _blockwise_attention(
        q, _expand_heads(k, post), _expand_heads(v, post), scale, causal,
        block_size)
    # [B, T, H/sp, D] -> [B, T/sp, H, D]: restore sequence sharding
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )
