"""TPU parallelism toolkit for the serving runtime and its model zoo.

The reference client has no model parallelism (SURVEY.md §2.7) — but this
framework serves models *on* TPU, so scale-out is first-class here:

- ``mesh``: device-mesh construction and named-axis sharding rules
  (``dp`` data / ``sp`` sequence / ``tp`` tensor) for ``jax.jit`` /
  ``shard_map`` programs.
- ``ring``: ring attention — sequence/context parallelism over the ``sp``
  axis using ``lax.ppermute`` so long contexts scale with the mesh while
  K/V blocks ride the ICI ring.
"""

from tpuserver.parallel.mesh import (  # noqa: F401
    MeshConfig,
    make_mesh,
    mesh_factorize,
    named_sharding,
    shard_params,
)
from tpuserver.parallel.ring import ring_attention  # noqa: F401
from tpuserver.parallel.ulysses import ulysses_attention  # noqa: F401
