"""Device-mesh construction and sharding-rule helpers.

Axes (in mesh order):

- ``dp``  — data parallel: batch dimension; gradients reduced with ``psum``
            inserted by XLA from the sharded ``jit``.
- ``sp``  — sequence/context parallel: the time dimension of activations;
            attention runs as a ``ppermute`` ring (see ``tpuserver.parallel.
            ring``).
- ``tp``  — tensor parallel: the hidden/head dimension of weights, Megatron
            column/row split expressed purely as ``NamedSharding`` — XLA
            inserts the all-reduces.

On real hardware callers should order ``jax.devices()`` so ``tp`` lands on
the innermost (fastest ICI) axis; ``mesh_factorize`` puts the largest factor
on ``tp`` for exactly that reason.
"""

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    dp: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self):
        return self.dp * self.sp * self.tp


def mesh_factorize(n_devices, want_sp=True):
    """Pick a (dp, sp, tp) factorization of ``n_devices``.

    tp gets the largest power-of-two factor up to 8 (tp collectives are the
    most latency-sensitive, so they belong on the innermost ICI axis), then
    sp (if requested) so long-context paths are exercised, then dp.
    """
    rem = n_devices
    tp = 1
    while tp < 8 and rem % 2 == 0:
        tp *= 2
        rem //= 2
    sp = 1
    if want_sp and rem % 2 == 0:
        sp = 2
        rem //= 2
    elif want_sp and rem == 1 and tp >= 4:
        # steal a factor from tp so the ring path is exercised
        tp //= 2
        sp = 2
    dp = rem
    assert dp * sp * tp == n_devices
    return MeshConfig(dp=dp, sp=sp, tp=tp)


def make_mesh(config=None, devices=None):
    """Build a ``Mesh`` with axes (dp, sp, tp) from a MeshConfig."""
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = mesh_factorize(len(devices))
    if config.size > len(devices):
        raise ValueError(
            "mesh {} needs {} devices, have {}".format(
                config, config.size, len(devices)
            )
        )
    arr = np.asarray(devices[: config.size]).reshape(
        config.dp, config.sp, config.tp
    )
    return Mesh(arr, AXES)


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


def shard_params(params, rules, mesh):
    """Apply a pytree of PartitionSpecs to a matching pytree of arrays."""
    return jax.tree_util.tree_map(
        lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec)),
        params,
        rules,
    )
