"""Ring attention: exact attention over a sequence sharded on the ``sp``
mesh axis.

Each device holds one block of Q/K/V along time.  K/V blocks rotate around
the ``sp`` ring with ``lax.ppermute`` while every device folds the visiting
block into a numerically-stable online softmax (the flash-attention
recurrence), so the full [T, T] score matrix never materializes and the
communication is pure neighbor traffic on ICI.  With sp=1 this degrades to a
single fold — plain fused attention.

Used inside ``shard_map`` (see ``tpuserver.models.llama``); everything here
is traced once per shape, control flow is ``lax.fori_loop``.
"""

import jax
import jax.numpy as jnp
from jax import lax


def _fold_block(q, k, v, o, m, l, q_pos, k_pos, scale, causal):
    """One online-softmax fold of a visiting K/V block.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; o: [B, Tq, H, D];
    m, l: [B, H, Tq] running max / normalizer; positions are global indices
    used for causal masking across blocks.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        mask = k_pos[None, None, None, :] > q_pos[None, None, :, None]
        s = jnp.where(mask, -jnp.inf, s)
    m_blk = jnp.max(s, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m, m_blk)
    # exp(-inf - -inf) guards: where a row saw no valid key yet, m_new stays
    # -inf and the correction factor must be 0, not nan.
    alpha = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m - m_new))
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name=None, causal=True, scale=None):
    """Exact (optionally causal) attention; when ``axis_name`` is given the
    time axis is assumed sharded over that mesh axis and K/V ride the ring.

    q, k, v: [B, T_local, H, D] (kv heads already expanded to H).
    Returns [B, T_local, H, D] in q.dtype.
    """
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if scale is None:
        scale = 1.0 / (D**0.5)
    qf = q.astype(jnp.float32)

    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    m = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    if axis_name is None:
        q_pos = jnp.arange(Tq)
        k_pos = jnp.arange(Tk)
        o, m, l = _fold_block(qf, k, v, o, m, l, q_pos, k_pos, scale, causal)
    else:
        sp = lax.psum(1, axis_name)
        my = lax.axis_index(axis_name)
        q_pos = my * Tq + jnp.arange(Tq)
        perm = [(i, (i + 1) % sp) for i in range(sp)]

        def body(step, carry):
            o, m, l, k_cur, v_cur = carry
            # after `step` rotations we hold the block originally on
            # device (my - step) mod sp
            blk = (my - step) % sp
            k_pos = blk * Tk + jnp.arange(Tk)
            o, m, l = _fold_block(
                qf, k_cur, v_cur, o, m, l, q_pos, k_pos, scale, causal
            )
            k_nxt = lax.ppermute(k_cur, axis_name, perm)
            v_nxt = lax.ppermute(v_cur, axis_name, perm)
            return o, m, l, k_nxt, v_nxt

        o, m, l, _, _ = lax.fori_loop(0, sp, body, (o, m, l, k, v))

    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (shouldn't happen)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)
