"""Hand-tiled Pallas TPU kernels for the hot ops (SURVEY §7's "pallas
for the rest" tier); XLA-composed fallbacks everywhere else."""

from tpuserver.ops.flash import (  # noqa: F401
    decode_attention,
    flash_attention,
)
