"""Roofline accounting for the llama serving path: analytic FLOP/byte
counts per config plus a chip-spec table, so benchmarks can report MFU
(achieved FLOP/s over the chip's peak) and MBU (achieved HBM bytes/s
over peak bandwidth) instead of bare tokens/sec.

No reference counterpart — the reference is a client-side load
generator; this is the TPU-native framework's own proof-of-performance
layer.  Peak numbers are the published per-chip specs (bf16 matmul peak
and HBM bandwidth); MFU follows the standard convention of counting
only algorithmic matmul/attention FLOPs (2*m*n*k per matmul), no
rematerialization credit.
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    peak_bf16_flops: float  # FLOP/s
    hbm_bandwidth: float    # bytes/s
    hbm_bytes: int


# published single-chip specs, keyed by jax Device.device_kind
CHIP_SPECS = {
    "TPU v4": ChipSpec("v4", 275e12, 1228e9, 32 << 30),
    "TPU v5 lite": ChipSpec("v5e", 197e12, 819e9, 16 << 30),
    "TPU v5e": ChipSpec("v5e", 197e12, 819e9, 16 << 30),
    "TPU v5": ChipSpec("v5p", 459e12, 2765e9, 95 << 30),
    "TPU v5p": ChipSpec("v5p", 459e12, 2765e9, 95 << 30),
    "TPU v6 lite": ChipSpec("v6e", 918e12, 1640e9, 32 << 30),
    "TPU v6e": ChipSpec("v6e", 918e12, 1640e9, 32 << 30),
}


def chip_spec(device=None):
    """Spec for ``device`` (default: jax's first device), or None when
    the platform isn't a known TPU (CPU test meshes)."""
    import jax

    if device is None:
        devices = jax.devices()
        if not devices:
            return None
        device = devices[0]
    return CHIP_SPECS.get(getattr(device, "device_kind", ""))


def param_count(cfg):
    """Analytic parameter count of ``llama.init_params`` for ``cfg``."""
    hd = cfg.head_dim
    per_layer = (
        cfg.d_model * cfg.n_heads * hd          # wq
        + 2 * cfg.d_model * cfg.n_kv_heads * hd  # wk, wv
        + cfg.n_heads * hd * cfg.d_model        # wo
        + 3 * cfg.d_model * cfg.d_ff            # gate, up, down
        + 2 * cfg.d_model                       # norms
    )
    return (
        2 * cfg.vocab * cfg.d_model             # embed + lm_head
        + cfg.n_layers * per_layer
        + cfg.d_model                           # final norm
    )


def matmul_params(cfg):
    """Params that participate in per-token matmuls (excludes the embed
    gather, which costs a lookup, not FLOPs; includes lm_head)."""
    return param_count(cfg) - cfg.vocab * cfg.d_model


def decode_flops_per_token(cfg, ctx_len):
    """Forward FLOPs to decode ONE token at context length ``ctx_len``.

    2 FLOPs per matmul parameter, plus attention: per layer the single
    query attends over ctx_len cached K/V rows — QK^T and PV are each
    2 * ctx_len * n_heads * head_dim FLOPs.
    """
    attn = cfg.n_layers * 4 * ctx_len * cfg.n_heads * cfg.head_dim
    return 2 * matmul_params(cfg) + attn


def prefill_flops(cfg, seq_len):
    """Forward FLOPs for a causal prefill of ``seq_len`` tokens.

    Matmuls are linear in tokens; causal attention sums to
    ~seq_len^2/2 score rows per head per layer (QK^T + PV).
    """
    matmul = 2 * matmul_params(cfg) * seq_len
    attn = cfg.n_layers * 4 * (seq_len * seq_len // 2) * (
        cfg.n_heads * cfg.head_dim
    )
    return matmul + attn


def decode_bytes_per_token(cfg, ctx_len, dtype_bytes=2,
                           weight_bytes_per_param=None):
    """HBM bytes touched to decode one token: every matmul weight is
    read once, the valid KV prefix is read, and one KV row is written.
    (The decode roofline — at batch 1 this is bandwidth-bound, so
    tokens/sec * bytes/token vs peak bandwidth is the honest
    utilization number.)  ``weight_bytes_per_param`` overrides the
    weight-read cost (1 for int8-quantized serving; KV stays
    ``dtype_bytes``)."""
    wb = (
        weight_bytes_per_param
        if weight_bytes_per_param is not None
        else dtype_bytes
    )
    weights = matmul_params(cfg) * wb
    kv_row = 2 * cfg.n_kv_heads * cfg.head_dim * dtype_bytes
    kv = cfg.n_layers * kv_row * (ctx_len + 1)
    return weights + kv


def bert_encoder_flops(seq_len=128, d_model=768, n_layers=12, d_ff=3072):
    """Forward FLOPs of one BERT-base-shaped encoder pass (the config-4
    ensemble's device stage): per layer 4 attention projections + the
    2 MLP matmuls (2*m*n*k each) + QK^T/PV attention, plus the pooler."""
    per_layer = (
        2 * seq_len * (4 * d_model * d_model + 2 * d_model * d_ff)
        + 4 * seq_len * seq_len * d_model
    )
    return n_layers * per_layer + 2 * d_model * d_model


def mfu(flops, seconds, spec):
    """Achieved-over-peak FLOP ratio (None without a known chip)."""
    if spec is None or seconds <= 0:
        return None
    return flops / seconds / spec.peak_bf16_flops


def mbu(nbytes, seconds, spec):
    """Achieved-over-peak HBM bandwidth ratio."""
    if spec is None or seconds <= 0:
        return None
    return nbytes / seconds / spec.hbm_bandwidth
