"""Pallas flash-attention forward kernel for TPU.

The hot op of the llama serving/training paths, hand-tiled for the MXU:
the grid walks (batch*heads, query blocks, K/V blocks) with the K/V
block dimension innermost, so VMEM only ever holds one [block_q, D]
query tile and one [block_k, D] K/V tile — sequence length is bounded
by HBM, not VMEM.  The online-softmax state (running max, normalizer,
output accumulator) lives in VMEM scratch carried across the K/V grid
steps; accumulation is fp32 (MXU-native via preferred_element_type)
regardless of input dtype, and causal query blocks skip fully-masked
K/V blocks via predication.

On non-TPU backends the kernel runs in interpret mode (same math,
Python-level execution) so tests pin it against the dense reference on
the CPU mesh; on TPU it compiles through Mosaic.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _online_softmax_fold(s, m_scr, l_scr, acc_scr, pv):
    """One block of the flash recurrence over scores ``s`` [rows, bk].

    Updates the carried (m, l, acc) scratch; ``pv(p)`` supplies the
    probability-value product in whatever block layout the kernel uses.
    Fully-masked rows keep m == -inf, and exp(-inf - -inf) is nan, so
    the shift is pinned to a finite value there.
    """
    m = m_scr[:, 0]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - shift[:, None])
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - shift), 0.0)
    l_scr[:, 0] = l_scr[:, 0] * alpha + jnp.sum(p, axis=-1)
    acc_scr[:] = acc_scr[:] * alpha[:, None] + pv(p)
    m_scr[:, 0] = m_new


def _fold_finish(o_ref, m_scr, l_scr, acc_scr):
    """Normalize the carried accumulator into the output block."""
    del m_scr
    l = l_scr[:, 0]
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[:] = (acc_scr[:] / l[:, None]).astype(o_ref.dtype)


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, causal,
    block_q, block_k):
    """One (batch*head, q-block, k-block) program.

    q_ref: [block_q, D]; k_ref/v_ref: [block_k, D]; o_ref: [block_q, D];
    scratch m/l: [block_q, 1] fp32, acc: [block_q, D] fp32 — carried
    across the (sequential) k-block grid dimension.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: K/V blocks wholly above the diagonal contribute nothing
    live = (
        ki * block_k <= qi * block_q + (block_q - 1)
        if causal
        else True
    )

    @pl.when(live)
    def _fold():
        # keep the matmul operands in the INPUT dtype: bf16 x bf16 with
        # fp32 accumulation is the MXU's native full-rate mode — an
        # explicit fp32 upcast before the dot would halve the peak.
        # The softmax state stays fp32 (preferred_element_type).
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(k_pos <= q_pos, s, -jnp.inf)
        _online_softmax_fold(
            s, m_scr, l_scr, acc_scr,
            lambda p: jnp.dot(
                p.astype(v.dtype), v,
                preferred_element_type=jnp.float32))

    @pl.when(ki == nk - 1)
    def _finish():
        _fold_finish(o_ref, m_scr, l_scr, acc_scr)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q, k, v, causal=True, scale=None, block_q=128, block_k=128,
    interpret=None):
    """Exact attention, q/k/v [B, T, H, D] -> [B, T, H, D].

    Drop-in for the XLA attention paths; T must be divisible by
    ``block_q`` and ``block_k`` (pick smaller blocks for short or odd
    sequences).  ``interpret=None`` auto-selects interpret mode off-TPU.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, t_kv)
    if t % block_q or t_kv % block_k:
        raise ValueError(
            "sequence lengths ({}, {}) must divide by block sizes "
            "({}, {})".format(t, t_kv, block_q, block_k))

    # [B, T, H, D] -> [B*H, T, D]: one grid row per (batch, head)
    qh = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kh = k.transpose(0, 2, 1, 3).reshape(b * h, t_kv, d)
    vh = v.transpose(0, 2, 1, 3).reshape(b * h, t_kv, d)

    kernel = functools.partial(
        _attn_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, t // block_q, t_kv // block_k),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qh, kh, vh)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _decode_kernel(
    len_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale,
    block_k, n_rep):
    """One (batch, k-block) program of single-query decode attention.

    len_ref: scalar-prefetch [batch] int32 valid lengths; q_ref: [H, D]
    (every query head of this batch row); k_ref/v_ref: [block_k, Hkv, D]
    cache slices; scratch m/l: [H, 1] fp32, acc: [H, D] fp32 carried
    across k blocks.  GQA replication happens on the in-VMEM block only.
    """
    b = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)
    length = len_ref[b]
    heads = q_ref.shape[0]
    block = k_ref.shape[0]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip blocks entirely past the valid cache prefix
    @pl.when(ki * block_k < length)
    def _fold():
        q = q_ref[:].astype(jnp.float32) * scale          # [H, D]
        k = k_ref[:].astype(jnp.float32)                  # [bk, Hkv, D]
        v = v_ref[:].astype(jnp.float32)
        if n_rep > 1:  # GQA: expand kv heads inside VMEM only
            k = jnp.repeat(k, n_rep, axis=1)              # [bk, H, D]
            v = jnp.repeat(v, n_rep, axis=1)
        # Mosaic-friendly batched vec-mat: elementwise multiply +
        # reduce on the VPU (the head-batched dot_general does not lower)
        s = jnp.sum(q[None, :, :] * k, axis=-1).T  # [H, bk]
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (heads, block), 1)
        s = jnp.where(k_pos < length, s, -jnp.inf)
        _online_softmax_fold(
            s, m_scr, l_scr, acc_scr,
            lambda p: jnp.sum(p.T[:, :, None] * v, axis=0))

    @pl.when(ki == nk - 1)
    def _finish():
        _fold_finish(o_ref, m_scr, l_scr, acc_scr)


@functools.partial(
    jax.jit, static_argnames=("scale", "block_k", "interpret"))
def decode_attention(
    q, k_cache, v_cache, lengths, scale=None, block_k=256,
    interpret=None):
    """Single-token decode attention over a padded KV cache.

    q: [B, H, D] (the current token's queries); k_cache/v_cache:
    [B, S, Hkv, D] with valid prefix ``lengths`` [B] int32; GQA
    replication (H = Hkv * n_rep) happens on in-VMEM blocks only — the
    expanded cache never exists in HBM.  Returns [B, H, D].
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, h, d = q.shape
    s = k_cache.shape[1]
    h_kv = k_cache.shape[2]
    if h % h_kv:
        raise ValueError(
            "query heads ({}) must be a multiple of kv heads ({})".format(
                h, h_kv))
    n_rep = h // h_kv
    block_k = min(block_k, s)
    if s % block_k:
        raise ValueError(
            "cache length {} must divide by block_k {}".format(s, block_k))

    def _kv_index(b, ki, len_ref):
        # clamp dead iterations (past the valid prefix) onto the last
        # live block: Pallas elides the re-fetch of an already-resident
        # block, so padded cache tail bytes are never DMA'd from HBM
        live_blocks = jax.lax.div(
            len_ref[b] + (block_k - 1), block_k)
        ki_eff = jnp.minimum(ki, jnp.maximum(live_blocks - 1, 0))
        return (b, ki_eff, 0, 0)

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, n_rep=n_rep)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, s // block_k),
        in_specs=[
            pl.BlockSpec((None, h, d), lambda b, ki, *refs: (b, 0, 0)),
            pl.BlockSpec((None, block_k, h_kv, d), _kv_index),
            pl.BlockSpec((None, block_k, h_kv, d), _kv_index),
        ],
        out_specs=pl.BlockSpec(
            (None, h, d), lambda b, ki, *refs: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), q, k_cache, v_cache)
    return out
