"""Int8 weight-only quantization for serving (bf16 activations).

The capability that lets the BASELINE.md-named flagship (Llama-3-8B,
16 GB of bf16 weights) serve on a single 16 GB-HBM v5e chip: weights are
stored int8 with per-output-channel symmetric scales (~8 GB), activations
stay bf16, and each matmul upcasts its weight tile in-register — XLA
fuses the ``convert`` into the dot so HBM traffic is the int8 bytes, not
a dequantized copy.  This is the TPU-native analogue of the GPU serving
stacks' W8A16 path; the reference client repo has no counterpart (it
measures servers; this repo also has to *be* one).

Quantized tensors are plain pytree dicts ``{"q": int8[...,-1],
"s": f32[out]}`` so they ride jit/sharding like any other param leaf.
"""

import jax.numpy as jnp


def quantize_int8(w, axis=0):
    """Per-output-channel symmetric int8 quantization of a 2-D weight.

    ``axis`` is the *reduction* (input) axis — scales are computed per
    channel of the other (output) axis, so the matmul result can be
    rescaled per output column with one broadcast multiply.
    Returns ``{"q": int8, "s": float32[out]}``.
    """
    if w.ndim != 2:
        raise ValueError(
            "quantize_int8 expects a 2-D weight, got shape {}".format(
                tuple(w.shape)
            )
        )
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.reshape(-1).astype(jnp.float32)}


def is_quantized(w):
    return isinstance(w, dict) and "q" in w and "s" in w


def matmul(x, w):
    """``x @ w`` for a plain or int8-quantized weight.

    For quantized weights the int8 tile upcasts to the activation dtype
    inside the fused dot (HBM reads stay int8) and the per-channel scale
    applies to the f32-accumulated result.
    """
    if not is_quantized(w):
        return x @ w
    y = x @ w["q"].astype(x.dtype)
    return (y * w["s"].astype(x.dtype)).astype(x.dtype)


def gather_rows(w, idx):
    """Row gather (embedding lookup) from a plain or per-row-quantized
    table (``quantize_int8(w, axis=1)``: one scale per row)."""
    if not is_quantized(w):
        return w[idx]
    rows = w["q"][idx].astype(jnp.bfloat16)
    return rows * w["s"][idx].astype(jnp.bfloat16)[..., None]


def quantized_bytes(w):
    """HBM bytes a (possibly quantized) weight leaf occupies."""
    if is_quantized(w):
        return w["q"].size + w["s"].size * 4
    return w.size * w.dtype.itemsize
