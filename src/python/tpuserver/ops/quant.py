"""Int8 weight-only quantization for serving (bf16 activations).

The capability that lets the BASELINE.md-named flagship (Llama-3-8B,
16 GB of bf16 weights) serve on a single 16 GB-HBM v5e chip: weights are
stored int8 with per-output-channel symmetric scales (~8 GB), activations
stay bf16, and each matmul upcasts its weight tile in-register — XLA
fuses the ``convert`` into the dot so HBM traffic is the int8 bytes, not
a dequantized copy.  This is the TPU-native analogue of the GPU serving
stacks' W8A16 path; the reference client repo has no counterpart (it
measures servers; this repo also has to *be* one).

Quantized tensors are plain pytree dicts ``{"q": int8[...,-1],
"s": f32[out]}`` so they ride jit/sharding like any other param leaf.
"""

import jax.numpy as jnp


def quantize_int8(w, axis=0):
    """Per-output-channel symmetric int8 quantization of a 2-D weight.

    ``axis`` is the *reduction* (input) axis — scales are computed per
    channel of the other (output) axis, so the matmul result can be
    rescaled per output column with one broadcast multiply.
    Returns ``{"q": int8, "s": float32[out]}``.
    """
    if w.ndim != 2:
        raise ValueError(
            "quantize_int8 expects a 2-D weight, got shape {}".format(
                tuple(w.shape)
            )
        )
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(wf / scale), -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale.reshape(-1).astype(jnp.float32)}


def is_quantized(w):
    return isinstance(w, dict) and "q" in w and "s" in w


def matmul(x, w):
    """``x @ w`` for a plain or int8-quantized weight.

    Two quantized regimes, selected statically by the activation shape:

    - **decode-scale** (few rows): bandwidth-bound — the int8 weight
      upcasts to the activation dtype in the dot, HBM reads stay int8,
      and the per-channel scale applies to the accumulated result.
    - **prefill-scale** (``rows >= 8``): compute-bound — the bf16-x-int8
      upcast path runs the MXU at ~7% MFU (measured on v5e at T=2048),
      so activations quantize dynamically per row to int8 and the dot
      runs int8 x int8 -> int32 on the MXU's double-rate integer path:
      73% MFU measured, FASTER than the bf16 matmul (68%).

    The regime test applies only to >=3-D activations, where axis -2 is
    the token axis.  For a 2-D activation (e.g. the lm_head input
    ``x[:, -1, :]`` of shape [B, D]) axis -2 is the *server-side batch*,
    and switching regimes with batch size would silently change the same
    request's logits numerics between a quiet and a loaded server.
    """
    if not is_quantized(w):
        return x @ w
    if x.ndim >= 3 and x.shape[-2] >= 8:
        return _w8a8_matmul(x, w)
    y = x @ w["q"].astype(x.dtype)
    return (y * w["s"].astype(x.dtype)).astype(x.dtype)


def _w8a8_matmul(x, w):
    """Dynamic per-row activation quantization + int8 MXU matmul.

    x: [..., rows, in]; w: {"q": int8 [in, out], "s": f32 [out]}.
    Accumulation is int32; the result rescales by (row scale x channel
    scale) in f32 before casting back to the activation dtype.

    TP cost note: the per-row amax reduces over the activation's LAST
    axis.  For row-parallel TP matmuls (llama's wo/w_down, whose inputs
    are column-split over tp) that axis is sharded, so GSPMD must insert
    one extra all-reduce(max) collective per matmul before the dot — a
    latency cost the decode-scale/weight-only path does not pay.  A
    shard-local scale (quantize per shard-row) would remove the
    collective at the price of shard-count-dependent numerics; until the
    w8a8 prefill speedup is re-verified at tp>1 on real hardware the
    collective is kept and documented (docs/benchmarking.md, "w8a8 under
    tensor parallelism").
    """
    from jax import lax

    xf = x.astype(jnp.float32)
    sx = jnp.maximum(
        jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0, 1e-8
    )
    xq = jnp.clip(jnp.round(xf / sx), -127, 127).astype(jnp.int8)
    y = lax.dot_general(
        xq, w["q"],
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (y.astype(jnp.float32) * sx * w["s"]).astype(x.dtype)


def gather_rows(w, idx, dtype=None):
    """Row gather (embedding lookup) from a plain or per-row-quantized
    table (``quantize_int8(w, axis=1)``: one scale per row).

    ``dtype`` is the dequantized row dtype — the model's configured
    activation dtype (``cfg.dtype``), so a float32-configured model gets
    a float32 residual stream instead of a silently-bf16 one.  Defaults
    to bfloat16 for callers without a config in hand."""
    if not is_quantized(w):
        return w[idx]
    dtype = jnp.bfloat16 if dtype is None else dtype
    rows = w["q"][idx].astype(dtype)
    return rows * w["s"][idx].astype(dtype)[..., None]


def quantized_bytes(w):
    """HBM bytes a (possibly quantized) weight leaf occupies."""
    if is_quantized(w):
        return w["q"].size + w["s"].size * 4
    return w.size * w.dtype.itemsize
