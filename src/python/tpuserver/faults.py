"""Named fault-injection points for chaos testing the serving stack.

Production code calls :func:`fire` at well-known points; by default that
is a no-op costing one dict check.  Chaos tests (tests/test_chaos.py,
tools/chaos_smoke.py) arm a point with :func:`install` or the
:class:`injected` context manager, and the next ``times`` passes through
it raise :class:`FaultInjected` (``mode="raise"``) or stall for
``delay`` seconds (``mode="sleep"``).  The recovery invariants the
scheduler and core promise — donated-cache rebuild, zero leaked slots,
typed errors to every consumer — are only trustworthy because these
hooks let tests force the failure paths on demand.

Registered injection points:

==================  ========================================================
``scheduler.step``   before each batched decode-step dispatch
                     (``mode="raise"`` = decode-step failure, the donated
                     cache/logits recovery path; ``mode="sleep"`` = slow
                     step, for deadline/overload pressure)
``scheduler.fetch``  before the device->host token transfer of a completed
                     step (host-transfer failure)
``scheduler.admit``  before a prefill-on-admit (admission failure: the
                     request fails, other slots keep decoding)
``core.shm_read``    before a shared-memory input read (shm read error)
==================  ========================================================

Env knob: ``TPUSERVER_FAULTS`` arms points at import time without code
changes, as a comma-separated list of ``name:mode[:times[:delay]]``
entries, e.g.::

    TPUSERVER_FAULTS="scheduler.step:raise:1,scheduler.fetch:sleep:-1:0.05"

``times=-1`` means unlimited.  :func:`clear` disarms.
"""

import os
import threading
import time

__all__ = [
    "FaultInjected", "fire", "install", "clear", "fired", "active",
    "injected", "load_env",
]


class FaultInjected(RuntimeError):
    """The error raised by an armed ``mode="raise"`` injection point."""

    def __init__(self, point):
        super().__init__("injected fault at '{}'".format(point))
        self.point = point


class _Fault:
    __slots__ = ("name", "mode", "remaining", "delay", "fired")

    def __init__(self, name, mode, times, delay):
        if mode not in ("raise", "sleep"):
            raise ValueError(
                "fault mode must be 'raise' or 'sleep' (got {!r})".format(
                    mode)
            )
        self.name = name
        self.mode = mode
        self.remaining = int(times)
        self.delay = float(delay)
        self.fired = 0


_lock = threading.Lock()
_points = {}  # name -> _Fault


def install(name, mode="raise", times=1, delay=0.0):
    """Arm injection point ``name``: the next ``times`` fires raise
    (``mode="raise"``) or sleep ``delay`` seconds (``mode="sleep"``).
    ``times=-1`` keeps the point armed until :func:`clear`."""
    fault = _Fault(name, mode, times, delay)
    with _lock:
        _points[name] = fault
    return fault


def clear(name=None):
    """Disarm one point (or all, when ``name`` is None)."""
    with _lock:
        if name is None:
            _points.clear()
        else:
            _points.pop(name, None)


def fired(name):
    """How many times point ``name`` has actually fired (0 if unarmed)."""
    with _lock:
        fault = _points.get(name)
        return fault.fired if fault is not None else 0


def active(name):
    """Whether point ``name`` is armed with fires remaining."""
    with _lock:
        fault = _points.get(name)
        return fault is not None and fault.remaining != 0


def fire(name):
    """The production-side hook: no-op unless ``name`` is armed.

    Raises :class:`FaultInjected` (mode ``raise``) or sleeps (mode
    ``sleep``) and decrements the point's remaining count.  The sleep
    happens OUTSIDE the registry lock so a slow point never blocks
    arming/disarming other points.
    """
    if not _points:  # fast path: nothing armed anywhere
        return
    with _lock:
        fault = _points.get(name)
        if fault is None or fault.remaining == 0:
            return
        if fault.remaining > 0:
            fault.remaining -= 1
        fault.fired += 1
        mode, delay = fault.mode, fault.delay
    if mode == "sleep":
        time.sleep(delay)
        return
    raise FaultInjected(name)


class injected:
    """Context manager: arm a point on enter, disarm on exit.

    >>> with faults.injected("scheduler.step"):
    ...     # the next decode step raises FaultInjected
    """

    def __init__(self, name, mode="raise", times=1, delay=0.0):
        self._name = name
        self._args = (mode, times, delay)
        self.fault = None

    def __enter__(self):
        self.fault = install(self._name, *self._args)
        return self.fault

    def __exit__(self, exc_type, exc, tb):
        clear(self._name)
        return False


def load_env(env=None):
    """Arm points from ``TPUSERVER_FAULTS`` (see module docstring)."""
    spec = (env if env is not None else os.environ).get(
        "TPUSERVER_FAULTS", "")
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                "TPUSERVER_FAULTS entry {!r} needs at least "
                "'name:mode'".format(entry)
            )
        name, mode = parts[0], parts[1]
        times = int(parts[2]) if len(parts) > 2 else 1
        delay = float(parts[3]) if len(parts) > 3 else 0.0
        install(name, mode=mode, times=times, delay=delay)


load_env()
