"""Named fault-injection points for chaos testing the serving stack.

Production code calls :func:`fire` at well-known points; by default that
is a no-op costing one dict check.  Chaos tests (tests/test_chaos.py,
tools/chaos_smoke.py) arm a point with :func:`install` or the
:class:`injected` context manager, and the next ``times`` passes through
it raise :class:`FaultInjected` (``mode="raise"``) or stall for
``delay`` seconds (``mode="sleep"``).  The recovery invariants the
scheduler and core promise — donated-cache rebuild, zero leaked slots,
typed errors to every consumer — are only trustworthy because these
hooks let tests force the failure paths on demand.

Registered injection points:

==========================  ================================================
``scheduler.step``           before each batched decode-step dispatch
                             (``mode="raise"`` = decode-loop death, the
                             supervised-restart path; ``mode="sleep"`` =
                             slow step, for deadline/overload pressure;
                             ``mode="hang"`` = a step stall long enough to
                             trip the hung-step watchdog; ``mode="nan"`` =
                             poison one slot's logits row with NaN — the
                             per-slot quarantine path.  For ``nan`` the
                             ``delay`` field is reused as the slot index
                             to poison)
``scheduler.fetch``          before the device->host token transfer of a
                             completed step (host-transfer failure —
                             handled as loop death / supervised restart)
``scheduler.admit``          before a prefill-on-admit (admission failure:
                             the request fails, other slots keep decoding)
``core.shm_read``            before a shared-memory input read
``http.generate_stream``     before each SSE event write of
                             ``/generate_stream`` (``raise`` = sever the
                             connection mid-stream, no terminal chunk —
                             drives client auto-resume end-to-end)
``grpc.stream_infer``        before each ModelStreamInfer response yield
                             (``raise`` = kill the bidi stream mid-flight)
==========================  ================================================

``install(..., skip=N)`` lets the first ``N`` passes through an armed
point succeed before it starts firing — the knob chaos tests use to
drop a connection *mid*-stream rather than before the first token.

Beyond the per-point actions above, every point accepts three
**gray-failure modes**: ``mode="slow"`` (every fire sleeps ``delay``
— a persistently degraded-but-alive replica), ``mode="jitter"`` (a
deterministic pseudo-random delay in ``[0, delay)`` from a seeded
LCG, so soaks replay exactly), and ``mode="partition"`` (the
half-open network shape: the connection is accepted and ``skip``
passes flow normally, then reads stall — no bytes, no error — until
:func:`clear`, or for ``delay`` seconds per fire when ``delay > 0``).
All stay armed until :func:`clear` and combine with ``@scope`` to
degrade one replica of a fleet — the traffic shapes the router's
gray-failure ejection defends against (docs/resilience.md
"Tail-latency defense").

**Scopes** (multi-replica chaos): several in-process servers share this
process-global registry, so a point armed with ``scope="replica-b"``
fires only for the server constructed with
``InferenceServer(fault_scope="replica-b")`` — chaos tests can kill one
replica of an in-process multi-server harness while its pool siblings
stay healthy.  A point armed without a scope fires for every replica
(the historical behavior).

Env knob: ``TPUSERVER_FAULTS`` arms points at import time without code
changes, as a comma-separated list of ``name[@scope]:mode[:times[:delay]]``
entries, e.g.::

    TPUSERVER_FAULTS="scheduler.step:raise:1,core.shm_read@b:raise:-1"

``times=-1`` means unlimited.  :func:`clear` disarms.
"""

import os
import threading
import time
import zlib

__all__ = [
    "FaultInjected", "POINTS", "fire", "install", "clear", "fired",
    "active", "injected", "load_env",
]

#: The fault-point registry — the single source of truth for injection
#: point names.  tpulint rule R6 statically checks that every
#: ``faults.fire("<name>")`` site in the server tree uses exactly one
#: registered name (a typo'd point silently never fires), and
#: tests/test_static_analysis.py checks the fault table in
#: docs/resilience.md against these keys, so docs, registry, and code
#: cannot drift apart.  Adding an injection point = add the fire()
#: site, register it here, and document it in the resilience table.
POINTS = {
    "scheduler.step": (
        "before each batched decode-step dispatch (raise = loop death "
        "/ supervised restart; sleep = slow step; hang = stall past "
        "the watchdog deadline; nan = poison slot int(delay)'s logits "
        "row / quarantine)"),
    "scheduler.fetch": (
        "before the device->host token transfer of a completed step "
        "(raise = loop death / supervised restart)"),
    "scheduler.admit": (
        "before a prefill-on-admit (admission failure: the request "
        "fails, other slots keep decoding)"),
    "core.shm_read": "before a shared-memory input read",
    "http.generate_stream": (
        "before each /generate_stream SSE event write (raise = sever "
        "the connection mid-stream — drives client auto-resume)"),
    "grpc.stream_infer": (
        "before each ModelStreamInfer response yield (raise = kill "
        "the bidi stream mid-flight)"),
}


class FaultInjected(RuntimeError):
    """The error raised by an armed ``mode="raise"`` injection point."""

    def __init__(self, point):
        super().__init__("injected fault at '{}'".format(point))
        self.point = point


#: LCG constants for ``mode="jitter"`` (glibc's rand() multiplier /
#: increment over a 2^31 modulus): a tiny, dependency-free generator
#: whose whole point is determinism — the same arming replays the exact
#: same delay sequence, so a gray-failure soak is reproducible run to
#: run (a ``random``-based jitter would not be, and seeding the global
#: RNG from a fault hook would perturb every other consumer).
_LCG_A = 1103515245
_LCG_C = 12345
_LCG_M = 1 << 31


class _Fault:
    __slots__ = ("name", "mode", "remaining", "delay", "fired", "scope",
                 "skip", "lcg")

    def __init__(self, name, mode, times, delay, scope=None, skip=0):
        if mode not in ("raise", "sleep", "hang", "nan", "slow",
                        "jitter", "partition"):
            raise ValueError(
                "fault mode must be 'raise', 'sleep', 'hang', 'nan', "
                "'slow', 'jitter' or 'partition' (got {!r})".format(mode)
            )
        self.name = name
        self.mode = mode
        # 'slow', 'jitter' and 'partition' model a DEGRADED-but-alive
        # replica (the gray-failure shape): a latency fault that
        # disarmed itself after N fires would read as a recovered
        # replica mid-soak, so all are persistent until clear()
        # regardless of ``times``
        self.remaining = (-1 if mode in ("slow", "jitter", "partition")
                          else int(times))
        self.delay = float(delay)
        self.fired = 0
        self.scope = scope
        self.skip = int(skip)
        # jitter state: seeded from the point identity so two scoped
        # armings of the same point draw distinct but stable sequences
        self.lcg = zlib.crc32(
            "{}@{}".format(name, scope or "").encode("utf-8")) % _LCG_M


_lock = threading.Lock()
_points = {}  # (name, scope) -> _Fault


def install(name, mode="raise", times=1, delay=0.0, scope=None, skip=0):
    """Arm injection point ``name``: the next ``times`` fires raise
    (``mode="raise"``), sleep ``delay`` seconds inside fire()
    (``mode="sleep"``), or hand the site an action to implement —
    ``mode="nan"`` poisons the logits row of slot ``int(delay)`` and
    ``mode="hang"`` stalls ``delay`` seconds inside the site's
    watchdog-heartbeat window (see :func:`fire`).  ``times=-1`` keeps
    the point armed until :func:`clear`.  ``skip`` lets the first N
    passes through succeed before firing starts (mid-stream chaos).
    With a ``scope``, only :func:`fire` calls carrying that scope trip
    the point (per-replica chaos); scope None matches every firer.

    Three modes model a GRAY failure — a replica that still answers
    probes while its data path misbehaves: ``mode="slow"`` sleeps
    ``delay`` seconds on EVERY fire (thermal throttle, swap storm),
    ``mode="jitter"`` sleeps a deterministic pseudo-random duration in
    ``[0, delay)`` drawn from a per-fault LCG seeded by the point
    identity — the same arming replays the exact same delay sequence,
    so gray-failure soaks reproduce run to run — and
    ``mode="partition"`` stalls the firing site entirely (the
    half-open network shape: connection accepted, ``skip`` passes
    flow, then no bytes and no error) until :func:`clear` releases it,
    or for ``delay`` seconds per fire when ``delay > 0``.  All are
    persistent (``times`` is ignored: a gray fault that disarmed
    itself would read as a recovery mid-soak) until :func:`clear`, and
    all honor ``@scope`` per-replica targeting —
    ``scheduler.step@replica-b:slow:-1:0.05`` degrades exactly one
    replica of a fleet."""
    fault = _Fault(name, mode, times, delay, scope, skip=skip)
    with _lock:
        _points[(name, scope)] = fault
    return fault


_ALL_SCOPES = object()


def clear(name=None, scope=_ALL_SCOPES):
    """Disarm points.  ``clear()`` disarms everything; ``clear(name)``
    disarms the point under every scope; ``clear(name, scope)`` (scope
    may be None for the global arming) disarms exactly one entry."""
    with _lock:
        if name is None:
            _points.clear()
        elif scope is _ALL_SCOPES:
            for key in [k for k in _points if k[0] == name]:
                _points.pop(key, None)
        else:
            _points.pop((name, scope), None)


def _lookup(name, scope):
    """The armed fault matching a fire site: exact scope first, then
    the scope-less global arming.  Call with _lock held."""
    fault = _points.get((name, scope))
    if fault is None and scope is not None:
        fault = _points.get((name, None))
    return fault


def fired(name, scope=None):
    """How many times point ``name`` has actually fired (0 if unarmed).
    With ``scope``, reads the per-scope arming (falling back to the
    global one, mirroring :func:`fire`)."""
    with _lock:
        fault = _lookup(name, scope)
        return fault.fired if fault is not None else 0


def active(name, scope=None):
    """Whether point ``name`` is armed with fires remaining for a firer
    carrying ``scope``."""
    with _lock:
        fault = _lookup(name, scope)
        return fault is not None and fault.remaining != 0


def fire(name, scope=None):
    """The production-side hook: no-op unless ``name`` is armed.

    ``scope`` identifies the firing replica (see module docstring);
    scope-less armings match every firer.  Raises
    :class:`FaultInjected` (mode ``raise``), sleeps (mode ``sleep``),
    or returns an action tuple the site must implement — mode ``nan``
    returns ``("nan", slot_index)`` (the scheduler's step site poisons
    that slot's logits row) and mode ``hang`` returns
    ``("hang", seconds)`` (the step site sleeps AFTER stamping its
    watchdog heartbeat: a sleep inside fire() would stall *before* the
    heartbeat exists and the hung-step watchdog could never observe
    it; sites that don't implement ``hang`` ignore it).  Returns None
    for untripped passes.  The sleep happens OUTSIDE the registry lock
    so a slow point never blocks arming/disarming other points.
    """
    if not _points:  # fast path: nothing armed anywhere
        return None
    with _lock:
        fault = _lookup(name, scope)
        if fault is None or fault.remaining == 0:
            return None
        if fault.skip > 0:
            fault.skip -= 1
            return None
        if fault.remaining > 0:
            fault.remaining -= 1
        fault.fired += 1
        mode, delay = fault.mode, fault.delay
    if mode in ("sleep", "slow"):
        time.sleep(delay)
        return None
    if mode == "partition":
        _stall_partitioned(fault)
        return None
    if mode == "jitter":
        # deterministic per-fire pseudo-random delay in [0, delay):
        # advance the fault's own LCG under the lock (torn updates
        # would fork the sequence), sleep outside it
        with _lock:
            fault.lcg = (_LCG_A * fault.lcg + _LCG_C) % _LCG_M
            jittered = delay * fault.lcg / _LCG_M
        time.sleep(jittered)
        return None
    if mode in ("nan", "hang"):
        return (mode, int(delay) if mode == "nan" else delay)
    raise FaultInjected(name)


#: partition-stall poll cadence: coarse enough to be free, fine enough
#: that clear() releases a stalled fire within one human blink
_PARTITION_POLL_S = 0.02


def _stall_partitioned(fault):
    """``mode="partition"``'s stall: the half-open network shape
    ``slow`` doesn't model.  The connection was ACCEPTED and traffic
    flowed (``skip`` passes), then reads stop — no bytes, no RST, no
    error the firing site could surface — until the arming is
    :func:`clear`-ed (or replaced), or ``delay`` seconds pass when
    ``delay > 0`` (a bounded blackout).  Unlike ``raise`` the site
    never sees an exception, and unlike ``slow`` nothing trickles
    through while armed: the stall polls the registry OUTSIDE the lock
    so a partitioned point never blocks arming/disarming others, and a
    concurrent clear() releases every stalled fire promptly."""
    deadline = (time.monotonic() + fault.delay
                if fault.delay > 0 else None)
    while True:
        with _lock:
            if _points.get((fault.name, fault.scope)) is not fault:
                return  # healed: cleared or re-armed
        if deadline is not None and time.monotonic() >= deadline:
            return
        time.sleep(_PARTITION_POLL_S)


class injected:
    """Context manager: arm a point on enter, disarm on exit.

    >>> with faults.injected("scheduler.step"):
    ...     # the next decode step raises FaultInjected
    """

    def __init__(self, name, mode="raise", times=1, delay=0.0, scope=None,
                 skip=0):
        self._name = name
        self._scope = scope
        self._skip = skip
        self._args = (mode, times, delay)
        self.fault = None

    def __enter__(self):
        self.fault = install(self._name, *self._args, scope=self._scope,
                             skip=self._skip)
        return self.fault

    def __exit__(self, exc_type, exc, tb):
        clear(self._name, scope=self._scope)
        return False


def load_env(env=None):
    """Arm points from ``TPUSERVER_FAULTS`` (see module docstring)."""
    spec = (env if env is not None else os.environ).get(
        "TPUSERVER_FAULTS", "")
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                "TPUSERVER_FAULTS entry {!r} needs at least "
                "'name:mode'".format(entry)
            )
        name, mode = parts[0], parts[1]
        name, _, scope = name.partition("@")
        times = int(parts[2]) if len(parts) > 2 else 1
        delay = float(parts[3]) if len(parts) > 3 else 0.0
        install(name, mode=mode, times=times, delay=delay,
                scope=scope or None)


load_env()
