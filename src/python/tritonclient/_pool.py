"""Health-aware multi-replica client layer: endpoint pools, circuit
breakers, failover, and hedged requests.

A single-URL client makes one replica a single point of failure: a
restart or brownout takes every caller down even when N-1 healthy
replicas are a connect away.  :class:`EndpointPool` wraps one
``InferenceServerClient`` per URL (HTTP or gRPC — the pool is
transport-agnostic) behind the same method surface and routes each
call:

- **health-aware routing** — endpoints are probed via the server's
  truthful ``is_server_ready()`` (draining/stopped replicas answer
  false or shed with typed 503/UNAVAILABLE), either by a background
  prober (``health_interval_s``) or lazily by request outcomes, so
  sick replicas rotate out before a request is wasted on them;
- **per-endpoint circuit breaker** — closed → open after
  ``breaker_threshold`` consecutive typed failures → half-open after
  the cooldown (a server ``Retry-After`` hint overrides the cooldown),
  where exactly ONE trial request probes the endpoint while concurrent
  callers fail over fast;
- **failover** — typed overload rejections (and connect-phase
  failures, unless ``retry_connection_errors=False``) provably cost
  the server no work, so they fall through to the next healthy
  endpoint under one deadline budget (``deadline_s``), reusing the
  shared :class:`~tritonclient._auxiliary.RetryPolicy` classification
  instead of nesting per-endpoint retries inside failover;
- **hedged requests** (opt-in via ``hedge_delay_s``) — idempotent
  calls (``infer``, metadata, health) that outlive the hedge delay are
  raced against a second endpoint and the first success wins; the
  loser is cancelled if still queued, otherwise discarded on
  completion (its breaker bookkeeping still lands).  Non-idempotent
  and streaming calls are never hedged.

Streaming (``start_stream``/``async_stream_infer``) pins one healthy
endpoint for the stream's lifetime — a stream is stateful, so neither
failover nor hedging applies mid-stream.
"""

import socket
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait

from tritonclient._auxiliary import (
    CONNECT_ERROR_DETAILS,
    FAILURE_CONNECT,
    FAILURE_INTERRUPTED,
    FAILURE_OTHER,
    FAILURE_OVERLOAD,
    RetryPolicy,
)
from tritonclient.utils import InferenceServerException, raise_error

__all__ = [
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CircuitBreaker",
    "EndpointPool",
    "classify_failure",
]

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"

#: Pool methods that are safe to execute twice (hedging, failover of
#: interrupted calls).  ``infer`` qualifies for the stateless serving
#: path this repo targets — sequence/stateful calls should go through
#: a pinned stream instead.
_IDEMPOTENT_METHODS = frozenset((
    "infer",
    "is_server_live",
    "is_server_ready",
    "is_model_ready",
    "get_server_metadata",
    "get_model_metadata",
    "get_model_config",
    "get_model_repository_index",
    "get_inference_statistics",
    "get_trace_settings",
    "get_log_settings",
    "get_system_shared_memory_status",
    "get_cuda_shared_memory_status",
    "get_xla_shared_memory_status",
))

#: The subset of idempotent calls worth hedging: latency-sensitive and
#: cheap to duplicate.  Matches the issue contract: infer, metadata,
#: health — never non-idempotent or streaming calls.
_HEDGEABLE_METHODS = frozenset((
    "infer",
    "is_server_live",
    "is_server_ready",
    "is_model_ready",
    "get_server_metadata",
    "get_model_metadata",
    "get_model_config",
))

#: Methods whose side effect lives on ONE server: routing them through
#: failover would land the mutation on an arbitrary replica (register a
#: shm region on A, then round-robin an infer that needs it to B).  The
#: pool broadcasts these to EVERY endpoint instead, raising the first
#: failure after attempting all.
_BROADCAST_METHODS = frozenset((
    "load_model",
    "unload_model",
    "register_system_shared_memory",
    "unregister_system_shared_memory",
    "register_cuda_shared_memory",
    "unregister_cuda_shared_memory",
    "register_xla_shared_memory",
    "unregister_xla_shared_memory",
    "update_trace_settings",
    "update_log_settings",
))

#: Server-typed shed messages that prove an UNAVAILABLE was a
#: shed-before-work rejection (tpuserver's ShuttingDown wording), not a
#: mid-call reset.
_SHED_DETAILS = (
    "draining",
    "not accepting new requests",
    "shut down",
)


def classify_failure(exc):
    """Classify an exception from a pooled client call.

    Returns ``(kind, retry_after_s)`` where ``kind`` is one of the
    ``tritonclient._auxiliary.FAILURE_*`` constants and
    ``retry_after_s`` is the server's backoff hint (float seconds) when
    one was attached to the error, else None.
    """
    if isinstance(exc, (ConnectionRefusedError, socket.gaierror)):
        return FAILURE_CONNECT, None
    if isinstance(exc, InferenceServerException):
        status = exc.status() or ""
        retry_after = RetryPolicy.parse_retry_after(exc.retry_after())
        if status in ("429", "503"):
            return FAILURE_OVERLOAD, retry_after
        if status == "StatusCode.RESOURCE_EXHAUSTED":
            return FAILURE_OVERLOAD, retry_after
        if status == "StatusCode.UNAVAILABLE":
            # UNAVAILABLE conflates three cases; the retry-after
            # trailer or the detail string disambiguates.
            if retry_after is not None:
                return FAILURE_OVERLOAD, retry_after
            detail = (exc.message() or "").lower()
            if any(marker in detail for marker in CONNECT_ERROR_DETAILS):
                return FAILURE_CONNECT, None
            if any(marker in detail for marker in _SHED_DETAILS):
                return FAILURE_OVERLOAD, None
            return FAILURE_INTERRUPTED, None  # possibly a mid-call reset
        return FAILURE_OTHER, retry_after
    if isinstance(exc, socket.timeout):
        return FAILURE_INTERRUPTED, None
    if isinstance(exc, (ConnectionError, OSError)):
        # sent-then-dropped: the server may have executed the request
        return FAILURE_INTERRUPTED, None
    return FAILURE_OTHER, None


class CircuitBreaker:
    """Per-endpoint circuit breaker: closed → open → half-open.

    - **closed**: requests flow; ``failure_threshold`` consecutive
      typed failures trip the breaker open.
    - **open**: requests fail over fast for ``cooldown_s`` seconds (a
      server ``Retry-After`` hint on the tripping failure overrides
      the cooldown — the server said when to come back).
    - **half-open**: after the cooldown, :meth:`allow` grants exactly
      ONE trial request; concurrent callers keep failing over until
      the probe reports.  Success closes the breaker, failure re-opens
      it for another cooldown.

    Thread-safe; ``now`` is injectable for tests.
    """

    def __init__(self, failure_threshold=3, cooldown_s=5.0,
                 now=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                "failure_threshold must be >= 1 (got {})".format(
                    failure_threshold))
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._now = now
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED  # guarded-by: _lock
        self._consecutive_failures = 0  # guarded-by: _lock
        self._open_until = 0.0  # guarded-by: _lock
        self._probe_inflight = False  # guarded-by: _lock

    def _poll_locked(self):
        if self._state == BREAKER_OPEN and self._now() >= self._open_until:
            self._state = BREAKER_HALF_OPEN
            self._probe_inflight = False

    @property
    def state(self):
        with self._lock:
            self._poll_locked()
            return self._state

    def reopens_in(self):
        """Seconds until an open breaker goes half-open (0 when it
        already allows a probe or is closed)."""
        with self._lock:
            self._poll_locked()
            if self._state != BREAKER_OPEN:
                return 0.0
            return max(0.0, self._open_until - self._now())

    def allow(self):
        """Whether a request may be sent through this endpoint now.

        In half-open state this CONSUMES the single probe slot — only
        call it for an endpoint the request will actually be sent to.
        """
        with self._lock:
            self._poll_locked()
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                return False
            if self._probe_inflight:
                return False  # someone else holds the half-open probe
            self._probe_inflight = True
            return True

    def record_success(self):
        with self._lock:
            self._state = BREAKER_CLOSED
            self._consecutive_failures = 0
            self._probe_inflight = False

    def record_failure(self, retry_after=None):
        """Record a typed (connect/overload) failure; returns True when
        this failure tripped the breaker open."""
        with self._lock:
            self._poll_locked()
            self._probe_inflight = False
            if self._state == BREAKER_HALF_OPEN:
                self._trip_locked(retry_after)  # failed probe: re-open
                return True
            self._consecutive_failures += 1
            if (
                self._state == BREAKER_CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._trip_locked(retry_after)
                return True
            return False

    def _trip_locked(self, retry_after):
        cooldown = RetryPolicy.parse_retry_after(retry_after)
        if cooldown is None:
            cooldown = self.cooldown_s
        self._state = BREAKER_OPEN
        self._open_until = self._now() + cooldown


class _Endpoint:
    """One pooled replica: its client, breaker, and health bookkeeping."""

    def __init__(self, url, client, breaker):
        self.url = url
        self.client = client
        self.breaker = breaker
        self.healthy = True  # last known readiness (optimistic start)
        self.requests = 0
        self.failures = 0

    def stats(self):
        return {
            "url": self.url,
            "healthy": self.healthy,
            "breaker": self.breaker.state,
            "requests": self.requests,
            "failures": self.failures,
        }


class EndpointPool:
    """N replicas behind the single-client ``InferenceServerClient``
    surface, with health routing, circuit breaking, failover, and
    opt-in hedging (module docstring has the full semantics).

    Parameters
    ----------
    urls : list[str]
        ``host:port`` of each replica (two or more for any real HA;
        one degenerates to a plain client with a breaker).
    protocol : str
        ``"http"`` or ``"grpc"`` — selects the default client class.
        The asyncio clients are not poolable yet (ISSUE 3 scopes the
        sync clients); ``"http_aio"``/``"grpc_aio"`` raise
        NotImplementedError.
    client_factory : callable(url) -> client
        Overrides client construction (tests inject fakes here).  The
        produced clients must NOT carry their own ``retry_policy`` —
        the pool owns retry/failover, and nesting retries inside
        failover multiplies attempts against a sick endpoint.
    retry_policy : tritonclient._auxiliary.RetryPolicy
        Attempt budget, backoff schedule, and failure classification
        shared across endpoints (default: ``RetryPolicy()``).  One
        logical call makes at most ``max_attempts`` endpoint attempts
        TOTAL, not per endpoint.
    breaker_threshold / breaker_cooldown_s
        Circuit-breaker tuning (see :class:`CircuitBreaker`).
    health_interval_s : float or None
        When set, a daemon thread probes every endpoint's
        ``is_server_ready()`` on this cadence and feeds the breakers,
        rotating draining replicas out before any request is wasted.
        None (default) relies on lazy signals: request outcomes and
        half-open trial requests.
    hedge_delay_s : float or None
        Opt-in hedging: an idempotent call still pending after this
        many seconds is raced against a second endpoint.  None
        disables hedging.
    deadline_s : float or None
        Wall-clock budget for one logical call across all failover
        attempts and backoff sleeps.
    """

    def __init__(self, urls, protocol="http", client_factory=None,
                 retry_policy=None, breaker_threshold=3,
                 breaker_cooldown_s=5.0, health_interval_s=None,
                 hedge_delay_s=None, deadline_s=None, verbose=False,
                 **client_kwargs):
        if not urls:
            raise_error("EndpointPool requires at least one endpoint URL")
        if len(set(urls)) != len(urls):
            raise_error("EndpointPool URLs must be unique: {}".format(urls))
        if protocol in ("http_aio", "grpc_aio"):
            raise NotImplementedError(
                "EndpointPool does not support the asyncio clients yet "
                "(ISSUE 3: health-aware multi-replica client covers the "
                "sync clients; aio pooling is follow-up work)")
        if client_factory is None:
            if protocol == "http":
                import tritonclient.http as _mod
            elif protocol == "grpc":
                import tritonclient.grpc as _mod
            else:
                raise_error(
                    "unknown protocol {!r} (use 'http' or 'grpc', or "
                    "pass client_factory)".format(protocol))

            def client_factory(url, _mod=_mod):
                return _mod.InferenceServerClient(
                    url, verbose=verbose, **client_kwargs)

        self._policy = retry_policy if retry_policy is not None else (
            RetryPolicy())
        self._deadline_s = deadline_s
        self._hedge_delay_s = hedge_delay_s
        self._verbose = verbose
        self._lock = threading.Lock()
        self._rr = 0  # round-robin cursor  # guarded-by: _lock
        self._closed = False
        self._stream_endpoint = None
        self._hedges_fired = 0  # guarded-by: _lock
        self._hedges_won = 0  # guarded-by: _lock
        self._endpoints = []
        for url in urls:
            client = client_factory(url)
            if getattr(client, "_retry_policy", None) is not None:
                for ep in self._endpoints:
                    ep.client.close()
                client.close()
                raise_error(
                    "per-endpoint clients must not carry their own "
                    "retry_policy: the pool owns retries and failover "
                    "(nesting retries inside failover multiplies "
                    "attempts against a sick endpoint) — pass "
                    "retry_policy to the EndpointPool instead")
            self._endpoints.append(_Endpoint(
                url,
                client,
                CircuitBreaker(
                    failure_threshold=breaker_threshold,
                    cooldown_s=breaker_cooldown_s,
                ),
            ))
        # two separate executors: async_infer callers occupy _executor
        # workers while (possibly) blocking on hedge futures, so hedge
        # attempts MUST run on their own executor — sharing one bounded
        # pool would let saturated async_infer workers wait on primary
        # attempts queued behind themselves, a permanent deadlock.
        # Hedge tasks never submit further tasks, so the hedge executor
        # always makes progress.
        self._executor = None  # guarded-by: _executor_lock
        self._hedge_executor = None  # guarded-by: _executor_lock
        self._executor_lock = threading.Lock()
        self._prober = None
        self._prober_stop = threading.Event()
        if health_interval_s is not None:
            if health_interval_s <= 0:
                raise_error("health_interval_s must be positive or None")
            self._prober = threading.Thread(
                target=self._probe_loop,
                args=(float(health_interval_s),),
                name="tritonclient-pool-prober",
                daemon=True,
            )
            self._prober.start()

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()

    def close(self):
        """Stop the prober and hedging workers, close every client."""
        if self._closed:
            return
        self._closed = True
        self._prober_stop.set()
        if self._prober is not None:
            self._prober.join(timeout=5)
        # snapshot under the executor lock: _closed was set above, so a
        # concurrent _ensure_* either published its executor before this
        # snapshot (and it is shut down here) or acquires the lock after
        # and refuses on _closed — a post-close executor can never be
        # created and leak its non-daemon workers
        with self._executor_lock:
            executor = self._executor
            hedge_executor = self._hedge_executor
        if executor is not None:
            executor.shutdown(wait=True)
        if hedge_executor is not None:
            # joins hedge losers too: a discarded attempt fully resolves
            # (and lands its breaker bookkeeping) before clients close
            hedge_executor.shutdown(wait=True)
        for ep in self._endpoints:
            try:
                ep.client.close()
            except Exception:
                pass

    # -- observability -----------------------------------------------------

    def stats(self):
        """Per-endpoint health/breaker/traffic counters plus hedging
        totals — the pool's routing decisions, inspectable."""
        with self._lock:
            hedges_fired = self._hedges_fired
            hedges_won = self._hedges_won
        return {
            "endpoints": [ep.stats() for ep in self._endpoints],
            "hedges_fired": hedges_fired,
            "hedges_won": hedges_won,
        }

    def endpoint_states(self):
        """``{url: breaker_state}`` — convenience for tests/dashboards."""
        return {ep.url: ep.breaker.state for ep in self._endpoints}

    # -- health probing ----------------------------------------------------

    def _probe_loop(self, interval_s):
        while not self._prober_stop.wait(interval_s):
            for ep in self._endpoints:
                if self._prober_stop.is_set():
                    return
                self._probe_endpoint(ep)

    def _probe_endpoint(self, ep):
        """One readiness probe, feeding both the health flag and the
        breaker.  'Not ready' (draining/starting) counts as a typed
        failure — the server answered, and the answer was 'route
        away'; breaker state therefore tracks readiness, so it
        re-closes only once the server returns to ready."""
        state = ep.breaker.state
        if state == BREAKER_OPEN:
            return  # cooling down; probing would defeat the cooldown
        if state == BREAKER_HALF_OPEN and not ep.breaker.allow():
            return  # another caller holds the half-open probe slot
        try:
            ready = bool(ep.client.is_server_ready())
        except Exception as exc:  # noqa: BLE001 — any probe failure counts
            kind, retry_after = classify_failure(exc)
            ep.healthy = False
            ep.breaker.record_failure(
                retry_after if kind != FAILURE_OTHER else None)
            return
        ep.healthy = ready
        if ready:
            ep.breaker.record_success()
        else:
            ep.breaker.record_failure()

    # -- endpoint selection ------------------------------------------------

    def _rotation(self):
        """Endpoints in round-robin order starting at the cursor."""
        with self._lock:
            n = len(self._endpoints)
            start = self._rr
            self._rr = (self._rr + 1) % n
        return [self._endpoints[(start + i) % n] for i in range(n)]

    def _pick(self, exclude=()):
        """The next endpoint to try, or None when every breaker is open
        (or holding a half-open probe).  Healthy endpoints are
        preferred; unhealthy ones are last-resort candidates whose
        half-open breakers meter the traffic they see.  Consumes the
        half-open probe slot of the endpoint it returns."""
        rotation = self._rotation()
        candidates = [ep for ep in rotation if ep.healthy] + [
            ep for ep in rotation if not ep.healthy
        ]
        for ep in candidates:
            if ep in exclude:
                continue
            if ep.breaker.allow():
                return ep
        return None

    def _any_routable(self, exclude=()):
        """Whether any endpoint could accept traffic without waiting
        out a cooldown (no probe slots consumed)."""
        return any(
            ep.breaker.state != BREAKER_OPEN
            for ep in self._endpoints
            if ep not in exclude
        )

    # -- the failover core -------------------------------------------------

    def _pool_unavailable(self, last_exc):
        if last_exc is not None:
            raise last_exc
        reopen = min(
            (ep.breaker.reopens_in() for ep in self._endpoints),
            default=0.0,
        )
        raise InferenceServerException(
            msg="no pool endpoint available: every circuit breaker is "
                "open (earliest half-open probe in {:.2f}s)".format(reopen),
            status="503",
        )

    def _invoke(self, method_name, args, kwargs, idempotent,
                exclude_first=(), stop=None, on_pick=None):
        """One logical call with failover across endpoints.

        ``exclude_first`` keeps a hedge's secondary off the primary's
        endpoint for its first attempt; ``stop`` (threading.Event) lets
        a hedge loser abandon further attempts once the winner landed;
        ``on_pick(ep)`` observes every endpoint an attempt is sent to
        (the hedge uses it to aim its secondary elsewhere).
        """
        policy = self._policy
        deadline = (
            time.monotonic() + self._deadline_s
            if self._deadline_s is not None
            else None
        )
        attempt = 0
        last_exc = None
        exclude = tuple(exclude_first)
        while attempt < policy.max_attempts:
            if stop is not None and stop.is_set():
                self._pool_unavailable(last_exc)
            remaining = (
                deadline - time.monotonic() if deadline is not None else None
            )
            if remaining is not None and remaining <= 0:
                self._pool_unavailable(last_exc)
            ep = self._pick(exclude=exclude)
            if ep is None and exclude:
                exclude = ()  # hedge preference only holds for attempt 1
                ep = self._pick()
            if ep is None:
                self._pool_unavailable(last_exc)
            exclude = ()
            attempt += 1
            if on_pick is not None:
                on_pick(ep)
            ep.requests += 1
            try:
                result = getattr(ep.client, method_name)(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 — classified below
                kind, retry_after = classify_failure(exc)
                if not policy.should_failover(kind, idempotent=idempotent):
                    if kind == FAILURE_OTHER:
                        # a typed answer: the endpoint is alive and
                        # serving — reset its failure streak
                        ep.breaker.record_success()
                        ep.healthy = True
                    else:
                        ep.failures += 1
                        ep.breaker.record_failure(retry_after)
                        ep.healthy = False
                    raise
                ep.failures += 1
                ep.breaker.record_failure(retry_after)
                ep.healthy = False
                last_exc = exc
                if attempt >= policy.max_attempts:
                    break
                if not self._any_routable(exclude=(ep,)):
                    # nowhere else to go: honor the backoff (capped at
                    # the remaining budget) before trying again
                    remaining = (
                        deadline - time.monotonic()
                        if deadline is not None
                        else None
                    )
                    if remaining is not None and remaining <= 0:
                        break
                    time.sleep(policy.backoff_s(
                        attempt - 1, retry_after, remaining))
                continue
            else:
                ep.breaker.record_success()
                ep.healthy = True
                return result
        self._pool_unavailable(last_exc)

    # -- hedging -----------------------------------------------------------

    def _ensure_executor(self):
        with self._executor_lock:
            if self._closed:
                # close() flips _closed BEFORE taking this lock for its
                # shutdown snapshot: refusing here means an executor can
                # never be created after the snapshot ran (it would leak
                # its non-daemon workers with nothing left to join them)
                raise_error("EndpointPool is closed")
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=max(8, 4 * len(self._endpoints)),
                    thread_name_prefix="tritonclient-pool",
                )
            return self._executor

    def _ensure_hedge_executor(self):
        with self._executor_lock:
            if self._closed:
                raise_error("EndpointPool is closed")  # see _ensure_executor
            if self._hedge_executor is None:
                self._hedge_executor = ThreadPoolExecutor(
                    max_workers=max(16, 8 * len(self._endpoints)),
                    thread_name_prefix="tritonclient-pool-hedge",
                )
            return self._hedge_executor

    def _hedged(self, method_name, args, kwargs):
        """Race a primary attempt against a delayed secondary on a
        different endpoint; first success wins, the loser is cancelled
        if still queued and discarded otherwise."""
        executor = self._ensure_hedge_executor()
        picked = []  # every endpoint the primary sends an attempt to
        stop = threading.Event()
        primary = executor.submit(
            self._invoke, method_name, args, kwargs, True, (), stop,
            picked.append)
        done, _ = wait((primary,), timeout=self._hedge_delay_s)
        if done:
            return primary.result()
        # aim the secondary away from wherever the primary is NOW
        # (after its own failovers), not just its first endpoint
        hedge_exclude = (picked[-1],) if picked else ()
        with self._lock:
            self._hedges_fired += 1
        secondary = executor.submit(
            self._invoke, method_name, args, kwargs, True,
            hedge_exclude, stop)
        futures = {primary, secondary}
        first_error = None
        while futures:
            done, futures = wait(futures, return_when=FIRST_COMPLETED)
            for fut in done:
                exc = fut.exception()
                if exc is None:
                    # winner: stop the loser's failover loop and cancel
                    # it outright if it has not started yet
                    stop.set()
                    for loser in futures:
                        loser.cancel()
                    if fut is secondary:
                        with self._lock:
                            self._hedges_won += 1
                    return fut.result()
                if first_error is None:
                    first_error = exc
        raise first_error

    # -- public surface ----------------------------------------------------

    def _broadcast(self, method_name, args, kwargs):
        """Apply a per-server mutation to EVERY endpoint (skipping
        none): replicas must agree on registered shm regions, loaded
        models, and settings, or the next round-robined request lands
        on a replica missing the side effect.  Every endpoint is
        attempted; the first failure is raised afterwards."""
        result = None
        first_exc = None
        for ep in self._endpoints:
            try:
                result = getattr(ep.client, method_name)(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = exc
                kind, retry_after = classify_failure(exc)
                if kind != FAILURE_OTHER:
                    ep.failures += 1
                    ep.breaker.record_failure(retry_after)
                    ep.healthy = False
        if first_exc is not None:
            raise first_exc
        return result

    def _dispatch(self, method_name, args, kwargs):
        if self._closed:
            raise_error("EndpointPool is closed")
        if method_name in _BROADCAST_METHODS:
            return self._broadcast(method_name, args, kwargs)
        idempotent = method_name in _IDEMPOTENT_METHODS
        if (
            self._hedge_delay_s is not None
            and method_name in _HEDGEABLE_METHODS
            and len(self._endpoints) > 1
        ):
            return self._hedged(method_name, args, kwargs)
        return self._invoke(method_name, args, kwargs, idempotent)

    def infer(self, *args, **kwargs):
        """Pool-routed ``infer`` (failover; hedged when enabled)."""
        return self._dispatch("infer", args, kwargs)

    def async_infer(self, *args, **kwargs):
        """Pool-routed async infer: runs :meth:`infer` (with its full
        failover/hedging semantics) on a pool worker and returns the
        HTTP client's ``InferAsyncRequest`` handle
        (``get_result(block=True, timeout=None)``).  The gRPC callback
        form is not reproduced here; pass a callable as the third
        positional argument only to the plain gRPC client."""
        # lazy import: tritonclient.http's package __init__ imports
        # this module, so a module-level import would be circular
        from tritonclient.http._client import InferAsyncRequest

        future = self._ensure_executor().submit(
            self._dispatch, "infer", args, kwargs)
        return InferAsyncRequest(future, self._verbose)

    # -- streaming: pinned, never hedged, never failed over ----------------

    def start_stream(self, *args, **kwargs):
        """Open a stream on ONE healthy endpoint and pin it: streams
        are stateful, so mid-stream failover/hedging would corrupt
        sequence state.  ``stop_stream`` unpins."""
        if self._stream_endpoint is not None:
            raise_error(
                "cannot start another stream with one already active")
        ep = self._pick()
        if ep is None:
            self._pool_unavailable(None)
        try:
            result = ep.client.start_stream(*args, **kwargs)
        except Exception as exc:  # noqa: BLE001 — classified for breaker
            # every outcome must reach the breaker: _pick() may have
            # consumed the half-open probe slot, and only
            # record_success/record_failure release it — an unrecorded
            # failure would blacklist the endpoint forever
            kind, retry_after = classify_failure(exc)
            if kind == FAILURE_OTHER:
                ep.breaker.record_success()  # typed answer: alive
            else:
                ep.breaker.record_failure(
                    retry_after if kind == FAILURE_OVERLOAD else None)
                ep.healthy = False
            raise
        ep.breaker.record_success()
        self._stream_endpoint = ep
        return result

    def async_stream_infer(self, *args, **kwargs):
        if self._stream_endpoint is None:
            raise_error("stream not available, use start_stream() first")
        return self._stream_endpoint.client.async_stream_infer(
            *args, **kwargs)

    def stop_stream(self, *args, **kwargs):
        ep, self._stream_endpoint = self._stream_endpoint, None
        if ep is not None:
            return ep.client.stop_stream(*args, **kwargs)

    def generate_stream(self, *args, **kwargs):
        """Run ONE resumable generation on one healthy endpoint, pinned
        for the generation's whole lifetime INCLUDING the client's
        auto-resume reconnects: generation replay state (token history,
        re-prefill source) is **replica-local**, so a live resume
        prefers the pinned endpoint.  Never hedged, never failed over
        mid-generation — the pooled client's own reconnect+resume
        handles transport drops; only a FRESH generate_stream call
        routes anew.

        One escape hatch rides the pinned client's reconnect loop: the
        pool seeds the OTHER endpoints as ``fallback_urls``, so a
        resume whose pinned endpoint refuses connections outright (a
        SIGKILLed router, a not-yet-respawned process) rotates to a
        peer under the same reconnect budget.  Behind fleet routers
        seq continuity — not endpoint identity — is the resume
        contract, so the peer serves the splice; a bare replica peer
        answers the unknown-generation 404 the reconnect loop already
        classifies as a transition, and the rotation returns to the
        pinned endpoint on the next attempt.  Pass your own
        ``fallback_urls`` (or ``fallback_urls=()``) to override.

        This is a generator: the endpoint is picked (and any half-open
        breaker probe slot consumed) only when iteration starts, so a
        handle that is created but never iterated cannot leak the
        probe slot and blacklist the endpoint."""
        if self._closed:
            raise_error("EndpointPool is closed")
        ep = self._pick()
        if ep is None:
            self._pool_unavailable(None)
        if "fallback_urls" not in kwargs and not getattr(
                ep.client, "_secure", False):
            # never auto-inject for secure gRPC channels: per-url TLS
            # material cannot be assumed to transfer, and the client
            # refuses fallback rotation on them with a typed error —
            # a secure pool keeps the plain same-endpoint pin
            kwargs["fallback_urls"] = [
                peer.url for peer in self._endpoints if peer is not ep]
        recorded = [False]

        def record_ok():
            if not recorded[0]:
                recorded[0] = True
                ep.breaker.record_success()

        try:
            for event in ep.client.generate_stream(*args, **kwargs):
                record_ok()
                yield event
        except Exception as exc:  # noqa: BLE001 — classified for the
            # breaker (same contract as start_stream)
            if not recorded[0]:
                recorded[0] = True
                kind, retry_after = classify_failure(exc)
                if kind == FAILURE_OTHER:
                    ep.breaker.record_success()  # typed answer: alive
                else:
                    ep.breaker.record_failure(
                        retry_after if kind == FAILURE_OVERLOAD
                        else None)
                    ep.healthy = False
            raise
        finally:
            # abandoned before the first event: release a possible
            # half-open probe slot so the endpoint is not blacklisted
            # forever
            record_ok()

    # -- everything else: generic delegation with failover ----------------

    def __getattr__(self, name):
        # Only reached for attributes not defined above.  Delegate any
        # public client method through the failover dispatcher so the
        # pool exposes the full InferenceServerClient surface without
        # hand-writing ~40 wrappers.
        if name.startswith("_"):
            raise AttributeError(name)
        probe = getattr(self._endpoints[0].client, name, None)
        if not callable(probe):
            raise AttributeError(
                "{!r} is not a method of the pooled client".format(name))

        def pooled_method(*args, _pool_method=name, **kwargs):
            return self._dispatch(_pool_method, args, kwargs)

        pooled_method.__name__ = name
        pooled_method.__doc__ = probe.__doc__
        return pooled_method


