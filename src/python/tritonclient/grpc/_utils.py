"""Shared request-assembly helpers for the sync and aio gRPC clients
(reference grpc/_utils.py)."""

import grpc

from tritonclient.utils import InferenceServerException

from . import grpc_service_pb2 as pb
from ._infer_input import _set_parameter


def raise_error_grpc(rpc_error):
    """Map a grpc.RpcError to InferenceServerException and raise it."""
    raise get_error_grpc(rpc_error) from None


def retry_after_from_rpc_error(rpc_error):
    """The server's ``retry-after`` trailing-metadata value (the gRPC
    twin of the HTTP Retry-After header), or None."""
    try:
        for key, value in rpc_error.trailing_metadata() or ():
            if key.lower() == "retry-after":
                return value
    except Exception:
        pass
    return None


def get_error_grpc(rpc_error):
    try:
        msg = rpc_error.details()
        code = rpc_error.code()
        status = "StatusCode." + code.name if code is not None else None
    except Exception:
        msg = str(rpc_error)
        status = None
    # the retry-after hint rides along so retry/failover layers
    # (tritonclient._pool) can honor the server's cooldown
    return InferenceServerException(
        msg=msg, status=status,
        retry_after=retry_after_from_rpc_error(rpc_error),
    )


def _get_inference_request(
    model_name,
    inputs,
    model_version="",
    request_id="",
    outputs=None,
    sequence_id=0,
    sequence_start=False,
    sequence_end=False,
    priority=0,
    timeout=None,
    parameters=None,
):
    """Build a ModelInferRequest (reference _utils.py:64-110)."""
    request = pb.ModelInferRequest()
    request.model_name = model_name
    request.model_version = model_version
    if request_id:
        request.id = request_id
    for infer_input in inputs:
        request.inputs.append(infer_input._get_tensor())
        raw = infer_input._get_content()
        if raw is not None:
            request.raw_input_contents.append(raw)
    for infer_output in outputs or []:
        request.outputs.append(infer_output._get_tensor())
    if sequence_id:
        _set_parameter(request.parameters, "sequence_id", int(sequence_id))
        _set_parameter(
            request.parameters, "sequence_start", bool(sequence_start)
        )
        _set_parameter(request.parameters, "sequence_end", bool(sequence_end))
    if priority:
        _set_parameter(request.parameters, "priority", int(priority))
    if timeout is not None:
        _set_parameter(request.parameters, "timeout", int(timeout))
    for key, value in (parameters or {}).items():
        if key in (
            "sequence_id", "sequence_start", "sequence_end", "priority",
            "binary_data_output",
        ):
            raise InferenceServerException(
                "parameter '{}' must be set through the dedicated "
                "argument".format(key)
            )
        _set_parameter(request.parameters, key, value)
    return request
