"""KServe-v2 gRPC service method table.

grpcio-tools is not available in this environment, so instead of a generated
``*_pb2_grpc.py`` the service layer is this explicit method registry used
with ``grpc.Channel.unary_unary``/``stream_stream`` generic callables (and,
server-side, ``grpc.method_handlers_generic_handler``).  Method set mirrors
the reference's grpc_service.proto service block (reference
src/c++/CMakeLists.txt fetches it from triton common), plus the
XlaSharedMemory* verbs that generalize the CUDA-shm path for TPU.
"""

from . import grpc_service_pb2 as pb

SERVICE = "inference.GRPCInferenceService"

# name -> (request class, response class, kind) where kind is "unary" or
# "stream" (bidi stream-stream).
METHODS = {
    "ServerLive": (pb.ServerLiveRequest, pb.ServerLiveResponse, "unary"),
    "ServerReady": (pb.ServerReadyRequest, pb.ServerReadyResponse, "unary"),
    "ModelReady": (pb.ModelReadyRequest, pb.ModelReadyResponse, "unary"),
    "ServerMetadata": (
        pb.ServerMetadataRequest, pb.ServerMetadataResponse, "unary"),
    # ServerMetrics-style unary (role of the reference server's
    # :8002/metrics plane on the gRPC transport): the Prometheus text
    # exposition rides a LogSettingsResponse string param ("metrics")
    # — the vendored descriptor pool cannot grow a new message without
    # protoc, and the wire is just length-delimited proto either way.
    "ServerMetrics": (
        pb.ServerMetadataRequest, pb.LogSettingsResponse, "unary"),
    "ModelMetadata": (
        pb.ModelMetadataRequest, pb.ModelMetadataResponse, "unary"),
    "ModelInfer": (pb.ModelInferRequest, pb.ModelInferResponse, "unary"),
    "ModelStreamInfer": (
        pb.ModelInferRequest, pb.ModelStreamInferResponse, "stream"),
    "ModelConfig": (pb.ModelConfigRequest, pb.ModelConfigResponse, "unary"),
    "ModelStatistics": (
        pb.ModelStatisticsRequest, pb.ModelStatisticsResponse, "unary"),
    "RepositoryIndex": (
        pb.RepositoryIndexRequest, pb.RepositoryIndexResponse, "unary"),
    "RepositoryModelLoad": (
        pb.RepositoryModelLoadRequest, pb.RepositoryModelLoadResponse,
        "unary"),
    "RepositoryModelUnload": (
        pb.RepositoryModelUnloadRequest, pb.RepositoryModelUnloadResponse,
        "unary"),
    "SystemSharedMemoryStatus": (
        pb.SystemSharedMemoryStatusRequest,
        pb.SystemSharedMemoryStatusResponse, "unary"),
    "SystemSharedMemoryRegister": (
        pb.SystemSharedMemoryRegisterRequest,
        pb.SystemSharedMemoryRegisterResponse, "unary"),
    "SystemSharedMemoryUnregister": (
        pb.SystemSharedMemoryUnregisterRequest,
        pb.SystemSharedMemoryUnregisterResponse, "unary"),
    "CudaSharedMemoryStatus": (
        pb.CudaSharedMemoryStatusRequest, pb.CudaSharedMemoryStatusResponse,
        "unary"),
    "CudaSharedMemoryRegister": (
        pb.CudaSharedMemoryRegisterRequest,
        pb.CudaSharedMemoryRegisterResponse, "unary"),
    "CudaSharedMemoryUnregister": (
        pb.CudaSharedMemoryUnregisterRequest,
        pb.CudaSharedMemoryUnregisterResponse, "unary"),
    "XlaSharedMemoryStatus": (
        pb.XlaSharedMemoryStatusRequest, pb.XlaSharedMemoryStatusResponse,
        "unary"),
    "XlaSharedMemoryRegister": (
        pb.XlaSharedMemoryRegisterRequest,
        pb.XlaSharedMemoryRegisterResponse, "unary"),
    "XlaSharedMemoryUnregister": (
        pb.XlaSharedMemoryUnregisterRequest,
        pb.XlaSharedMemoryUnregisterResponse, "unary"),
    "TraceSetting": (
        pb.TraceSettingRequest, pb.TraceSettingResponse, "unary"),
    "LogSettings": (pb.LogSettingsRequest, pb.LogSettingsResponse, "unary"),
}


def method_path(name):
    return "/{}/{}".format(SERVICE, name)


class ServiceStub:
    """Callable-per-method stub built from a ``grpc.Channel``.

    ``stub.ModelInfer(request, metadata=..., timeout=...)`` etc.;
    ``stub.ModelInfer.future(...)`` works for async use because the
    underlying grpc multicallables expose ``.future``.
    """

    def __init__(self, channel):
        for name, (req_cls, resp_cls, kind) in METHODS.items():
            if kind == "unary":
                call = channel.unary_unary(
                    method_path(name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )
            else:
                call = channel.stream_stream(
                    method_path(name),
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                )
            setattr(self, name, call)

