"""tritonclient.grpc — KServe-v2 gRPC client (sync; asyncio variant in
``tritonclient.grpc.aio``)."""

from tritonclient.grpc import model_config_pb2, grpc_service_pb2  # noqa: F401
from tritonclient._pool import CircuitBreaker  # noqa: F401
from tritonclient._pool import EndpointPool as _EndpointPool
from tritonclient.grpc._client import (  # noqa: F401
    InferenceServerClient,
    KeepAliveOptions,
    RetryPolicy,
)


class EndpointPool(_EndpointPool):
    """``tritonclient._pool.EndpointPool`` defaulting to gRPC clients —
    the import location implies the protocol, so the grpc namespace
    must not silently build HTTP clients against gRPC ports."""

    def __init__(self, urls, protocol="grpc", **kwargs):
        super().__init__(urls, protocol=protocol, **kwargs)
from tritonclient.grpc._infer_input import (  # noqa: F401
    InferInput,
    InferRequestedOutput,
)
from tritonclient.grpc._infer_result import InferResult  # noqa: F401
from tritonclient.utils import InferenceServerException  # noqa: F401

service_pb2 = grpc_service_pb2
