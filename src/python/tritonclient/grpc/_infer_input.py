"""Input/requested-output tensor descriptors for the gRPC client.

Protobuf-backed mirrors of the reference grpc/_infer_input.py /
_requested_output.py, with the TPU-first extensions shared with the HTTP
client: array-likes (incl. ``jax.Array``) accepted everywhere, native BF16
via ml_dtypes, and ``set_shared_memory`` pointing at system or XLA regions.
"""

import numpy as np

from tritonclient.utils import (
    np_to_triton_dtype,
    raise_error,
    serialize_bf16_tensor,
    serialize_byte_tensor,
)

from . import grpc_service_pb2 as pb


def _set_parameter(param_map, key, value):
    p = param_map[key]
    if isinstance(value, bool):
        p.bool_param = value
    elif isinstance(value, int):
        p.int64_param = value
    elif isinstance(value, float):
        p.double_param = value
    elif isinstance(value, str):
        p.string_param = value
    else:
        raise_error(
            "unsupported parameter type {} for '{}'".format(
                type(value), key
            )
        )


def _clear_parameter(param_map, key):
    if key in param_map:
        del param_map[key]


class InferInput:
    """An input tensor for a gRPC inference request."""

    def __init__(self, name, shape, datatype):
        self._input = pb.ModelInferRequest.InferInputTensor()
        self._input.name = name
        self._input.shape.extend(int(s) for s in shape)
        self._input.datatype = datatype
        self._raw_content = None

    def name(self):
        return self._input.name

    def datatype(self):
        return self._input.datatype

    def shape(self):
        return list(self._input.shape)

    def set_shape(self, shape):
        del self._input.shape[:]
        self._input.shape.extend(int(s) for s in shape)
        return self

    def set_data_from_numpy(self, input_tensor):
        """Set tensor data from an array-like (np.ndarray or jax.Array —
        fetched from device exactly once here)."""
        if not isinstance(input_tensor, np.ndarray):
            try:
                input_tensor = np.asarray(input_tensor)
            except Exception:
                raise_error("input_tensor must be a numpy array or array-like")
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if self._input.datatype == "BF16" or dtype == "BF16":
            serialized = serialize_bf16_tensor(input_tensor)
            self._raw_content = (
                serialized.item() if serialized.size > 0 else b""
            )
        elif self._input.datatype == "BYTES":
            serialized = serialize_byte_tensor(input_tensor)
            self._raw_content = (
                serialized.item() if serialized.size > 0 else b""
            )
        else:
            if dtype is None:
                raise_error(
                    "unsupported numpy dtype {}".format(input_tensor.dtype)
                )
            if dtype != self._input.datatype:
                raise_error(
                    "got unexpected datatype {} from numpy array, expected "
                    "{}".format(dtype, self._input.datatype)
                )
            self._raw_content = np.ascontiguousarray(input_tensor).tobytes()
        self.set_shape(input_tensor.shape)
        self._input.ClearField("contents")
        _clear_parameter(self._input.parameters, "shared_memory_region")
        _clear_parameter(self._input.parameters, "shared_memory_byte_size")
        _clear_parameter(self._input.parameters, "shared_memory_offset")
        return self

    def set_shared_memory(self, region_name, byte_size, offset=0):
        """Reference this input's data from a registered shared-memory
        region (system or XLA/TPU)."""
        self._raw_content = None
        self._input.ClearField("contents")
        _set_parameter(
            self._input.parameters, "shared_memory_region", region_name
        )
        _set_parameter(
            self._input.parameters, "shared_memory_byte_size", int(byte_size)
        )
        if offset:
            _set_parameter(
                self._input.parameters, "shared_memory_offset", int(offset)
            )
        return self

    def _get_tensor(self):
        return self._input

    def _get_content(self):
        return self._raw_content


class InferRequestedOutput:
    """A requested output for a gRPC inference request."""

    def __init__(self, name, class_count=0):
        self._output = pb.ModelInferRequest.InferRequestedOutputTensor()
        self._output.name = name
        if class_count:
            _set_parameter(
                self._output.parameters, "classification", int(class_count)
            )

    def name(self):
        return self._output.name

    def set_shared_memory(self, region_name, byte_size, offset=0):
        """Deliver this output into a registered shared-memory region."""
        self.unset_shared_memory()
        _set_parameter(
            self._output.parameters, "shared_memory_region", region_name
        )
        _set_parameter(
            self._output.parameters, "shared_memory_byte_size", int(byte_size)
        )
        if offset:
            _set_parameter(
                self._output.parameters, "shared_memory_offset", int(offset)
            )
        return self

    def unset_shared_memory(self):
        _clear_parameter(self._output.parameters, "shared_memory_region")
        _clear_parameter(self._output.parameters, "shared_memory_byte_size")
        _clear_parameter(self._output.parameters, "shared_memory_offset")
        return self

    def _get_tensor(self):
        return self._output
