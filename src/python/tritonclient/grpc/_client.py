"""Sync gRPC client for the KServe-v2 protocol — full surface of the
reference ``tritonclient.grpc.InferenceServerClient`` (grpc/_client.py:87+):
health, metadata, config, repository control, statistics, trace and log
settings, system/CUDA/XLA shared-memory registration, sync/async infer and
bidirectional (decoupled-capable) streaming.

TPU-first deltas from the reference: XlaSharedMemory* verbs replace the
CUDA-shm path as the on-device plane (CUDA verbs kept for API parity), and
InferInput accepts ``jax.Array``.
"""

import time

import grpc

from tritonclient._auxiliary import (  # noqa: F401 — RetryPolicy re-exported
    CONNECT_ERROR_DETAILS,
    RetryPolicy,
)
from tritonclient.utils import InferenceServerException, raise_error

from . import grpc_service_pb2 as pb
from ._infer_input import InferInput, InferRequestedOutput  # noqa: F401
from ._infer_result import InferResult
from ._infer_stream import _InferStream
from ._service import ServiceStub
from ._utils import (
    _get_inference_request,
    get_error_grpc,
    raise_error_grpc,
    retry_after_from_rpc_error,
)

# Reference grpc_client.cc:78-145 keeps a process-wide channel cache with a
# share count; grpc-python channels multiplex internally, so one channel per
# client is the idiomatic equivalent.  Keepalive mirrors KeepAliveOptions
# (reference grpc_client.h:61-82).


class KeepAliveOptions:
    """gRPC keepalive settings (reference grpc_client.h:61-82)."""

    def __init__(
        self,
        keepalive_time_ms=7200000,
        keepalive_timeout_ms=20000,
        keepalive_permit_without_calls=False,
        http2_max_pings_without_data=2,
    ):
        self.keepalive_time_ms = keepalive_time_ms
        self.keepalive_timeout_ms = keepalive_timeout_ms
        self.keepalive_permit_without_calls = keepalive_permit_without_calls
        self.http2_max_pings_without_data = http2_max_pings_without_data


#: gRPC codes the retry policy treats as overload rejections — the wire
#: twins of HTTP 429 (RESOURCE_EXHAUSTED) and 503 (UNAVAILABLE; also what
#: grpc-core surfaces for connection-refused, covering connection errors)
_RETRYABLE_CODES = frozenset(
    (grpc.StatusCode.RESOURCE_EXHAUSTED, grpc.StatusCode.UNAVAILABLE)
)


class InferenceServerClient:
    """A client talking KServe-v2 over gRPC to ``url`` (host:port).

    ``retry_policy`` (a ``tritonclient._auxiliary.RetryPolicy``) opts
    unary RPCs into exponential-backoff retries of RESOURCE_EXHAUSTED /
    UNAVAILABLE failures, honoring the server's ``retry-after``
    trailing metadata; DEADLINE_EXCEEDED and every other code propagate
    immediately.  Default None = no retries."""

    def __init__(
        self,
        url,
        verbose=False,
        ssl=False,
        root_certificates=None,
        private_key=None,
        certificate_chain=None,
        creds=None,
        keepalive_options=None,
        channel_args=None,
        retry_policy=None,
    ):
        if keepalive_options is None:
            keepalive_options = KeepAliveOptions()
        options = [
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
            ("grpc.keepalive_time_ms", keepalive_options.keepalive_time_ms),
            (
                "grpc.keepalive_timeout_ms",
                keepalive_options.keepalive_timeout_ms,
            ),
            (
                "grpc.keepalive_permit_without_calls",
                int(keepalive_options.keepalive_permit_without_calls),
            ),
            (
                "grpc.http2.max_pings_without_data",
                keepalive_options.http2_max_pings_without_data,
            ),
        ]
        for arg in channel_args or []:
            options.append(arg)
        if creds is not None:
            self._channel = grpc.secure_channel(url, creds, options=options)
        elif ssl:
            rc = open(root_certificates, "rb").read() if (
                root_certificates
            ) else None
            pk = open(private_key, "rb").read() if private_key else None
            cc = open(certificate_chain, "rb").read() if (
                certificate_chain
            ) else None
            credentials = grpc.ssl_channel_credentials(
                root_certificates=rc, private_key=pk, certificate_chain=cc
            )
            self._channel = grpc.secure_channel(
                url, credentials, options=options
            )
        else:
            self._channel = grpc.insecure_channel(url, options=options)
        self._stub = ServiceStub(self._channel)
        self._url = url
        self._channel_options = options
        self._secure = creds is not None or ssl
        self._verbose = verbose
        self._stream = None
        self._retry_policy = retry_policy

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, type_, value, traceback):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def close(self):
        """Close the client: stop any active stream and the channel."""
        self.stop_stream()
        self._channel.close()

    def _rebind(self, url):
        """Re-point this client at ``url`` (insecure channels only):
        close the current channel and open a fresh one.  The
        ``generate_stream`` fallback rotation uses this between
        reconnect attempts — the single bidi-stream slot is empty at
        that point, so no in-flight RPC rides the old channel."""
        if url == self._url:
            return
        if self._secure:
            raise_error(
                "fallback_urls requires insecure channels (per-url TLS "
                "material cannot be assumed to transfer)")
        self._channel.close()
        self._channel = grpc.insecure_channel(
            url, options=self._channel_options)
        self._stub = ServiceStub(self._channel)
        self._url = url

    # -- helpers -----------------------------------------------------------

    def _metadata(self, headers):
        if headers is None:
            return None
        return tuple(headers.items())

    @staticmethod
    def _is_connect_failure(rpc_error):
        """Whether an UNAVAILABLE provably failed before the request
        left the client (grpc-core's connect-phase detail strings,
        shared with the pool's classifier).  Best-effort: an
        unrecognized detail is treated as possibly mid-call, i.e. NOT
        safely retryable."""
        try:
            details = (rpc_error.details() or "").lower()
        except Exception:
            return False
        return any(marker in details for marker in CONNECT_ERROR_DETAILS)

    @staticmethod
    def _retry_after_of(rpc_error):
        """The server's ``retry-after`` trailing-metadata value (the
        gRPC twin of the HTTP header), or None."""
        return retry_after_from_rpc_error(rpc_error)

    def _call(self, name, request, headers=None, timeout=None):
        if self._verbose:
            print("{}, metadata {}\n{}".format(name, headers, request))
        policy = self._retry_policy
        # the retry loop's wall-clock budget: the sooner of the caller's
        # RPC timeout and the policy's max_total_s — a server Retry-After
        # hint may never sleep past either
        budget_s = None
        if policy is not None:
            if timeout is not None:
                budget_s = float(timeout)
            if policy.max_total_s is not None:
                budget_s = (
                    policy.max_total_s
                    if budget_s is None
                    else min(budget_s, policy.max_total_s)
                )
        budget_deadline = (
            time.monotonic() + budget_s if budget_s is not None else None
        )
        attempt = 0
        while True:
            try:
                response = getattr(self._stub, name)(
                    request=request,
                    metadata=self._metadata(headers),
                    timeout=timeout,
                )
                if self._verbose:
                    print(response)
                return response
            except grpc.RpcError as rpc_error:
                # retry only typed overload/unreachable rejections (the
                # server shed the request before work, or never saw it);
                # DEADLINE_EXCEEDED and everything else may have
                # executed server-side and must propagate.
                # UNAVAILABLE conflates a server-typed 503, a connect
                # failure, AND a mid-call reset (the dangerous one): it
                # is retryable only when the server's retry-after
                # trailer proves a typed shed, or when the detail
                # string marks a connect-phase failure (the request
                # never left the client).
                code = rpc_error.code() if policy is not None else None
                retry_after = (
                    self._retry_after_of(rpc_error)
                    if code in _RETRYABLE_CODES
                    else None
                )
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    retryable = True
                elif code == grpc.StatusCode.UNAVAILABLE:
                    retryable = retry_after is not None or (
                        policy.retry_connection_errors
                        and self._is_connect_failure(rpc_error)
                    )
                else:
                    retryable = False
                remaining = (
                    budget_deadline - time.monotonic()
                    if budget_deadline is not None
                    else None
                )
                if (
                    retryable
                    and attempt + 1 < policy.max_attempts
                    and (remaining is None or remaining > 0)
                ):
                    time.sleep(
                        policy.backoff_s(attempt, retry_after, remaining)
                    )
                    attempt += 1
                    continue
                raise_error_grpc(rpc_error)

    @staticmethod
    def _as_json(message, as_json):
        if not as_json:
            return message
        from google.protobuf import json_format

        return json_format.MessageToDict(
            message, preserving_proto_field_name=True
        )

    # -- health / metadata -------------------------------------------------

    def is_server_live(self, headers=None, client_timeout=None):
        return self._call(
            "ServerLive", pb.ServerLiveRequest(), headers, client_timeout
        ).live

    def is_server_ready(self, headers=None, client_timeout=None):
        return self._call(
            "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
        ).ready

    def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ):
        return self._call(
            "ModelReady",
            pb.ModelReadyRequest(name=model_name, version=model_version),
            headers,
            client_timeout,
        ).ready

    def get_server_metadata(
        self, headers=None, as_json=False, client_timeout=None
    ):
        return self._as_json(
            self._call(
                "ServerMetadata", pb.ServerMetadataRequest(), headers,
                client_timeout,
            ),
            as_json,
        )

    def get_model_metadata(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "ModelMetadata",
                pb.ModelMetadataRequest(
                    name=model_name, version=model_version
                ),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def get_model_config(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "ModelConfig",
                pb.ModelConfigRequest(
                    name=model_name, version=model_version
                ),
                headers,
                client_timeout,
            ),
            as_json,
        )

    # -- repository --------------------------------------------------------

    def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        return self._as_json(
            self._call(
                "RepositoryIndex", pb.RepositoryIndexRequest(), headers,
                client_timeout,
            ),
            as_json,
        )

    def load_model(
        self, model_name, headers=None, config=None, files=None,
        client_timeout=None,
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        for path, content in (files or {}).items():
            request.parameters[path].bytes_param = content
        self._call("RepositoryModelLoad", request, headers, client_timeout)

    def unload_model(
        self, model_name, headers=None, unload_dependents=False,
        client_timeout=None,
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = (
            unload_dependents
        )
        self._call("RepositoryModelUnload", request, headers, client_timeout)

    # -- statistics / settings ---------------------------------------------

    def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "ModelStatistics",
                pb.ModelStatisticsRequest(
                    name=model_name, version=model_version
                ),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def update_trace_settings(
        self, model_name=None, settings=None, headers=None, as_json=False,
        client_timeout=None,
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                request.settings[key].Clear()
                continue
            if isinstance(value, (list, tuple)):
                request.settings[key].value.extend(str(v) for v in value)
            else:
                request.settings[key].value.append(str(value))
        return self._as_json(
            self._call("TraceSetting", request, headers, client_timeout),
            as_json,
        )

    def get_trace_settings(
        self, model_name=None, headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "TraceSetting",
                pb.TraceSettingRequest(model_name=model_name or ""),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def update_log_settings(
        self, settings, headers=None, as_json=False, client_timeout=None
    ):
        request = pb.LogSettingsRequest()
        for key, value in settings.items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            elif isinstance(value, str):
                request.settings[key].string_param = value
            else:
                raise_error(
                    "unsupported log setting type for '{}'".format(key)
                )
        return self._as_json(
            self._call("LogSettings", request, headers, client_timeout),
            as_json,
        )

    def get_log_settings(
        self, headers=None, as_json=False, client_timeout=None
    ):
        return self._as_json(
            self._call(
                "LogSettings", pb.LogSettingsRequest(), headers,
                client_timeout,
            ),
            as_json,
        )

    def get_metrics(self, headers=None, client_timeout=None):
        """The server's Prometheus text exposition via the
        ServerMetrics-style unary — byte-identical to the HTTP
        frontend's ``GET /metrics`` (the gRPC twin of scraping it)."""
        resp = self._call(
            "ServerMetrics", pb.ServerMetadataRequest(), headers,
            client_timeout,
        )
        return resp.settings["metrics"].string_param

    # -- shared memory -----------------------------------------------------

    def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "SystemSharedMemoryStatus",
                pb.SystemSharedMemoryStatusRequest(name=region_name),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None,
        client_timeout=None,
    ):
        self._call(
            "SystemSharedMemoryRegister",
            pb.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size
            ),
            headers,
            client_timeout,
        )

    def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        self._call(
            "SystemSharedMemoryUnregister",
            pb.SystemSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
        )

    def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        return self._as_json(
            self._call(
                "CudaSharedMemoryStatus",
                pb.CudaSharedMemoryStatusRequest(name=region_name),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None,
        client_timeout=None,
    ):
        self._call(
            "CudaSharedMemoryRegister",
            pb.CudaSharedMemoryRegisterRequest(
                name=name, raw_handle=raw_handle, device_id=device_id,
                byte_size=byte_size,
            ),
            headers,
            client_timeout,
        )

    def unregister_cuda_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        self._call(
            "CudaSharedMemoryUnregister",
            pb.CudaSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
        )

    def get_xla_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        """Status of registered XLA/TPU shared-memory regions (the TPU
        generalization of the CUDA-shm verbs, reference grpc_client.h:365)."""
        return self._as_json(
            self._call(
                "XlaSharedMemoryStatus",
                pb.XlaSharedMemoryStatusRequest(name=region_name),
                headers,
                client_timeout,
            ),
            as_json,
        )

    def register_xla_shared_memory(
        self, name, raw_handle, device_ordinal, byte_size, headers=None,
        client_timeout=None,
    ):
        """Register a TPU HBM region by its serialized handle (see
        tritonclient.utils.xla_shared_memory.get_raw_handle)."""
        self._call(
            "XlaSharedMemoryRegister",
            pb.XlaSharedMemoryRegisterRequest(
                name=name, raw_handle=raw_handle,
                device_ordinal=device_ordinal, byte_size=byte_size,
            ),
            headers,
            client_timeout,
        )

    def unregister_xla_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        self._call(
            "XlaSharedMemoryUnregister",
            pb.XlaSharedMemoryUnregisterRequest(name=name),
            headers,
            client_timeout,
        )

    # -- inference ---------------------------------------------------------

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        parameters=None,
    ):
        """Synchronous inference (reference grpc/_client.py:1248)."""
        request = _get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        response = self._call("ModelInfer", request, headers, client_timeout)
        return InferResult(response)

    def async_infer(
        self,
        model_name,
        inputs,
        callback,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        parameters=None,
    ):
        """Asynchronous inference; ``callback(result, error)`` fires on a
        gRPC completion thread (reference grpc/_client.py:1392)."""
        request = _get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        if self._verbose:
            print("async_infer\n{}".format(request))
        future = self._stub.ModelInfer.future(
            request=request,
            metadata=self._metadata(headers),
            timeout=client_timeout,
        )

        def done(fut):
            try:
                response = fut.result()
                if self._verbose:
                    print(response)
                callback(InferResult(response), None)
            except grpc.RpcError as rpc_error:
                callback(None, get_error_grpc(rpc_error))
            except Exception as e:
                callback(None, InferenceServerException(str(e)))

        future.add_done_callback(done)
        return future

    # -- streaming ---------------------------------------------------------

    def start_stream(
        self, callback, stream_timeout=None, headers=None,
        compression_algorithm=None,
    ):
        """Open the bidirectional ModelStreamInfer stream; responses (and
        stream errors) are delivered to ``callback(result, error)``
        (reference grpc/_client.py:1520)."""
        if self._stream is not None:
            raise_error(
                "cannot start another stream with one already active"
            )
        self._stream = _InferStream(callback, self._verbose)
        try:
            response_iterator = self._stub.ModelStreamInfer(
                self._stream._request_iterator,
                metadata=self._metadata(headers),
                timeout=stream_timeout,
                compression=compression_algorithm,
            )
            self._stream._init_handler(response_iterator)
        except grpc.RpcError as rpc_error:
            self._stream = None
            raise_error_grpc(rpc_error)

    def stop_stream(self, cancel_requests=False):
        """Close the active stream, if any."""
        if self._stream is not None:
            self._stream.close(cancel_requests)
            self._stream = None

    def async_stream_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        enable_empty_final_response=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Enqueue a request on the active stream (reference
        grpc/_client.py:1586)."""
        if self._stream is None:
            raise_error("stream not available, use start_stream() first")
        request = _get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        if enable_empty_final_response:
            request.parameters[
                "triton_enable_empty_final_response"
            ].bool_param = True
        if self._verbose:
            print("async_stream_infer\n{}".format(request))
        self._stream._enqueue_request(request)

    def generate_stream(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        parameters=None,
        headers=None,
        resume=True,
        max_reconnects=5,
        reconnect_backoff_s=0.05,
        read_timeout=600.0,
        on_reconnect=None,
        fallback_urls=None,
    ):
        """Synchronous generator over ONE decoupled generation with
        transparent reconnect+resume, yielding an ``InferResult`` per
        streamed response (the terminal empty-final response is
        consumed, not yielded).

        ``fallback_urls`` (``host:port`` peers — a respawned server on
        a new address, or sibling endpoints fronting the same fleet)
        makes each reconnect attempt rotate through the target list by
        re-binding the channel (insecure channels only): a
        connect-refused primary retries the resume against the peer
        under the same ``max_reconnects`` + backoff budget, because
        behind a resilient fleet seq continuity — not endpoint
        identity — is the resume contract.

        Owns the client's single bidi-stream slot for the call's
        duration (``start_stream`` semantics — raises if a stream is
        already active).  Each response of a resumable server
        generation carries ``generation_id`` and the 0-based token
        ``seq`` in its response parameters; on a *stream-level* failure
        (RpcError — the transport died) the call re-opens the stream
        and sends a resume request (``resume_generation_id`` +
        ``resume_from_seq``), the server replays the missed tokens and
        splices the live continuation, and duplicates are dropped by
        ``seq`` — no duplicated or missing tokens.  Resume is
        **same-endpoint only** (replay state is replica-local).
        In-band ``error_message`` responses raise immediately — those
        are typed server failures (quarantined slot, expired resume
        id), not transport faults.  ``on_reconnect(attempt, exc)``
        fires before each reattempt."""
        if self._stream is not None:
            raise_error(
                "cannot generate_stream with a stream already active"
            )
        base_params = dict(parameters or {})
        gen_id = base_params.get("generation_id")
        # reconnect target rotation (attempt N re-binds the channel to
        # targets[N % len]); validated up front so a bad url fails the
        # call, not a mid-generation reconnect
        targets = [self._url]
        for fb in fallback_urls or ():
            if not isinstance(fb, str) or ":" not in fb:
                raise_error(
                    "fallback_urls entries must be host:port strings "
                    "(got {!r})".format(fb))
            targets.append(fb)
        if len(targets) > 1 and self._secure:
            raise_error(
                "fallback_urls requires insecure channels (per-url TLS "
                "material cannot be assumed to transfer)")

        class _StreamDropped(Exception):
            def __init__(self, error):
                self.error = error

        try:
            yield from self._generate_stream_rotating(
                targets, model_name, inputs, model_version, outputs,
                request_id, base_params, headers, resume,
                max_reconnects, reconnect_backoff_s, read_timeout,
                on_reconnect, gen_id, _StreamDropped)
        finally:
            # the rotation must not outlive the call: a client left
            # bound to the last fallback would silently route every
            # later RPC (and its owner pool's breaker accounting) at
            # the wrong endpoint
            if len(targets) > 1:
                self._rebind(targets[0])

    def _generate_stream_rotating(
            self, targets, model_name, inputs, model_version, outputs,
            request_id, base_params, headers, resume, max_reconnects,
            reconnect_backoff_s, read_timeout, on_reconnect, gen_id,
            _StreamDropped):
        import queue as _queue

        last_seq = -1
        yielded_any = False
        attempt = 0
        while True:
            if len(targets) > 1:
                self._rebind(targets[attempt % len(targets)])
            responses = _queue.Queue()
            try:
                try:
                    self.start_stream(
                        lambda result, error: responses.put(
                            (result, error)),
                        headers=headers,
                    )
                    send_params = dict(base_params)
                    sent_resume = gen_id is not None and last_seq >= 0
                    if sent_resume:
                        # mid-generation reconnect: ask the server to
                        # replay from the first seq we have not seen
                        send_params.pop("generation_id", None)
                        send_params["resume_generation_id"] = gen_id
                        send_params["resume_from_seq"] = last_seq + 1
                    self.async_stream_infer(
                        model_name,
                        inputs,
                        model_version=model_version,
                        outputs=outputs,
                        request_id=request_id,
                        enable_empty_final_response=True,
                        parameters=send_params,
                    )
                except InferenceServerException as e:
                    # the just-opened stream died before (or while) the
                    # request was enqueued — a transport-level failure
                    # (in-band server errors never deactivate the
                    # stream), so it rides the same reconnect path;
                    # prefer the stream's own delivered error (e.g.
                    # "connection refused") over the generic
                    # stream-invalid message
                    try:
                        _, delivered = responses.get_nowait()
                    except _queue.Empty:
                        delivered = None
                    raise _StreamDropped(delivered or e)
                while True:
                    try:
                        result, error = responses.get(timeout=read_timeout)
                    except _queue.Empty:
                        raise InferenceServerException(
                            "generate_stream: no response within "
                            "{}s".format(read_timeout))
                    if error is not None:
                        if getattr(error, "status", lambda: None)() is None:
                            if (sent_resume and "unknown or expired "
                                    "generation id" in str(error)):
                                # OUR resume named a generation this
                                # server does not (yet) hold — under a
                                # fleet router that's a transition
                                # (restart, handoff in progress), not a
                                # verdict: seq continuity is the resume
                                # contract, not endpoint identity, so
                                # ride the reconnect path bounded by
                                # max_reconnects
                                raise _StreamDropped(error)
                            # in-band server error: terminal
                            raise error
                        raise _StreamDropped(error)
                    resp = result.get_response()
                    final = resp.parameters.get("triton_final_response")
                    if final is not None and final.bool_param:
                        return
                    if "generation_id" in resp.parameters:
                        gen_id = resp.parameters[
                            "generation_id"].string_param
                    if "seq" in resp.parameters:
                        seq = resp.parameters["seq"].int64_param
                        if seq <= last_seq:
                            continue  # replayed duplicate
                        last_seq = seq
                    yielded_any = True
                    yield result
            except _StreamDropped as drop:
                # resume is only safe with a resume token (the server
                # marked the generation resumable) OR before anything
                # was delivered (a fresh re-send cannot duplicate);
                # re-running a non-resumable generation after yielding
                # tokens would duplicate them
                attempt += 1
                if (not resume or attempt > max_reconnects
                        or (yielded_any and (gen_id is None
                                             or last_seq < 0))):
                    if yielded_any and (gen_id is None or last_seq < 0):
                        raise InferenceServerException(
                            "stream lost mid-generation and the "
                            "generation is not resumable (no "
                            "generation_id/seq on its responses): "
                            "{}".format(drop.error))
                    raise drop.error
                if on_reconnect is not None:
                    on_reconnect(attempt, drop.error)
                time.sleep(
                    min(reconnect_backoff_s * (2 ** (attempt - 1)), 2.0))
            finally:
                self.stop_stream(cancel_requests=True)
