"""Bidirectional streaming machinery (reference grpc/_infer_stream.py:35-179).

``_InferStream`` owns the ModelStreamInfer call: requests are fed from a
queue through ``_RequestIterator`` (the gRPC request iterator), responses
are drained by a daemon thread that invokes the user callback with
``(InferResult | None, InferenceServerException | None)`` — decoupled
models may produce zero or many responses per request.
"""

import queue
import threading

import grpc

from tritonclient.utils import InferenceServerException

from ._infer_result import InferResult
from ._utils import get_error_grpc


class _RequestIterator:
    """Iterator over enqueued ModelInferRequest protos; blocks until the
    stream is closed with a None sentinel."""

    def __init__(self):
        self._queue = queue.Queue()

    def put(self, request):
        self._queue.put(request)

    def __iter__(self):
        return self

    def __next__(self):
        request = self._queue.get()
        if request is None:
            raise StopIteration
        return request


class _InferStream:
    """One open ModelStreamInfer bidi stream."""

    def __init__(self, callback, verbose=False):
        self._callback = callback
        self._verbose = verbose
        self._request_iterator = _RequestIterator()
        self._response_iterator = None
        self._handler = None
        self._active = True

    def _init_handler(self, response_iterator):
        self._response_iterator = response_iterator
        self._handler = threading.Thread(
            target=self._process_response, daemon=True
        )
        self._handler.start()

    def _enqueue_request(self, request):
        if not self._active:
            raise InferenceServerException(
                "The stream is no longer in valid state, the error detail "
                "is reported through provided callback. A new stream should "
                "be started after stopping the current stream."
            )
        self._request_iterator.put(request)

    def _process_response(self):
        """[handler thread] deliver each stream response to the callback;
        a dead stream surfaces the error once and deactivates."""
        try:
            for response in self._response_iterator:
                if self._verbose:
                    print(response)
                if response.error_message:
                    self._callback(
                        None,
                        InferenceServerException(response.error_message),
                    )
                else:
                    self._callback(
                        InferResult(response.infer_response), None
                    )
        except grpc.RpcError as rpc_error:
            self._active = False
            if rpc_error.code() != grpc.StatusCode.CANCELLED:
                self._callback(None, get_error_grpc(rpc_error))
        except Exception as e:  # stream death must reach the user
            self._active = False
            self._callback(None, InferenceServerException(str(e)))

    def close(self, cancel_requests=False):
        """Close the stream: stop the request feed and join the reader."""
        if cancel_requests and self._response_iterator is not None:
            self._response_iterator.cancel()
        self._request_iterator.put(None)
        self._active = False
        if self._handler is not None:
            self._handler.join()
            self._handler = None
