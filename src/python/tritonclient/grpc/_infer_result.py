"""Inference result wrapper for the gRPC client (reference grpc/_client.py
InferResult), numpy/BF16/BYTES aware."""

import numpy as np

from tritonclient.utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)

from . import grpc_service_pb2 as pb


class InferResult:
    """Wraps a ModelInferResponse and exposes numpy access to outputs."""

    def __init__(self, result):
        self._result = result

    @classmethod
    def from_response(cls, response):
        return cls(response)

    def as_numpy(self, name):
        """The output tensor as a numpy array, or None if not present (e.g.
        delivered via shared memory)."""
        index = 0
        for output in self._result.outputs:
            if output.name == name:
                shape = list(output.shape)
                if "shared_memory_region" in output.parameters:
                    # delivered via shared memory: read it from the region
                    return None
                if index < len(self._result.raw_output_contents):
                    raw = self._result.raw_output_contents[index]
                    if output.datatype == "BYTES":
                        return deserialize_bytes_tensor(raw).reshape(shape)
                    if output.datatype == "BF16":
                        return deserialize_bf16_tensor(raw).reshape(shape)
                    np_dtype = triton_to_np_dtype(output.datatype)
                    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)
                # typed contents fallback
                c = output.contents
                for field in (
                    "bool_contents", "int_contents", "int64_contents",
                    "uint_contents", "uint64_contents", "fp32_contents",
                    "fp64_contents", "bytes_contents",
                ):
                    vals = getattr(c, field)
                    if len(vals):
                        if field == "bytes_contents":
                            return np.array(
                                list(vals), dtype=np.object_
                            ).reshape(shape)
                        np_dtype = triton_to_np_dtype(output.datatype)
                        return np.array(vals, dtype=np_dtype).reshape(shape)
                return None
            index += 1
        return None

    def get_output(self, name, as_json=False):
        """The InferOutputTensor protobuf (or dict) for ``name``."""
        for output in self._result.outputs:
            if output.name == name:
                if as_json:
                    from google.protobuf import json_format

                    return json_format.MessageToDict(
                        output, preserving_proto_field_name=True
                    )
                return output
        return None

    def get_response(self, as_json=False):
        if as_json:
            from google.protobuf import json_format

            return json_format.MessageToDict(
                self._result, preserving_proto_field_name=True
            )
        return self._result
