"""tritonclient.grpc.aio — asyncio gRPC client (reference
grpc/aio/__init__.py:67-829).

Same method surface as the sync client but awaitable, and ``stream_infer``
is an async generator over the bidirectional ModelStreamInfer stream
yielding ``(InferResult | None, InferenceServerException | None)`` —
decoupled-model friendly (reference aio/__init__.py:729-829).
"""

import asyncio
import time

import grpc

from tritonclient._auxiliary import RetryPolicy  # noqa: F401
from tritonclient.grpc import grpc_service_pb2 as pb
from tritonclient.grpc._client import (  # noqa: F401
    _RETRYABLE_CODES,
    KeepAliveOptions,
)
from tritonclient.grpc._infer_input import (  # noqa: F401
    InferInput,
    InferRequestedOutput,
)
from tritonclient.grpc._infer_result import InferResult
from tritonclient.grpc._service import ServiceStub
from tritonclient.grpc._utils import (
    _get_inference_request,
    get_error_grpc,
    raise_error_grpc,
    retry_after_from_rpc_error,
)
from tritonclient.utils import InferenceServerException, raise_error


class InferenceServerClient:
    """Asyncio client talking KServe-v2 over gRPC to ``url`` (host:port)."""

    def __init__(
        self,
        url,
        verbose=False,
        ssl=False,
        root_certificates=None,
        private_key=None,
        certificate_chain=None,
        creds=None,
        keepalive_options=None,
        channel_args=None,
        retry_policy=None,
    ):
        # same unary-RPC classification the sync client applies
        # (tritonclient.grpc._client._call): RESOURCE_EXHAUSTED always
        # retries, UNAVAILABLE only when a retry-after trailer proves a
        # typed shed or the detail string marks a connect-phase failure
        self._retry_policy = retry_policy
        if keepalive_options is None:
            keepalive_options = KeepAliveOptions()
        options = [
            ("grpc.max_send_message_length", -1),
            ("grpc.max_receive_message_length", -1),
            ("grpc.keepalive_time_ms", keepalive_options.keepalive_time_ms),
            (
                "grpc.keepalive_timeout_ms",
                keepalive_options.keepalive_timeout_ms,
            ),
            (
                "grpc.keepalive_permit_without_calls",
                int(keepalive_options.keepalive_permit_without_calls),
            ),
            (
                "grpc.http2.max_pings_without_data",
                keepalive_options.http2_max_pings_without_data,
            ),
        ]
        for arg in channel_args or []:
            options.append(arg)
        if creds is not None:
            self._channel = grpc.aio.secure_channel(
                url, creds, options=options
            )
        elif ssl:
            rc = open(root_certificates, "rb").read() if (
                root_certificates
            ) else None
            pk = open(private_key, "rb").read() if private_key else None
            cc = open(certificate_chain, "rb").read() if (
                certificate_chain
            ) else None
            credentials = grpc.ssl_channel_credentials(
                root_certificates=rc, private_key=pk, certificate_chain=cc
            )
            self._channel = grpc.aio.secure_channel(
                url, credentials, options=options
            )
        else:
            self._channel = grpc.aio.insecure_channel(url, options=options)
        self._stub = ServiceStub(self._channel)
        self._verbose = verbose

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        await self.close()

    async def close(self):
        await self._channel.close()

    # -- helpers -----------------------------------------------------------

    def _metadata(self, headers):
        if headers is None:
            return None
        return tuple(headers.items())

    @staticmethod
    def _is_connect_failure(rpc_error):
        from tritonclient.grpc._client import InferenceServerClient as _Sync

        return _Sync._is_connect_failure(rpc_error)

    async def _call(self, name, request, headers=None, timeout=None):
        """One unary RPC with the opt-in retry policy applied — the
        asyncio twin of the sync client's ``_call``: RESOURCE_EXHAUSTED
        always retries (a typed shed), UNAVAILABLE only when the
        retry-after trailer proves a shed or the detail marks a
        connect-phase failure; DEADLINE_EXCEEDED and every other code
        may have executed server-side and propagates immediately."""
        if self._verbose:
            print("{}, metadata {}\n{}".format(name, headers, request))
        policy = self._retry_policy
        # the retry loop's wall-clock budget: the sooner of the
        # caller's RPC timeout and the policy's max_total_s
        budget_s = None
        if policy is not None:
            if timeout is not None:
                budget_s = float(timeout)
            if policy.max_total_s is not None:
                budget_s = (
                    policy.max_total_s
                    if budget_s is None
                    else min(budget_s, policy.max_total_s)
                )
        budget_deadline = (
            time.monotonic() + budget_s if budget_s is not None else None
        )
        attempt = 0
        while True:
            try:
                response = await getattr(self._stub, name)(
                    request, metadata=self._metadata(headers),
                    timeout=timeout,
                )
                if self._verbose:
                    print(response)
                return response
            except grpc.RpcError as rpc_error:
                code = rpc_error.code() if policy is not None else None
                retry_after = (
                    retry_after_from_rpc_error(rpc_error)
                    if code in _RETRYABLE_CODES
                    else None
                )
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    retryable = True
                elif code == grpc.StatusCode.UNAVAILABLE:
                    retryable = retry_after is not None or (
                        policy.retry_connection_errors
                        and self._is_connect_failure(rpc_error)
                    )
                else:
                    retryable = False
                remaining = (
                    budget_deadline - time.monotonic()
                    if budget_deadline is not None
                    else None
                )
                if (
                    retryable
                    and attempt + 1 < policy.max_attempts
                    and (remaining is None or remaining > 0)
                ):
                    await asyncio.sleep(
                        policy.backoff_s(attempt, retry_after, remaining)
                    )
                    attempt += 1
                    continue
                raise_error_grpc(rpc_error)

    @staticmethod
    def _as_json(message, as_json):
        if not as_json:
            return message
        from google.protobuf import json_format

        return json_format.MessageToDict(
            message, preserving_proto_field_name=True
        )

    # -- health / metadata / repository / settings -------------------------

    async def is_server_live(self, headers=None, client_timeout=None):
        r = await self._call(
            "ServerLive", pb.ServerLiveRequest(), headers, client_timeout
        )
        return r.live

    async def is_server_ready(self, headers=None, client_timeout=None):
        r = await self._call(
            "ServerReady", pb.ServerReadyRequest(), headers, client_timeout
        )
        return r.ready

    async def is_model_ready(
        self, model_name, model_version="", headers=None, client_timeout=None
    ):
        r = await self._call(
            "ModelReady",
            pb.ModelReadyRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )
        return r.ready

    async def get_server_metadata(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "ServerMetadata", pb.ServerMetadataRequest(), headers,
            client_timeout,
        )
        return self._as_json(r, as_json)

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelMetadata",
            pb.ModelMetadataRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def get_model_config(
        self, model_name, model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelConfig",
            pb.ModelConfigRequest(name=model_name, version=model_version),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def get_model_repository_index(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "RepositoryIndex", pb.RepositoryIndexRequest(), headers,
            client_timeout,
        )
        return self._as_json(r, as_json)

    async def load_model(
        self, model_name, headers=None, config=None, files=None,
        client_timeout=None,
    ):
        request = pb.RepositoryModelLoadRequest(model_name=model_name)
        if config is not None:
            request.parameters["config"].string_param = config
        for path, content in (files or {}).items():
            request.parameters[path].bytes_param = content
        await self._call(
            "RepositoryModelLoad", request, headers, client_timeout
        )

    async def unload_model(
        self, model_name, headers=None, unload_dependents=False,
        client_timeout=None,
    ):
        request = pb.RepositoryModelUnloadRequest(model_name=model_name)
        request.parameters["unload_dependents"].bool_param = (
            unload_dependents
        )
        await self._call(
            "RepositoryModelUnload", request, headers, client_timeout
        )

    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "ModelStatistics",
            pb.ModelStatisticsRequest(
                name=model_name, version=model_version
            ),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def update_trace_settings(
        self, model_name=None, settings=None, headers=None, as_json=False,
        client_timeout=None,
    ):
        request = pb.TraceSettingRequest(model_name=model_name or "")
        for key, value in (settings or {}).items():
            if value is None:
                request.settings[key].Clear()
            elif isinstance(value, (list, tuple)):
                request.settings[key].value.extend(str(v) for v in value)
            else:
                request.settings[key].value.append(str(value))
        r = await self._call(
            "TraceSetting", request, headers, client_timeout
        )
        return self._as_json(r, as_json)

    async def get_trace_settings(
        self, model_name=None, headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "TraceSetting",
            pb.TraceSettingRequest(model_name=model_name or ""),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def update_log_settings(
        self, settings, headers=None, as_json=False, client_timeout=None
    ):
        request = pb.LogSettingsRequest()
        for key, value in settings.items():
            if isinstance(value, bool):
                request.settings[key].bool_param = value
            elif isinstance(value, int):
                request.settings[key].uint32_param = value
            elif isinstance(value, str):
                request.settings[key].string_param = value
            else:
                raise_error(
                    "unsupported log setting type for '{}'".format(key)
                )
        r = await self._call("LogSettings", request, headers, client_timeout)
        return self._as_json(r, as_json)

    async def get_log_settings(
        self, headers=None, as_json=False, client_timeout=None
    ):
        r = await self._call(
            "LogSettings", pb.LogSettingsRequest(), headers, client_timeout
        )
        return self._as_json(r, as_json)

    # -- shared memory -----------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "SystemSharedMemoryStatus",
            pb.SystemSharedMemoryStatusRequest(name=region_name),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None,
        client_timeout=None,
    ):
        await self._call(
            "SystemSharedMemoryRegister",
            pb.SystemSharedMemoryRegisterRequest(
                name=name, key=key, offset=offset, byte_size=byte_size
            ),
            headers, client_timeout,
        )

    async def unregister_system_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        await self._call(
            "SystemSharedMemoryUnregister",
            pb.SystemSharedMemoryUnregisterRequest(name=name),
            headers, client_timeout,
        )

    async def get_cuda_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "CudaSharedMemoryStatus",
            pb.CudaSharedMemoryStatusRequest(name=region_name),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None,
        client_timeout=None,
    ):
        await self._call(
            "CudaSharedMemoryRegister",
            pb.CudaSharedMemoryRegisterRequest(
                name=name, raw_handle=raw_handle, device_id=device_id,
                byte_size=byte_size,
            ),
            headers, client_timeout,
        )

    async def unregister_cuda_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        await self._call(
            "CudaSharedMemoryUnregister",
            pb.CudaSharedMemoryUnregisterRequest(name=name),
            headers, client_timeout,
        )

    async def get_xla_shared_memory_status(
        self, region_name="", headers=None, as_json=False,
        client_timeout=None,
    ):
        r = await self._call(
            "XlaSharedMemoryStatus",
            pb.XlaSharedMemoryStatusRequest(name=region_name),
            headers, client_timeout,
        )
        return self._as_json(r, as_json)

    async def register_xla_shared_memory(
        self, name, raw_handle, device_ordinal, byte_size, headers=None,
        client_timeout=None,
    ):
        await self._call(
            "XlaSharedMemoryRegister",
            pb.XlaSharedMemoryRegisterRequest(
                name=name, raw_handle=raw_handle,
                device_ordinal=device_ordinal, byte_size=byte_size,
            ),
            headers, client_timeout,
        )

    async def unregister_xla_shared_memory(
        self, name="", headers=None, client_timeout=None
    ):
        await self._call(
            "XlaSharedMemoryUnregister",
            pb.XlaSharedMemoryUnregisterRequest(name=name),
            headers, client_timeout,
        )

    # -- inference ---------------------------------------------------------

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        client_timeout=None,
        headers=None,
        parameters=None,
    ):
        request = _get_inference_request(
            model_name=model_name,
            inputs=inputs,
            model_version=model_version,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            parameters=parameters,
        )
        response = await self._call(
            "ModelInfer", request, headers, client_timeout
        )
        return InferResult(response)

    async def stream_infer(
        self,
        inputs_iterator,
        stream_timeout=None,
        headers=None,
        compression_algorithm=None,
    ):
        """Async generator over ModelStreamInfer.

        ``inputs_iterator`` is an async iterator of dicts with the ``infer``
        kwargs (model_name, inputs, outputs, request_id, sequence_*,
        enable_empty_final_response, ...); yields ``(result, error)`` pairs
        as responses arrive (reference grpc/aio/__init__.py:729-829)."""

        async def request_iterator():
            async for kwargs in inputs_iterator:
                if not isinstance(kwargs, dict):
                    raise InferenceServerException(
                        "inputs_iterator must yield dicts of infer args"
                    )
                enable_final = kwargs.pop(
                    "enable_empty_final_response", False
                )
                request = _get_inference_request(**kwargs)
                if enable_final:
                    request.parameters[
                        "triton_enable_empty_final_response"
                    ].bool_param = True
                yield request

        try:
            call = self._stub.ModelStreamInfer(
                request_iterator(),
                metadata=self._metadata(headers),
                timeout=stream_timeout,
                compression=compression_algorithm,
            )
            async for response in call:
                if self._verbose:
                    print(response)
                if response.error_message:
                    yield None, InferenceServerException(
                        response.error_message
                    )
                else:
                    yield InferResult(response.infer_response), None
        except grpc.RpcError as rpc_error:
            if rpc_error.code() != grpc.StatusCode.CANCELLED:
                yield None, get_error_grpc(rpc_error)
