"""Sync HTTP/REST client for the KServe-v2 protocol.

Re-implements the full surface of reference http/_client.py:94-1600.  The
reference rides a geventhttpclient connection pool with gevent greenlets for
``async_infer``; this implementation keeps the same semantics on a stdlib
``http.client`` keep-alive connection pool plus a thread pool — no monkey
patching, and it composes cleanly with jax (which gevent does not).
"""

import base64
import json
import queue
import socket
import ssl as ssl_module
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from urllib.parse import quote, urlparse

from tritonclient._auxiliary import InferStat, RequestTimers, RetryPolicy
from tritonclient.http._infer_input import InferInput
from tritonclient.http._infer_result import InferResult
from tritonclient.http._requested_output import InferRequestedOutput
from tritonclient.http._utils import (
    _compress_request_body,
    _get_error_message,
    _get_inference_request,
    _get_query_string,
)
from tritonclient.utils import InferenceServerException, raise_error

__all__ = [
    "InferenceServerClient",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "InferAsyncRequest",
    "RetryPolicy",
]


class InferAsyncRequest:
    """Handle for an in-flight ``async_infer`` request; ``get_result()``
    blocks until the response arrives (reference http/_client.py:40-92)."""

    def __init__(self, future, verbose=False):
        self._future = future
        self._verbose = verbose

    def get_result(self, block=True, timeout=None):
        """Get the InferResult (or raise the request's exception)."""
        if not block and not self._future.done():
            raise_error("request not yet completed")
        return self._future.result(timeout=timeout)

    def cancelled(self):
        return self._future.cancelled()


class _PooledConnection:
    """A keep-alive HTTP/1.1 connection with raw send/recv helpers.

    Plain-HTTP requests ride a hand-rolled socket path: stdlib
    http.client burns ~250 us/request in its email-module header parser,
    which dominates small-tensor infer latency (the reference picks
    geventhttpclient's C parser for the same reason,
    reference http/_client.py:155-180).  HTTPS falls back to
    http.client for its TLS plumbing.
    """

    def __init__(self, scheme, host, port, connection_timeout, network_timeout,
                 ssl_context):
        self._scheme = scheme
        self._host = host
        self._port = port
        self._connection_timeout = connection_timeout
        self._network_timeout = network_timeout
        self._conn = None  # https fallback (http.client connection)
        self._sock = None
        self._buf = bytearray()
        if scheme == "https":
            import http.client

            self._conn = http.client.HTTPSConnection(
                host, port, timeout=connection_timeout, context=ssl_context
            )

    # -- https fallback ----------------------------------------------------

    def _request_https(self, method, path, body, headers):
        if self._conn.sock is None:
            self._conn.connect()
        self._conn.sock.settimeout(self._network_timeout)
        self._conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conn.request(method, path, body=body, headers=headers)
        resp = self._conn.getresponse()
        return resp.status, dict(resp.headers), resp.read()

    # -- raw-socket fast path ---------------------------------------------

    def _connect(self):
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connection_timeout
        )
        self._sock.settimeout(self._network_timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = bytearray()

    def _read_more(self):
        chunk = self._sock.recv(65536)
        if not chunk:
            raise ConnectionError("connection closed by server")
        self._buf += chunk  # bytearray += is amortized in-place

    def _read_exact(self, n):
        if len(self._buf) >= n:
            out = bytes(self._buf[:n])
            del self._buf[:n]
            return out
        # large read: drain the buffer, then recv_into the remainder
        out = bytearray(n)
        have = len(self._buf)
        out[:have] = self._buf
        del self._buf[:]
        view = memoryview(out)
        while have < n:
            got = self._sock.recv_into(view[have:])
            if not got:
                raise ConnectionError("connection closed by server")
            have += got
        return bytes(out)

    def _read_line(self):
        start = 0
        while True:
            eol = self._buf.find(b"\r\n", start)
            if eol >= 0:
                line = bytes(self._buf[:eol])
                del self._buf[:eol + 2]
                return line
            start = max(0, len(self._buf) - 1)
            self._read_more()

    @staticmethod
    def _check_header(key, value):
        text = "{}{}".format(key, value)
        if "\r" in text or "\n" in text:
            raise ValueError(
                "invalid CR/LF in header {!r}".format(key))

    def request(self, method, path, body, headers):
        if self._conn is not None:
            return self._request_https(method, path, body, headers)
        if self._sock is None:
            self._connect()
        if "\r" in path or "\n" in path or " " in path:
            raise ValueError("invalid characters in request path")
        head = [
            "{} {} HTTP/1.1".format(method, path),
            "Host: {}:{}".format(self._host, self._port),
        ]
        for key, value in headers.items():
            self._check_header(key, value)
            head.append("{}: {}".format(key, value))
        request = "\r\n".join(head).encode("latin-1") + b"\r\n\r\n"
        if body and hasattr(self._sock, "sendmsg"):
            # writev without concatenating the (possibly large) body;
            # sendmsg may send partially, so advance views until drained
            views = [memoryview(request), memoryview(body)]
            while views:
                sent = self._sock.sendmsg(views)
                while views and sent >= len(views[0]):
                    sent -= len(views[0])
                    views.pop(0)
                if views and sent:
                    views[0] = views[0][sent:]
        elif body:
            # sendmsg is Unix-only; fall back to two sendalls (still no
            # concatenation copy of the body)
            self._sock.sendall(request)
            self._sock.sendall(body)
        else:
            self._sock.sendall(request)

        while True:
            status_line = self._read_line()
            parts = status_line.split(None, 2)
            status = int(parts[1])
            resp_headers = {}
            while True:
                line = self._read_line()
                if not line:
                    break
                key, _, value = line.partition(b":")
                resp_headers[key.decode("latin-1").strip()] = (
                    value.decode("latin-1").strip()
                )
            if 100 <= status < 200:
                # interim response (e.g. a solicited 100 Continue):
                # bodiless by definition; the real response follows on
                # the same connection
                continue
            break
        lowered = {k.lower(): v for k, v in resp_headers.items()}
        if status in (204, 304):
            resp_body = b""  # bodiless by status (RFC 9112 6.3)
        elif lowered.get("transfer-encoding", "").lower() == "chunked":
            pieces = []
            while True:
                size = int(self._read_line().split(b";")[0], 16)
                if size == 0:
                    while self._read_line():  # trailers until blank line
                        pass
                    break
                pieces.append(self._read_exact(size))
                self._read_exact(2)  # CRLF after each chunk
            resp_body = b"".join(pieces)
        elif "content-length" in lowered:
            resp_body = self._read_exact(int(lowered["content-length"]))
        else:  # no framing: read to close
            try:
                while True:
                    self._read_more()
            except ConnectionError:
                pass
            resp_body = bytes(self._buf)
            self._buf = bytearray()
            self.close()
        if lowered.get("connection", "").lower() == "close":
            self.close()
        return status, resp_headers, resp_body

    def close(self):
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass
        if self._sock is not None:
            try:
                self._sock.close()
            except Exception:
                pass
            self._sock = None
        self._buf = bytearray()


class InferenceServerClient:
    """Client to the HTTP/REST endpoints of an inference server.

    Parameters
    ----------
    url : str
        ``host:port`` of the server (no scheme), e.g. ``"localhost:8000"``.
    verbose : bool
        If True print request/response details.
    concurrency : int
        Number of pooled connections (and worker threads for async_infer).
    connection_timeout : float
        Connect timeout in seconds.
    network_timeout : float
        Read timeout in seconds.
    ssl : bool
        Use HTTPS.
    ssl_options : dict
        Optional keys ``keyfile``, ``certfile``, ``ca_certs``.
    insecure : bool
        If True skip certificate verification.
    ssl_context_factory : callable
        Factory returning an ``ssl.SSLContext`` (overrides ssl_options).
    retry_policy : tritonclient._auxiliary.RetryPolicy
        Opt-in retries: exponential backoff with jitter, honoring
        ``Retry-After``, retrying ONLY connection errors and typed
        overload rejections (429/503) — never timeouts, which may have
        executed server-side.  Default None = no retries (the
        historical behavior).
    """

    def __init__(
        self,
        url,
        verbose=False,
        concurrency=1,
        connection_timeout=60.0,
        network_timeout=60.0,
        max_greenlets=None,
        ssl=False,
        ssl_options=None,
        ssl_context_factory=None,
        insecure=False,
        retry_policy=None,
    ):
        # Set first so close()/__del__ are safe even if __init__ raises below.
        self._closed = True
        if url.startswith("http://") or url.startswith("https://"):
            raise_error("url should not include the scheme")
        scheme = "https" if ssl else "http"
        parsed = urlparse(scheme + "://" + url)
        self._host = parsed.hostname
        self._port = parsed.port or (443 if ssl else 80)
        self._base_path = parsed.path.rstrip("/")
        self._scheme = scheme
        self._verbose = verbose
        self._concurrency = max(1, concurrency)
        self._connection_timeout = connection_timeout
        self._network_timeout = network_timeout

        self._ssl_context = None
        if ssl:
            if ssl_context_factory is not None:
                self._ssl_context = ssl_context_factory()
            else:
                ctx = ssl_module.create_default_context()
                if ssl_options:
                    if "ca_certs" in ssl_options:
                        ctx.load_verify_locations(ssl_options["ca_certs"])
                    if "certfile" in ssl_options:
                        ctx.load_cert_chain(
                            ssl_options["certfile"],
                            ssl_options.get("keyfile"),
                        )
                if insecure:
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl_module.CERT_NONE
                self._ssl_context = ctx

        self._retry_policy = retry_policy
        self._pool = queue.LifoQueue()
        for _ in range(self._concurrency):
            self._pool.put(None)  # lazily created
        self._executor = None
        self._executor_lock = threading.Lock()
        self._infer_stat = InferStat()
        self._closed = False

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, type_, value, traceback):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            # interpreter shutdown: queue internals may already be torn
            # down (queue.Empty raises through a half-collected module)
            pass

    def close(self, _empty=queue.Empty):
        """Close the client: drain the pool and stop worker threads.

        ``queue.Empty`` is bound as a default so ``__del__`` during
        interpreter shutdown (module globals already torn down) still works.
        """
        if self._closed:
            return
        self._closed = True
        if self._executor is not None:
            self._executor.shutdown(wait=True)
        while True:
            try:
                conn = self._pool.get_nowait()
            except _empty:
                break
            if conn is not None:
                conn.close()

    # -- low-level transport ----------------------------------------------

    def _new_connection(self):
        return _PooledConnection(
            self._scheme,
            self._host,
            self._port,
            self._connection_timeout,
            self._network_timeout,
            self._ssl_context,
        )

    def _request(self, method, request_uri, body=None, headers=None,
                 query_params=None):
        """One logical request, with the opt-in retry policy applied.

        Only two failure classes ever retry (see RetryPolicy): the
        connection could not be ESTABLISHED (refused/unresolvable — the
        server provably never saw the request) and typed overload
        statuses (429/503 — the server shed the request before doing
        work).  Timeouts and mid-response drops propagate immediately:
        the server may have executed the request, and resending a
        non-idempotent infer would double-execute it.
        """
        policy = self._retry_policy
        if policy is None:
            return self._request_once(
                method, request_uri, body, headers, query_params
            )
        # the logical-call budget: no backoff sleep may extend past it,
        # so a large server Retry-After hint cannot park the caller
        # beyond its own deadline
        budget_deadline = (
            time.monotonic() + policy.max_total_s
            if policy.max_total_s is not None
            else None
        )

        def _remaining():
            if budget_deadline is None:
                return None
            return budget_deadline - time.monotonic()

        attempt = 0
        while True:
            try:
                status, resp_headers, resp_body = self._request_once(
                    method, request_uri, body, headers, query_params
                )
            except (ConnectionRefusedError, socket.gaierror) as e:
                # connect-phase failure only: a ConnectionError AFTER
                # the request was sent (reset mid-response) is NOT here
                # — the server may have executed it
                remaining = _remaining()
                if (
                    not policy.retry_connection_errors
                    or attempt + 1 >= policy.max_attempts
                    or (remaining is not None and remaining <= 0)
                ):
                    raise
                time.sleep(policy.backoff_s(attempt, None, remaining))
                attempt += 1
                continue
            remaining = _remaining()
            if (
                status in policy.retryable_statuses
                and attempt + 1 < policy.max_attempts
                and (remaining is None or remaining > 0)
            ):
                retry_after = {
                    k.lower(): v for k, v in resp_headers.items()
                }.get("retry-after")
                time.sleep(policy.backoff_s(attempt, retry_after, remaining))
                attempt += 1
                continue
            return status, resp_headers, resp_body

    def _request_once(self, method, request_uri, body=None, headers=None,
                      query_params=None):
        path = self._base_path + "/" + request_uri
        if query_params is not None:
            path = path + "?" + _get_query_string(query_params)
        if self._verbose:
            print(f"{method} {path}, headers {headers}")
        hdrs = dict(headers) if headers else {}
        if body is not None and "Content-Length" not in hdrs:
            hdrs["Content-Length"] = str(len(body))
        import http.client as _http_client

        conn = self._pool.get()
        try:
            fresh = conn is None
            if fresh:
                conn = self._new_connection()
            try:
                status, resp_headers, resp_body = conn.request(
                    method, path, body, hdrs
                )
            except (ConnectionError, OSError,
                    _http_client.HTTPException) as e:
                conn.close()
                # Retry exactly once, and only when the failure is a stale
                # keep-alive connection (pooled conn, not a timeout): a
                # timeout may mean the server already executed this —
                # resending a non-idempotent infer would double-execute it.
                if fresh or isinstance(e, socket.timeout):
                    raise
                conn = self._new_connection()
                try:
                    status, resp_headers, resp_body = conn.request(
                        method, path, body, hdrs
                    )
                except Exception:
                    conn.close()
                    raise
        except Exception:
            self._pool.put(None)
            raise
        else:
            self._pool.put(conn)
        if self._verbose:
            print(status, resp_headers)
        return status, resp_headers, resp_body

    def _get(self, request_uri, headers=None, query_params=None):
        return self._request("GET", request_uri, None, headers, query_params)

    def _post(self, request_uri, request_body, headers=None,
              query_params=None):
        return self._request(
            "POST", request_uri, request_body, headers, query_params
        )

    @staticmethod
    def _raise_if_error(status, response_body, response_headers=None):
        if status != 200:
            retry_after = None
            if response_headers:
                # carried onto the exception so retry/failover layers
                # (tritonclient._pool) can honor the server's cooldown
                retry_after = {
                    k.lower(): v for k, v in response_headers.items()
                }.get("retry-after")
            raise InferenceServerException(
                msg=_get_error_message(response_body),
                status=str(status),
                retry_after=retry_after,
            )

    def _get_json(self, request_uri, headers=None, query_params=None):
        status, resp_headers, body = self._get(
            request_uri, headers, query_params
        )
        self._raise_if_error(status, body, resp_headers)
        content = json.loads(body) if body else {}
        if self._verbose:
            print(content)
        return content

    def _post_json(self, request_uri, request=None, headers=None,
                   query_params=None):
        body = json.dumps(request).encode("utf-8") if request is not None else b""
        status, resp_headers, resp_body = self._post(
            request_uri, body, headers, query_params
        )
        self._raise_if_error(status, resp_body, resp_headers)
        content = json.loads(resp_body) if resp_body else {}
        if self._verbose:
            print(content)
        return content

    # -- health / metadata -------------------------------------------------

    def is_server_live(self, headers=None, query_params=None):
        """Contact the server's liveness endpoint; returns bool."""
        status, _, _ = self._get("v2/health/live", headers, query_params)
        return status == 200

    def is_server_ready(self, headers=None, query_params=None):
        """Contact the server's readiness endpoint; returns bool."""
        status, _, _ = self._get("v2/health/ready", headers, query_params)
        return status == 200

    def is_model_ready(self, model_name, model_version="", headers=None,
                       query_params=None):
        """Contact the model's readiness endpoint; returns bool."""
        if model_version:
            uri = "v2/models/{}/versions/{}/ready".format(
                quote(model_name), model_version
            )
        else:
            uri = "v2/models/{}/ready".format(quote(model_name))
        status, _, _ = self._get(uri, headers, query_params)
        return status == 200

    def get_server_metadata(self, headers=None, query_params=None):
        """Get server metadata as a dict."""
        return self._get_json("v2", headers, query_params)

    def get_model_metadata(self, model_name, model_version="", headers=None,
                           query_params=None):
        """Get model metadata as a dict."""
        if model_version:
            uri = "v2/models/{}/versions/{}".format(
                quote(model_name), model_version
            )
        else:
            uri = "v2/models/{}".format(quote(model_name))
        return self._get_json(uri, headers, query_params)

    def get_model_config(self, model_name, model_version="", headers=None,
                         query_params=None):
        """Get model configuration as a dict."""
        if model_version:
            uri = "v2/models/{}/versions/{}/config".format(
                quote(model_name), model_version
            )
        else:
            uri = "v2/models/{}/config".format(quote(model_name))
        return self._get_json(uri, headers, query_params)

    # -- repository control ------------------------------------------------

    def get_model_repository_index(self, headers=None, query_params=None):
        """Get the index of the model repository (list of dicts)."""
        return self._post_json(
            "v2/repository/index", None, headers, query_params
        )

    def load_model(self, model_name, headers=None, query_params=None,
                   config=None, files=None):
        """Request the server to load or reload the model.

        ``config`` is an optional JSON config string override; ``files`` maps
        file paths to base64 content for repository override (reference
        grpc_client.h:232-256 / http/_client.py load_model).
        """
        load_request = {}
        if config is not None or files is not None:
            load_request["parameters"] = {}
        if config is not None:
            load_request["parameters"]["config"] = config
        if files is not None:
            for path, content in files.items():
                load_request["parameters"][path] = base64.b64encode(
                    content
                ).decode("utf-8")
        self._post_json(
            "v2/repository/models/{}/load".format(quote(model_name)),
            load_request if load_request else None,
            headers,
            query_params,
        )

    def unload_model(self, model_name, headers=None, query_params=None,
                     unload_dependents=False):
        """Request the server to unload the model."""
        unload_request = {
            "parameters": {"unload_dependents": unload_dependents}
        }
        self._post_json(
            "v2/repository/models/{}/unload".format(quote(model_name)),
            unload_request,
            headers,
            query_params,
        )

    # -- statistics / trace / logging -------------------------------------

    def get_inference_statistics(self, model_name="", model_version="",
                                 headers=None, query_params=None):
        """Get per-model inference statistics as a dict."""
        if model_name:
            if model_version:
                uri = "v2/models/{}/versions/{}/stats".format(
                    quote(model_name), model_version
                )
            else:
                uri = "v2/models/{}/stats".format(quote(model_name))
        else:
            uri = "v2/models/stats"
        return self._get_json(uri, headers, query_params)

    def update_trace_settings(self, model_name=None, settings={},
                              headers=None, query_params=None):
        """Update trace settings (server-global or per-model)."""
        if model_name is not None and model_name != "":
            uri = "v2/models/{}/trace/setting".format(quote(model_name))
        else:
            uri = "v2/trace/setting"
        return self._post_json(uri, settings, headers, query_params)

    def get_trace_settings(self, model_name=None, headers=None,
                           query_params=None):
        """Get trace settings (server-global or per-model)."""
        if model_name is not None and model_name != "":
            uri = "v2/models/{}/trace/setting".format(quote(model_name))
        else:
            uri = "v2/trace/setting"
        return self._get_json(uri, headers, query_params)

    def update_log_settings(self, settings, headers=None, query_params=None):
        """Update the server's log settings."""
        return self._post_json("v2/logging", settings, headers, query_params)

    def get_log_settings(self, headers=None, query_params=None):
        """Get the server's log settings."""
        return self._get_json("v2/logging", headers, query_params)

    # -- shared memory -----------------------------------------------------

    def get_system_shared_memory_status(self, region_name="", headers=None,
                                        query_params=None):
        """Get the status of registered system shared-memory regions."""
        if region_name:
            uri = "v2/systemsharedmemory/region/{}/status".format(
                quote(region_name)
            )
        else:
            uri = "v2/systemsharedmemory/status"
        return self._get_json(uri, headers, query_params)

    def register_system_shared_memory(self, name, key, byte_size, offset=0,
                                      headers=None, query_params=None):
        """Register a system (POSIX) shared-memory region with the server."""
        register_request = {
            "key": key,
            "offset": offset,
            "byte_size": byte_size,
        }
        self._post_json(
            "v2/systemsharedmemory/region/{}/register".format(quote(name)),
            register_request,
            headers,
            query_params,
        )
        if self._verbose:
            print("Registered system shared memory with name '{}'".format(name))

    def unregister_system_shared_memory(self, name="", headers=None,
                                        query_params=None):
        """Unregister one (or all, if name empty) system shm regions."""
        if name:
            uri = "v2/systemsharedmemory/region/{}/unregister".format(
                quote(name)
            )
        else:
            uri = "v2/systemsharedmemory/unregister"
        self._post_json(uri, None, headers, query_params)
        if self._verbose:
            if name:
                print(
                    "Unregistered system shared memory with name '{}'".format(
                        name
                    )
                )
            else:
                print("Unregistered all system shared memory regions")

    def get_cuda_shared_memory_status(self, region_name="", headers=None,
                                      query_params=None):
        """Get the status of registered CUDA shared-memory regions."""
        if region_name:
            uri = "v2/cudasharedmemory/region/{}/status".format(
                quote(region_name)
            )
        else:
            uri = "v2/cudasharedmemory/status"
        return self._get_json(uri, headers, query_params)

    def register_cuda_shared_memory(self, name, raw_handle, device_id,
                                    byte_size, headers=None,
                                    query_params=None):
        """Register a CUDA shared-memory region; ``raw_handle`` is the
        base64-encoded serialized cudaIpcMemHandle_t."""
        register_request = {
            "raw_handle": {"b64": raw_handle.decode("utf-8")
                           if isinstance(raw_handle, bytes) else raw_handle},
            "device_id": device_id,
            "byte_size": byte_size,
        }
        self._post_json(
            "v2/cudasharedmemory/region/{}/register".format(quote(name)),
            register_request,
            headers,
            query_params,
        )
        if self._verbose:
            print("Registered cuda shared memory with name '{}'".format(name))

    def unregister_cuda_shared_memory(self, name="", headers=None,
                                      query_params=None):
        """Unregister one (or all, if name empty) CUDA shm regions."""
        if name:
            uri = "v2/cudasharedmemory/region/{}/unregister".format(quote(name))
        else:
            uri = "v2/cudasharedmemory/unregister"
        self._post_json(uri, None, headers, query_params)

    def get_xla_shared_memory_status(self, region_name="", headers=None,
                                     query_params=None):
        """Get the status of registered XLA/TPU shared-memory regions.

        TPU-native analogue of ``get_cuda_shared_memory_status`` (reference
        http_client.h:411-442)."""
        if region_name:
            uri = "v2/xlasharedmemory/region/{}/status".format(
                quote(region_name)
            )
        else:
            uri = "v2/xlasharedmemory/status"
        return self._get_json(uri, headers, query_params)

    def register_xla_shared_memory(self, name, raw_handle, device_ordinal,
                                   byte_size, headers=None, query_params=None):
        """Register an XLA/TPU-HBM shared-memory region with the server.

        ``raw_handle`` is the base64-encoded serialized XlaShmHandle produced
        by ``tritonclient.utils.xla_shared_memory.get_raw_handle``."""
        register_request = {
            "raw_handle": {"b64": raw_handle.decode("utf-8")
                           if isinstance(raw_handle, bytes) else raw_handle},
            "device_ordinal": device_ordinal,
            "byte_size": byte_size,
        }
        self._post_json(
            "v2/xlasharedmemory/region/{}/register".format(quote(name)),
            register_request,
            headers,
            query_params,
        )
        if self._verbose:
            print("Registered xla shared memory with name '{}'".format(name))

    def unregister_xla_shared_memory(self, name="", headers=None,
                                     query_params=None):
        """Unregister one (or all, if name empty) XLA/TPU shm regions."""
        if name:
            uri = "v2/xlasharedmemory/region/{}/unregister".format(quote(name))
        else:
            uri = "v2/xlasharedmemory/unregister"
        self._post_json(uri, None, headers, query_params)

    # -- inference ---------------------------------------------------------

    @staticmethod
    def generate_request_body(
        inputs,
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Generate an inference request body without sending it (reference
        http/_client.py:1207-1260).  Returns (body_bytes, header_length)."""
        return _get_inference_request(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )

    @staticmethod
    def parse_response_body(response_body, verbose=False, header_length=None,
                            content_encoding=None):
        """Parse a raw inference response body into an InferResult."""
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding
        )

    def _infer_uri(self, model_name, model_version):
        if model_version:
            return "v2/models/{}/versions/{}/infer".format(
                quote(model_name), model_version
            )
        return "v2/models/{}/infer".format(quote(model_name))

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
    ):
        """Run a synchronous inference; returns an InferResult.

        Mirrors reference http/_client.py:1315-1462 (binary-tensor protocol,
        optional gzip/deflate compression both ways).
        """
        timers = RequestTimers()
        timers.request_start()
        request_body, json_size = _get_inference_request(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )

        hdrs = dict(headers) if headers else {}
        if request_compression_algorithm == "gzip":
            hdrs["Content-Encoding"] = "gzip"
            request_body = _compress_request_body("gzip", request_body)
        elif request_compression_algorithm == "deflate":
            hdrs["Content-Encoding"] = "deflate"
            request_body = _compress_request_body("deflate", request_body)
        if response_compression_algorithm == "gzip":
            hdrs["Accept-Encoding"] = "gzip"
        elif response_compression_algorithm == "deflate":
            hdrs["Accept-Encoding"] = "deflate"
        if json_size is not None:
            hdrs["Inference-Header-Content-Length"] = str(json_size)
        hdrs.setdefault("Content-Type", "application/octet-stream")

        timers.send_start()
        try:
            status, resp_headers, response_body = self._post(
                self._infer_uri(model_name, model_version),
                request_body,
                hdrs,
                query_params,
            )
            timers.send_end()
            self._raise_if_error(status, response_body, resp_headers)
        except Exception:
            self._infer_stat.update(timers, success=False)
            raise

        header_length = resp_headers.get("Inference-Header-Content-Length")
        content_encoding = resp_headers.get("Content-Encoding")
        timers.recv_start()
        result = InferResult.from_response_body(
            response_body,
            self._verbose,
            int(header_length) if header_length is not None else None,
            content_encoding,
        )
        timers.recv_end()
        timers.request_end()
        self._infer_stat.update(timers, success=True)
        return result

    def generate_stream(
        self,
        model_name,
        inputs,
        model_version="",
        parameters=None,
        request_id="",
        headers=None,
        resume=True,
        max_reconnects=5,
        reconnect_backoff_s=0.05,
        read_timeout=600.0,
        on_reconnect=None,
        fallback_urls=None,
    ):
        """Stream a decoupled generation over ``/generate_stream`` SSE,
        yielding one dict per event (the KServe generate-response JSON:
        ``outputs`` plus, for resumable generations, ``parameters`` with
        ``generation_id`` and the 0-based token ``seq``).

        With ``resume=True`` (default) a connection dropped
        *mid-generation* transparently reconnects: the client re-POSTs
        the same body with the SSE-standard ``Last-Event-ID`` header
        (``<generation_id>/<seq>`` of the last event received), the
        server replays the missed tokens from its replay buffer and
        splices the live continuation — no duplicated or missing
        tokens.  Against a bare replica resume is same-endpoint only
        (generation replay state is replica-local); behind a fleet
        router the contract is **seq continuity, not endpoint
        identity** — so ``fallback_urls`` (``host:port`` peers, e.g.
        the warm-standby router or the supervisor's respawn address)
        makes each reconnect rotate through the target list: a
        connect-refused primary (router SIGKILLed) retries the resume
        against the peer under the same ``max_reconnects`` + backoff
        budget.  Up to ``max_reconnects`` reattempts with exponential
        backoff; ``on_reconnect(attempt, exc)`` is called before each
        one (perf tooling counts resumes through it).  In-band
        ``{"error": ...}`` events raise InferenceServerException
        without reconnecting — those are typed server-side failures
        (e.g. a quarantined slot), not transport faults.

        Typed-status handling across targets: 404 on a RESUME and
        429/503 anywhere before the terminal event are transitions
        (router restart, standby not yet promoted, momentary
        saturation) and ride the reconnect path; a 404 on the FIRST
        request stays terminal — the model/endpoint genuinely is not
        there.

        ``inputs`` is a dict name -> numpy array (serialized as JSON
        data — generation prompts are small); ``parameters`` are the
        request parameters (``eos_id``, ``generation_id``, ...).
        """
        import http.client as _http_client

        import numpy as np

        from tritonclient.utils import np_to_triton_dtype

        def _input_json(name, arr):
            if isinstance(arr, dict) and "shared_memory_region" in arr:
                # a shared-memory reference (the zero-copy data plane):
                # the prompt ids live in a registered region; the wire
                # carries only this descriptor
                return {
                    "name": name,
                    "shape": list(arr["shape"]),
                    "datatype": arr["datatype"],
                    "parameters": {
                        "shared_memory_region":
                            arr["shared_memory_region"],
                        "shared_memory_byte_size":
                            arr["shared_memory_byte_size"],
                        "shared_memory_offset":
                            arr.get("shared_memory_offset", 0),
                    },
                }
            return {
                "name": name,
                "shape": list(np.asarray(arr).shape),
                "datatype": ("BYTES"
                             if np.asarray(arr).dtype == np.object_
                             else np_to_triton_dtype(
                                 np.asarray(arr).dtype)),
                "data": [
                    v.decode("utf-8") if isinstance(v, bytes) else v
                    for v in np.asarray(arr).reshape(-1).tolist()
                ],
            }

        body_json = {
            "inputs": [
                _input_json(name, arr) for name, arr in inputs.items()
            ],
        }
        if request_id:
            body_json["id"] = request_id
        if parameters:
            body_json["parameters"] = dict(parameters)
        body = json.dumps(body_json)
        uri = "{}/v2/models/{}{}/generate_stream".format(
            self._base_path, quote(model_name),
            "/versions/{}".format(model_version) if model_version else "",
        )

        # reconnect target rotation: the primary first, then each
        # fallback router in turn (attempt N dials targets[N % len]);
        # validated up front — a malformed entry silently dropped
        # would degrade the supposed HA rotation to no-failover with
        # no signal until the first real outage
        targets = [(self._host, self._port)]
        for fb in fallback_urls or ():
            fb_host, sep, fb_port = str(fb).rpartition(":")
            if not (sep and fb_host and fb_port.isdigit()):
                raise InferenceServerException(
                    "fallback_urls entries must be host:port strings "
                    "(got {!r})".format(fb))
            targets.append((fb_host, int(fb_port)))

        last_event_id = None
        last_seq = -1
        yielded_any = False
        attempt = 0
        while True:
            t_host, t_port = targets[attempt % len(targets)]
            conn = (
                _http_client.HTTPSConnection(
                    t_host, t_port, timeout=read_timeout,
                    context=self._ssl_context)
                if self._scheme == "https"
                else _http_client.HTTPConnection(
                    t_host, t_port, timeout=read_timeout)
            )
            dropped = None
            try:
                hdrs = dict(headers) if headers else {}
                hdrs["Content-Type"] = "application/json"
                if last_event_id is not None:
                    hdrs["Last-Event-ID"] = last_event_id
                try:
                    conn.request("POST", uri, body, hdrs)
                    resp = conn.getresponse()
                except (ConnectionError, socket.timeout, OSError,
                        _http_client.HTTPException) as e:
                    dropped = e
                    resp = None
                if resp is not None:
                    transition = (
                        resp.status == 404 and last_event_id is not None
                    ) or (
                        resp.status in (429, 503)
                        and (last_event_id is not None or not yielded_any)
                    )
                    if transition:
                        # a RESUME answered 404 (server does not — yet —
                        # know this generation) or a typed overload
                        # (429/503: a router's shed valve, a standby
                        # router awaiting promotion, a busy serving
                        # slot) — under a fleet these are transitions,
                        # not verdicts (router restart/takeover,
                        # handoff in progress, momentary saturation):
                        # ride the reconnect path (rotating through
                        # fallback targets) and let the retries bound
                        # it.  429/503 retry even on a FIRST request
                        # that delivered nothing — re-POSTing an
                        # admission that never started cannot duplicate
                        # tokens; a first-request 404 stays terminal
                        # (the model/endpoint genuinely is not there).
                        reason = (
                            "resume target does not know generation"
                            if resp.status == 404
                            else "generation target is overloaded or "
                                 "standby")
                        dropped = InferenceServerException(
                            "{}: {}".format(
                                reason, _get_error_message(resp.read())),
                            status=str(resp.status),
                        )
                        resp = None
                    elif resp.status != 200:
                        raise InferenceServerException(
                            "generate_stream failed: {}".format(
                                _get_error_message(resp.read())),
                            status=str(resp.status),
                        )
                if resp is not None:
                    event_id = None
                    try:
                        for line in resp:
                            line = line.strip()
                            if line.startswith(b"id: "):
                                event_id = line[4:].decode(
                                    "utf-8", errors="replace")
                                continue
                            if not line.startswith(b"data: "):
                                continue
                            event = json.loads(line[len(b"data: "):])
                            if "error" in event:
                                # typed server failure: terminal, never
                                # ridden out by reconnecting
                                raise InferenceServerException(
                                    event["error"])
                            if event.get("final"):
                                return  # in-band end: generation done
                            seq = (event.get("parameters") or {}).get(
                                "seq")
                            if seq is not None and seq <= last_seq:
                                event_id = None
                                continue  # replayed duplicate
                            if seq is not None:
                                last_seq = seq
                            if event_id is not None:
                                last_event_id = event_id
                                event_id = None
                            yielded_any = True
                            yield event
                        # the stream ended WITHOUT the in-band terminal
                        # event: a mid-generation connection drop (a
                        # premature chunked EOF is not reliably an
                        # exception in stdlib http.client)
                        dropped = ConnectionError(
                            "stream ended without terminal event")
                    except (ConnectionError, socket.timeout, OSError,
                            _http_client.HTTPException) as e:
                        dropped = e
            finally:
                conn.close()
            # reconnect path: the stream died mid-flight.  Resume is
            # only safe when the server issued SSE ids (a resumable,
            # scheduler-backed generation) OR nothing was delivered yet
            # (a fresh re-send cannot duplicate); re-running a
            # non-resumable generation after yielding tokens would
            # duplicate them (and re-execute server-side effects like
            # KV-cache parking), so that fails instead.
            attempt += 1
            if (not resume or attempt > max_reconnects
                    or (yielded_any and last_event_id is None)):
                reason = (
                    " (resume disabled)" if not resume
                    else " (generation is not resumable: the server sent"
                         " no event ids)"
                    if yielded_any and last_event_id is None
                    else ""
                )
                if isinstance(dropped, InferenceServerException):
                    # retries exhausted on a typed answer (e.g. the
                    # resume 404 every reattempt repeated): surface it
                    # with its status intact
                    raise dropped
                raise InferenceServerException(
                    "generate_stream connection lost{}: {}".format(
                        reason, dropped))
            if on_reconnect is not None:
                on_reconnect(attempt, dropped)
            time.sleep(min(reconnect_backoff_s * (2 ** (attempt - 1)), 2.0))

    def async_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
    ):
        """Run inference on a worker thread; returns an InferAsyncRequest
        whose ``get_result()`` blocks for the InferResult (reference
        http/_client.py:1464-1600, gevent pool -> thread pool)."""
        with self._executor_lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self._concurrency,
                    thread_name_prefix="tritonclient-http",
                )
        future = self._executor.submit(
            self.infer,
            model_name,
            inputs,
            model_version,
            outputs,
            request_id,
            sequence_id,
            sequence_start,
            sequence_end,
            priority,
            timeout,
            headers,
            query_params,
            request_compression_algorithm,
            response_compression_algorithm,
            parameters,
        )
        return InferAsyncRequest(future, self._verbose)

    def get_inference_stat(self):
        """Client-side accumulated InferStat for this client's requests."""
        return self._infer_stat
