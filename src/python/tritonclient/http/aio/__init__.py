"""tritonclient.http.aio — asyncio HTTP client on aiohttp (reference
http/aio/__init__.py:42-789).

Shares the wire codec with the sync client: request bodies come from
``_get_inference_request`` (JSON header + binary-tensor sections), responses
are parsed by ``InferResult.from_response_body``.
"""

import asyncio
import gzip
import zlib
from urllib.parse import quote

import aiohttp

from tritonclient._auxiliary import RetryPolicy  # noqa: F401
from tritonclient.http._infer_input import InferInput  # noqa: F401
from tritonclient.http._infer_result import InferResult
from tritonclient.http._requested_output import (  # noqa: F401
    InferRequestedOutput,
)
from tritonclient.http._utils import _get_inference_request
from tritonclient.utils import InferenceServerException, raise_error


class InferenceServerClient:
    """Asyncio client for the KServe-v2 HTTP protocol at ``url``
    (host:port, no scheme) — full surface of the sync client, awaitable."""

    def __init__(
        self,
        url,
        verbose=False,
        conn_limit=100,
        conn_timeout=60.0,
        network_timeout=60.0,
        ssl=False,
        ssl_context=None,
        retry_policy=None,
    ):
        # same retry-vs-failover classification the sync client applies
        # (tritonclient.http._client._request): retry ONLY failures the
        # server provably did not complete — connect-phase errors and
        # typed overload statuses (429/503, honoring Retry-After)
        self._retry_policy = retry_policy
        scheme = "https" if ssl else "http"
        self._base_url = "{}://{}".format(scheme, url)
        # generate_stream dials absolute URLs (the primary plus each
        # fallback router) so it cannot ride the base_url session;
        # keep the pieces it needs to build per-target sessions
        self._scheme = scheme
        self._netloc = url
        self._stream_ssl = ssl_context if ssl else False
        self._verbose = verbose
        timeout = aiohttp.ClientTimeout(
            connect=conn_timeout, total=network_timeout
        )
        connector = aiohttp.TCPConnector(
            limit=conn_limit, ssl=ssl_context if ssl else False
        )
        self._session = aiohttp.ClientSession(
            base_url=self._base_url, timeout=timeout, connector=connector
        )

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        await self.close()

    async def close(self):
        await self._session.close()

    # -- plumbing ----------------------------------------------------------

    async def _request_once(self, method, uri, body, headers, query_params):
        if self._verbose:
            print("{} {}, headers {}".format(method, uri, headers))
        async with self._session.request(
            method, "/" + uri, data=body, headers=headers,
            params=query_params,
        ) as resp:
            rbody = await resp.read()
            return resp, rbody

    async def _request(self, method, uri, body=None, headers=None,
                       query_params=None):
        """One logical request with the opt-in retry policy applied —
        the asyncio twin of the sync client's ``_request``: only
        connect-phase failures (the server never saw the request) and
        typed overload statuses (429/503, Retry-After honored) ever
        retry; timeouts and mid-response drops propagate immediately
        because the server may have executed the request."""
        policy = self._retry_policy
        if policy is None:
            return await self._request_once(
                method, uri, body, headers, query_params
            )
        import time

        budget_deadline = (
            time.monotonic() + policy.max_total_s
            if policy.max_total_s is not None
            else None
        )

        def _remaining():
            if budget_deadline is None:
                return None
            return budget_deadline - time.monotonic()

        attempt = 0
        while True:
            try:
                resp, rbody = await self._request_once(
                    method, uri, body, headers, query_params
                )
            except aiohttp.ClientConnectorError:
                # connect-phase only (refused/unresolvable — aiohttp
                # types DNS and TCP connect failures here); an error
                # AFTER the request was sent is NOT retried
                remaining = _remaining()
                if (
                    not policy.retry_connection_errors
                    or attempt + 1 >= policy.max_attempts
                    or (remaining is not None and remaining <= 0)
                ):
                    raise
                await asyncio.sleep(
                    policy.backoff_s(attempt, None, remaining)
                )
                attempt += 1
                continue
            remaining = _remaining()
            if (
                resp.status in policy.retryable_statuses
                and attempt + 1 < policy.max_attempts
                and (remaining is None or remaining > 0)
            ):
                retry_after = resp.headers.get("Retry-After")
                await asyncio.sleep(
                    policy.backoff_s(attempt, retry_after, remaining)
                )
                attempt += 1
                continue
            return resp, rbody

    async def _get(self, uri, headers=None, query_params=None):
        return await self._request("GET", uri, None, headers, query_params)

    async def _post(self, uri, body, headers=None, query_params=None):
        return await self._request("POST", uri, body, headers, query_params)

    @staticmethod
    def _raise_if_error(resp, body):
        if resp.status >= 400:
            error_msg = body.decode("utf-8", errors="replace")
            try:
                import json

                error_msg = json.loads(error_msg)["error"]
            except Exception:
                pass
            raise InferenceServerException(
                msg=error_msg, status=str(resp.status)
            )

    async def _get_json(self, uri, headers=None, query_params=None):
        resp, body = await self._get(uri, headers, query_params)
        self._raise_if_error(resp, body)
        import json

        result = json.loads(body) if body else {}
        if self._verbose:
            print(result)
        return result

    async def _post_json(
        self, uri, request=None, headers=None, query_params=None
    ):
        import json

        body = json.dumps(request).encode("utf-8") if (
            request is not None
        ) else b""
        resp, rbody = await self._post(uri, body, headers, query_params)
        self._raise_if_error(resp, rbody)
        result = json.loads(rbody) if rbody else {}
        if self._verbose:
            print(result)
        return result

    # -- health / metadata -------------------------------------------------

    async def is_server_live(self, headers=None, query_params=None):
        resp, body = await self._get("v2/health/live", headers, query_params)
        return resp.status == 200

    async def is_server_ready(self, headers=None, query_params=None):
        resp, body = await self._get("v2/health/ready", headers, query_params)
        return resp.status == 200

    async def is_model_ready(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = "v2/models/{}".format(quote(model_name))
        if model_version:
            uri += "/versions/{}".format(model_version)
        resp, body = await self._get(uri + "/ready", headers, query_params)
        return resp.status == 200

    async def get_server_metadata(self, headers=None, query_params=None):
        return await self._get_json("v2", headers, query_params)

    async def get_model_metadata(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = "v2/models/{}".format(quote(model_name))
        if model_version:
            uri += "/versions/{}".format(model_version)
        return await self._get_json(uri, headers, query_params)

    async def get_model_config(
        self, model_name, model_version="", headers=None, query_params=None
    ):
        uri = "v2/models/{}".format(quote(model_name))
        if model_version:
            uri += "/versions/{}".format(model_version)
        return await self._get_json(uri + "/config", headers, query_params)

    # -- repository --------------------------------------------------------

    async def get_model_repository_index(
        self, headers=None, query_params=None
    ):
        return await self._post_json(
            "v2/repository/index", {}, headers, query_params
        )

    async def load_model(
        self, model_name, headers=None, query_params=None, config=None,
        files=None,
    ):
        import base64

        request = {}
        if config is not None or files:
            request["parameters"] = {}
            if config is not None:
                request["parameters"]["config"] = config
            for path, content in (files or {}).items():
                request["parameters"][path] = base64.b64encode(
                    content
                ).decode("utf-8")
        await self._post_json(
            "v2/repository/models/{}/load".format(quote(model_name)),
            request, headers, query_params,
        )

    async def unload_model(
        self, model_name, headers=None, query_params=None,
        unload_dependents=False,
    ):
        await self._post_json(
            "v2/repository/models/{}/unload".format(quote(model_name)),
            {"parameters": {"unload_dependents": unload_dependents}},
            headers, query_params,
        )

    # -- statistics / settings ---------------------------------------------

    async def get_inference_statistics(
        self, model_name="", model_version="", headers=None,
        query_params=None,
    ):
        if model_name:
            uri = "v2/models/{}".format(quote(model_name))
            if model_version:
                uri += "/versions/{}".format(model_version)
            uri += "/stats"
        else:
            uri = "v2/models/stats"
        return await self._get_json(uri, headers, query_params)

    async def update_trace_settings(
        self, model_name=None, settings=None, headers=None, query_params=None
    ):
        uri = "v2{}/trace/setting".format(
            "/models/" + quote(model_name) if model_name else ""
        )
        return await self._post_json(
            uri, settings or {}, headers, query_params
        )

    async def get_trace_settings(
        self, model_name=None, headers=None, query_params=None
    ):
        uri = "v2{}/trace/setting".format(
            "/models/" + quote(model_name) if model_name else ""
        )
        return await self._get_json(uri, headers, query_params)

    async def update_log_settings(
        self, settings, headers=None, query_params=None
    ):
        return await self._post_json(
            "v2/logging", settings, headers, query_params
        )

    async def get_log_settings(self, headers=None, query_params=None):
        return await self._get_json("v2/logging", headers, query_params)

    # -- shared memory -----------------------------------------------------

    async def get_system_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        uri = "v2/systemsharedmemory"
        if region_name:
            uri += "/region/{}".format(quote(region_name))
        return await self._get_json(uri + "/status", headers, query_params)

    async def register_system_shared_memory(
        self, name, key, byte_size, offset=0, headers=None, query_params=None
    ):
        await self._post_json(
            "v2/systemsharedmemory/region/{}/register".format(quote(name)),
            {"key": key, "offset": offset, "byte_size": byte_size},
            headers, query_params,
        )

    async def unregister_system_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/systemsharedmemory"
        if name:
            uri += "/region/{}".format(quote(name))
        await self._post_json(uri + "/unregister", {}, headers, query_params)

    async def get_cuda_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        uri = "v2/cudasharedmemory"
        if region_name:
            uri += "/region/{}".format(quote(region_name))
        return await self._get_json(uri + "/status", headers, query_params)

    async def register_cuda_shared_memory(
        self, name, raw_handle, device_id, byte_size, headers=None,
        query_params=None,
    ):
        await self._post_json(
            "v2/cudasharedmemory/region/{}/register".format(quote(name)),
            {
                "raw_handle": {
                    "b64": raw_handle.decode("utf-8")
                    if isinstance(raw_handle, bytes)
                    else raw_handle
                },
                "device_id": device_id,
                "byte_size": byte_size,
            },
            headers, query_params,
        )

    async def unregister_cuda_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/cudasharedmemory"
        if name:
            uri += "/region/{}".format(quote(name))
        await self._post_json(uri + "/unregister", {}, headers, query_params)

    async def get_xla_shared_memory_status(
        self, region_name="", headers=None, query_params=None
    ):
        uri = "v2/xlasharedmemory"
        if region_name:
            uri += "/region/{}".format(quote(region_name))
        return await self._get_json(uri + "/status", headers, query_params)

    async def register_xla_shared_memory(
        self, name, raw_handle, device_ordinal, byte_size, headers=None,
        query_params=None,
    ):
        await self._post_json(
            "v2/xlasharedmemory/region/{}/register".format(quote(name)),
            {
                "raw_handle": {
                    "b64": raw_handle.decode("utf-8")
                    if isinstance(raw_handle, bytes)
                    else raw_handle
                },
                "device_ordinal": device_ordinal,
                "byte_size": byte_size,
            },
            headers, query_params,
        )

    async def unregister_xla_shared_memory(
        self, name="", headers=None, query_params=None
    ):
        uri = "v2/xlasharedmemory"
        if name:
            uri += "/region/{}".format(quote(name))
        await self._post_json(uri + "/unregister", {}, headers, query_params)

    # -- inference ---------------------------------------------------------

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
    ):
        """Asynchronous inference; awaitable, returns InferResult."""
        body, json_size = _get_inference_request(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )
        headers = dict(headers or {})
        if json_size is not None:
            headers["Inference-Header-Content-Length"] = str(json_size)
        if request_compression_algorithm == "gzip":
            headers["Content-Encoding"] = "gzip"
            body = gzip.compress(body)
        elif request_compression_algorithm == "deflate":
            headers["Content-Encoding"] = "deflate"
            body = zlib.compress(body)
        if response_compression_algorithm:
            headers["Accept-Encoding"] = response_compression_algorithm

        if model_version:
            uri = "v2/models/{}/versions/{}/infer".format(
                quote(model_name), model_version
            )
        else:
            uri = "v2/models/{}/infer".format(quote(model_name))
        resp, rbody = await self._post(uri, body, headers, query_params)
        self._raise_if_error(resp, rbody)
        header_length = resp.headers.get("Inference-Header-Content-Length")
        # aiohttp decompresses Content-Encoding transparently
        return InferResult.from_response_body(
            rbody,
            self._verbose,
            int(header_length) if header_length is not None else None,
        )

    async def generate_stream(
        self,
        model_name,
        inputs,
        model_version="",
        parameters=None,
        request_id="",
        headers=None,
        resume=True,
        max_reconnects=5,
        reconnect_backoff_s=0.05,
        read_timeout=600.0,
        on_reconnect=None,
        fallback_urls=None,
    ):
        """Stream a decoupled generation over ``/generate_stream`` SSE —
        the asyncio twin of the sync client's ``generate_stream``
        (``async for event in client.generate_stream(...)``), with the
        same resume contract: a connection dropped *mid-generation*
        re-POSTs the body with ``Last-Event-ID`` and splices the
        replayed continuation, each reconnect rotating through the
        primary plus ``fallback_urls`` (``host:port`` peers — a warm
        standby, the sibling actives of a partitioned router tier).
        404 on a RESUME and 429/503 before the terminal event ride the
        reconnect path; a first-request 404 and in-band
        ``{"error": ...}`` events stay terminal.  ``on_reconnect``
        may be a plain callable or a coroutine function."""
        import json

        import numpy as np

        from tritonclient.utils import np_to_triton_dtype

        def _input_json(name, arr):
            if isinstance(arr, dict) and "shared_memory_region" in arr:
                return {
                    "name": name,
                    "shape": list(arr["shape"]),
                    "datatype": arr["datatype"],
                    "parameters": {
                        "shared_memory_region":
                            arr["shared_memory_region"],
                        "shared_memory_byte_size":
                            arr["shared_memory_byte_size"],
                        "shared_memory_offset":
                            arr.get("shared_memory_offset", 0),
                    },
                }
            return {
                "name": name,
                "shape": list(np.asarray(arr).shape),
                "datatype": ("BYTES"
                             if np.asarray(arr).dtype == np.object_
                             else np_to_triton_dtype(
                                 np.asarray(arr).dtype)),
                "data": [
                    v.decode("utf-8") if isinstance(v, bytes) else v
                    for v in np.asarray(arr).reshape(-1).tolist()
                ],
            }

        body_json = {
            "inputs": [
                _input_json(name, arr) for name, arr in inputs.items()
            ],
        }
        if request_id:
            body_json["id"] = request_id
        if parameters:
            body_json["parameters"] = dict(parameters)
        body = json.dumps(body_json)
        uri = "/v2/models/{}{}/generate_stream".format(
            quote(model_name),
            "/versions/{}".format(model_version) if model_version else "",
        )

        # reconnect target rotation, validated up front exactly like the
        # sync helper: a malformed entry silently dropped would degrade
        # the supposed HA rotation to no-failover with no signal
        targets = [self._netloc]
        for fb in fallback_urls or ():
            fb_host, sep, fb_port = str(fb).rpartition(":")
            if not (sep and fb_host and fb_port.isdigit()):
                raise InferenceServerException(
                    "fallback_urls entries must be host:port strings "
                    "(got {!r})".format(fb))
            targets.append("{}:{}".format(fb_host, int(fb_port)))

        def _error_message(raw):
            try:
                return json.loads(raw)["error"]
            except Exception:
                return raw.decode("utf-8", errors="replace")

        # a dedicated no-base_url session: generate_stream dials a
        # different host per attempt, which the base_url session rejects
        timeout = aiohttp.ClientTimeout(
            total=None, sock_connect=read_timeout, sock_read=read_timeout)
        session = aiohttp.ClientSession(timeout=timeout)
        last_event_id = None
        last_seq = -1
        yielded_any = False
        attempt = 0
        try:
            while True:
                target = targets[attempt % len(targets)]
                dropped = None
                resp = None
                try:
                    hdrs = dict(headers) if headers else {}
                    hdrs["Content-Type"] = "application/json"
                    if last_event_id is not None:
                        hdrs["Last-Event-ID"] = last_event_id
                    try:
                        resp = await session.post(
                            "{}://{}{}".format(
                                self._scheme, target, uri),
                            data=body, headers=hdrs,
                            ssl=self._stream_ssl)
                    except (aiohttp.ClientError, asyncio.TimeoutError,
                            OSError) as e:
                        dropped = e
                        resp = None
                    if resp is not None:
                        transition = (
                            resp.status == 404
                            and last_event_id is not None
                        ) or (
                            resp.status in (429, 503)
                            and (last_event_id is not None
                                 or not yielded_any)
                        )
                        if transition:
                            # same classification as the sync helper: a
                            # RESUME 404 or a typed overload is a fleet
                            # transition (router restart, standby not
                            # yet promoted, momentary saturation), not
                            # a verdict — ride the reconnect path
                            reason = (
                                "resume target does not know generation"
                                if resp.status == 404
                                else "generation target is overloaded "
                                     "or standby")
                            raw = await resp.read()
                            dropped = InferenceServerException(
                                "{}: {}".format(
                                    reason, _error_message(raw)),
                                status=str(resp.status),
                            )
                            resp.close()
                            resp = None
                        elif resp.status != 200:
                            raw = await resp.read()
                            raise InferenceServerException(
                                "generate_stream failed: {}".format(
                                    _error_message(raw)),
                                status=str(resp.status),
                            )
                    if resp is not None:
                        event_id = None
                        try:
                            async for line in resp.content:
                                line = line.strip()
                                if line.startswith(b"id: "):
                                    event_id = line[4:].decode(
                                        "utf-8", errors="replace")
                                    continue
                                if not line.startswith(b"data: "):
                                    continue
                                event = json.loads(line[len(b"data: "):])
                                if "error" in event:
                                    # typed server failure: terminal,
                                    # never ridden out by reconnecting
                                    raise InferenceServerException(
                                        event["error"])
                                if event.get("final"):
                                    return  # in-band end
                                seq = (event.get("parameters")
                                       or {}).get("seq")
                                if seq is not None and seq <= last_seq:
                                    event_id = None
                                    continue  # replayed duplicate
                                if seq is not None:
                                    last_seq = seq
                                if event_id is not None:
                                    last_event_id = event_id
                                    event_id = None
                                yielded_any = True
                                yield event
                            # stream ended WITHOUT the in-band terminal
                            # event: a mid-generation connection drop
                            dropped = ConnectionError(
                                "stream ended without terminal event")
                        except (aiohttp.ClientError,
                                asyncio.TimeoutError, OSError) as e:
                            dropped = e
                finally:
                    if resp is not None:
                        resp.close()
                # reconnect path, same guard as the sync helper: resume
                # only when the server issued SSE ids OR nothing was
                # delivered yet (a fresh re-send cannot duplicate)
                attempt += 1
                if (not resume or attempt > max_reconnects
                        or (yielded_any and last_event_id is None)):
                    reason = (
                        " (resume disabled)" if not resume
                        else " (generation is not resumable: the server"
                             " sent no event ids)"
                        if yielded_any and last_event_id is None
                        else ""
                    )
                    if isinstance(dropped, InferenceServerException):
                        raise dropped
                    raise InferenceServerException(
                        "generate_stream connection lost{}: {}".format(
                            reason, dropped))
                if on_reconnect is not None:
                    maybe = on_reconnect(attempt, dropped)
                    if asyncio.iscoroutine(maybe):
                        await maybe
                await asyncio.sleep(
                    min(reconnect_backoff_s * (2 ** (attempt - 1)), 2.0))
        finally:
            await session.close()
