"""HTTP/REST client for the KServe-v2 protocol (sync; see ``.aio`` for
asyncio).  Mirrors the surface of reference ``tritonclient.http``."""

from tritonclient._pool import CircuitBreaker, EndpointPool
from tritonclient.http._client import (
    InferAsyncRequest,
    InferenceServerClient,
    InferInput,
    InferRequestedOutput,
    InferResult,
    RetryPolicy,
)
from tritonclient.utils import InferenceServerException

__all__ = [
    "CircuitBreaker",
    "EndpointPool",
    "InferAsyncRequest",
    "InferenceServerClient",
    "InferenceServerException",
    "InferInput",
    "InferRequestedOutput",
    "InferResult",
    "RetryPolicy",
]
