"""Input tensor descriptor for the HTTP client.

Re-implements reference http/_infer_input.py (binary-aware
``set_data_from_numpy`` incl. BYTES and BF16, shared-memory references) with a
TPU-first extension: any array-like — including ``jax.Array`` — is accepted;
bf16 arrays are serialized natively via ml_dtypes instead of requiring the
fp32-truncation path.
"""

import numpy as np

from tritonclient.utils import (
    np_to_triton_dtype,
    raise_error,
    serialize_bf16_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)


class InferInput:
    """An input tensor for an inference request.

    Parameters
    ----------
    name : str
        The name of the input whose data will be described by this object.
    shape : list
        The shape of the associated input.
    datatype : str
        The datatype of the associated input.
    """

    def __init__(self, name, shape, datatype):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype
        self._parameters = {}
        self._data = None
        self._raw_data = None

    def name(self):
        """Get the name of the input associated with this object."""
        return self._name

    def datatype(self):
        """Get the datatype of the input associated with this object."""
        return self._datatype

    def shape(self):
        """Get the shape of the input associated with this object."""
        return self._shape

    def set_shape(self, shape):
        """Set the shape of the input."""
        self._shape = list(shape)
        return self

    def set_data_from_numpy(self, input_tensor, binary_data=True):
        """Set the tensor data from the specified array-like.

        Accepts ``np.ndarray`` (as the reference does) and any array-like with
        an ``__array__`` protocol — notably ``jax.Array``, which is fetched
        from device exactly once here (and not at all when using the
        shared-memory paths; see ``set_shared_memory`` /
        ``tritonclient.utils.xla_shared_memory``).

        Parameters
        ----------
        input_tensor : np.ndarray or jax.Array
            The tensor data.
        binary_data : bool
            Whether the data should be sent in the binary section of the
            request (True, default) or inline in the JSON header (False).
        """
        if not isinstance(input_tensor, np.ndarray):
            try:
                input_tensor = np.asarray(input_tensor)
            except Exception:
                raise_error("input_tensor must be a numpy array or array-like")

        dtype = np_to_triton_dtype(input_tensor.dtype)
        if self._datatype != dtype:
            # BF16 tensors may legitimately arrive as fp32 (the reference's
            # only path) or as native bf16 arrays.
            if not (
                self._datatype == "BF16"
                and input_tensor.dtype in (np.float32, np.float16, np.float64)
            ):
                raise_error(
                    "got unexpected datatype {} from numpy array, expected {}".format(
                        dtype, self._datatype
                    )
                )
        valid_shape = True
        if len(self._shape) != len(input_tensor.shape):
            valid_shape = False
        else:
            for i in range(len(self._shape)):
                if self._shape[i] != input_tensor.shape[i]:
                    valid_shape = False
        if not valid_shape:
            raise_error(
                "got unexpected numpy array shape [{}], expected [{}]".format(
                    str(input_tensor.shape)[1:-1], str(self._shape)[1:-1]
                )
            )

        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)

        if not binary_data:
            self._parameters.pop("binary_data_size", None)
            self._raw_data = None
            if self._datatype == "BYTES":
                self._data = []
                try:
                    if input_tensor.size > 0:
                        for obj in np.nditer(
                            input_tensor, flags=["refs_ok"], order="C"
                        ):
                            # We need to convert the object to string using
                            # utf-8 encoding for non-binary JSON transport.
                            if input_tensor.dtype == np.object_:
                                if type(obj.item()) == bytes:
                                    self._data.append(
                                        str(obj.item(), encoding="utf-8")
                                    )
                                else:
                                    self._data.append(str(obj.item()))
                            else:
                                self._data.append(str(obj.item(), encoding="utf-8"))
                except UnicodeDecodeError:
                    raise_error(
                        f'Failed to encode "{obj.item()}" using UTF-8. Please '
                        "use binary_data=True, if you want to pass a byte array."
                    )
            elif self._datatype == "BF16":
                raise_error(
                    "BF16 inputs must use binary_data=True (no JSON "
                    "representation exists for BF16)"
                )
            else:
                self._data = [val.item() for val in input_tensor.flatten()]
        else:
            self._data = None
            if self._datatype == "BYTES":
                serialized_output = serialize_byte_tensor(input_tensor)
                if serialized_output.size > 0:
                    self._raw_data = serialized_output.item()
                else:
                    self._raw_data = b""
            elif self._datatype == "BF16":
                serialized_output = serialize_bf16_tensor(input_tensor)
                if serialized_output.size > 0:
                    self._raw_data = serialized_output.item()
                else:
                    self._raw_data = b""
            else:
                expected_np = triton_to_np_dtype(self._datatype)
                if expected_np is not None and input_tensor.dtype != expected_np:
                    input_tensor = input_tensor.astype(expected_np)
                self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
            self._parameters["binary_data_size"] = len(self._raw_data)
        return self

    def set_shared_memory(self, region_name, byte_size, offset=0):
        """Set the tensor data to come from a registered shared-memory region
        (system, CUDA, or XLA/TPU — the region name resolves server-side)."""
        self._data = None
        self._raw_data = None
        self._parameters.pop("binary_data_size", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def _get_binary_data(self):
        """The raw bytes for the binary section of the request, or None."""
        return self._raw_data

    def _get_tensor(self):
        """The JSON-serializable dict describing this input."""
        tensor = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            tensor["parameters"] = self._parameters
        if self._data is not None:
            tensor["data"] = self._data
        return tensor
