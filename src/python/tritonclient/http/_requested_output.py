"""Requested-output descriptor for the HTTP client (reference
http/_infer_requested_output.py)."""

from tritonclient.utils import raise_error


class InferRequestedOutput:
    """An output tensor requested from an inference.

    Parameters
    ----------
    name : str
        The name of the output.
    binary_data : bool
        Whether the output should be returned in the binary section of the
        response (True, default) or inline in the JSON header.
    class_count : int
        If non-zero, request the output as a classification of the top
        ``class_count`` results (forces JSON, not binary).
    """

    def __init__(self, name, binary_data=True, class_count=0):
        self._name = name
        self._parameters = {}
        if class_count != 0:
            self._parameters["classification"] = class_count
            binary_data = False
        self._binary = binary_data
        self._parameters["binary_data"] = binary_data

    def name(self):
        """Get the name of the output associated with this object."""
        return self._name

    def set_shared_memory(self, region_name, byte_size, offset=0):
        """Make the server write this output into a registered shared-memory
        region (system, CUDA, or XLA/TPU)."""
        if "classification" in self._parameters:
            raise_error("shared memory can't be set on classification output")
        if self._binary:
            self._parameters["binary_data"] = False
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def unset_shared_memory(self):
        """Clear any shared-memory reference on this output."""
        self._parameters["binary_data"] = self._binary
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        return self

    def _get_tensor(self):
        """The JSON-serializable dict describing this requested output."""
        return {"name": self._name, "parameters": self._parameters}
