"""Request-body assembly for the HTTP/REST v2 protocol with the binary-tensor
extension.  Re-implements the behavior of reference http/_utils.py:74-131."""

import gzip
import json
import zlib

from tritonclient.utils import raise_error


def _get_query_string(query_params):
    params = []
    for key, value in query_params.items():
        if isinstance(value, (list, tuple)):
            for item in value:
                params.append("%s=%s" % (key, item))
        else:
            params.append("%s=%s" % (key, value))
    if params:
        return "&".join(params)
    return ""


def _get_inference_request(
    inputs,
    request_id,
    outputs,
    sequence_id,
    sequence_start,
    sequence_end,
    priority,
    timeout,
    custom_parameters=None,
):
    """Build the request body: JSON header + concatenated raw tensor data.

    Returns (request_body_bytes, json_size_or_None); json_size is None when
    there is no trailing binary section (pure-JSON request).
    """
    infer_request = {}
    parameters = {}
    if request_id != "":
        infer_request["id"] = request_id
    if sequence_id != 0 and sequence_id != "":
        parameters["sequence_id"] = sequence_id
        parameters["sequence_start"] = sequence_start
        parameters["sequence_end"] = sequence_end
    if priority != 0:
        parameters["priority"] = priority
    if timeout is not None:
        parameters["timeout"] = timeout

    infer_request["inputs"] = [this_input._get_tensor() for this_input in inputs]
    if outputs:
        infer_request["outputs"] = [
            this_output._get_tensor() for this_output in outputs
        ]
    else:
        # no outputs specified => server returns all outputs; request binary
        # form of all outputs via parameter (reference http/_utils.py:92-98)
        parameters["binary_data_output"] = True

    if custom_parameters:
        for key, value in custom_parameters.items():
            if key in (
                "sequence_id",
                "sequence_start",
                "sequence_end",
                "priority",
                "binary_data_output",
            ):
                raise_error(
                    f"Parameter {key} is a reserved parameter and cannot be "
                    "specified as a custom parameter"
                )
            parameters[key] = value
    if parameters:
        infer_request["parameters"] = parameters

    request_json = json.dumps(infer_request).encode("utf-8")

    binary_chunks = []
    for this_input in inputs:
        raw = this_input._get_binary_data()
        if raw is not None:
            binary_chunks.append(raw)

    if not binary_chunks:
        return request_json, None
    return request_json + b"".join(binary_chunks), len(request_json)


def _compress_request_body(algorithm, body):
    if algorithm == "gzip":
        return gzip.compress(body)
    if algorithm == "deflate":
        return zlib.compress(body)
    raise_error(f"Unsupported compression algorithm: {algorithm}")


def _decompress_response_body(encoding, body):
    if encoding == "gzip":
        return gzip.decompress(body)
    if encoding == "deflate":
        return zlib.decompress(body)
    return body


def _get_error_message(response_body):
    """Extract the error message from a non-OK response body (JSON 'error'
    field or the plain-text body itself, reference tests
    test_inference_server_client.py:45-101)."""
    if not response_body:
        return "(empty response body)"
    try:
        decoded = response_body.decode("utf-8", errors="replace")
        parsed = json.loads(decoded)
        if isinstance(parsed, dict) and "error" in parsed:
            return parsed["error"]
        return decoded
    except (ValueError, AttributeError):
        return response_body.decode("utf-8", errors="replace")
