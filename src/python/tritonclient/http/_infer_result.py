"""Inference result wrapper for the HTTP client.

Parses the v2 response with the binary-tensor extension: a JSON header of
``Inference-Header-Content-Length`` bytes followed by concatenated raw output
buffers (reference http/_infer_result.py).
"""

import json

import numpy as np

from tritonclient._result_base import result_as_jax
from tritonclient.http._utils import _decompress_response_body
from tritonclient.utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    raise_error,
    triton_to_np_dtype,
)


class InferResult:
    """An object holding the result of an inference request."""

    def __init__(self, response_body, verbose=False, header_length=None,
                 content_encoding=None):
        if content_encoding is not None:
            response_body = _decompress_response_body(
                content_encoding, response_body
            )
        self._output_name_to_buffer_map = {}
        if header_length is None:
            content = response_body
            self._buffer = None
        else:
            content = response_body[:header_length]
            self._buffer = response_body[header_length:]
            # Binary buffers appear in output order, each of
            # parameters.binary_data_size bytes.
        if verbose:
            print("infer response header:", content)
        try:
            self._result = json.loads(content)
        except ValueError as e:
            raise_error(
                "unable to parse inference response JSON: {}".format(e)
            )
        if self._buffer is not None:
            offset = 0
            for output in self._result.get("outputs", []):
                parameters = output.get("parameters", {})
                if "binary_data_size" in parameters:
                    size = parameters["binary_data_size"]
                    self._output_name_to_buffer_map[output["name"]] = (
                        offset,
                        size,
                    )
                    offset += size

    @classmethod
    def from_response_body(
        cls, response_body, verbose=False, header_length=None,
        content_encoding=None
    ):
        """Build an InferResult from a raw response body (the static-path
        twin of the constructor, reference http/_client.py:1207-1313)."""
        return cls(response_body, verbose, header_length, content_encoding)

    def get_response(self):
        """Get the parsed response JSON (dict)."""
        return self._result

    def get_output(self, name):
        """Get the output dict for the named output, or None."""
        for output in self._result.get("outputs", []):
            if output["name"] == name:
                return output
        return None

    def as_numpy(self, name):
        """Get the tensor data for the named output as a numpy array (or None
        if the output is absent or lives in shared memory)."""
        output = self.get_output(name)
        if output is None:
            return None
        shape = output.get("shape", [])
        datatype = output["datatype"]
        parameters = output.get("parameters", {})
        if name in self._output_name_to_buffer_map:
            offset, size = self._output_name_to_buffer_map[name]
            raw = self._buffer[offset : offset + size]
            if datatype == "BYTES":
                np_array = deserialize_bytes_tensor(raw)
            elif datatype == "BF16":
                np_array = deserialize_bf16_tensor(raw)
            else:
                np_dtype = triton_to_np_dtype(datatype)
                if np_dtype is None:
                    raise_error("unknown response datatype " + datatype)
                np_array = np.frombuffer(raw, dtype=np_dtype)
            return np_array.reshape(shape)
        if "data" not in output:
            # output resides in shared memory
            return None
        if datatype == "BYTES":
            np_array = np.array(
                [
                    d.encode("utf-8") if isinstance(d, str) else d
                    for d in _flatten(output["data"])
                ],
                dtype=np.object_,
            )
        else:
            np_dtype = triton_to_np_dtype(datatype)
            np_array = np.array(_flatten(output["data"]), dtype=np_dtype)
        return np_array.reshape(shape)

    def as_jax(self, name, device=None):
        """TPU-first accessor: the named output as a ``jax.Array`` (committed
        to ``device`` if given).  BF16 outputs arrive as native bfloat16."""
        return result_as_jax(self, name, device)


def _flatten(data):
    out = []
    stack = [data]
    while stack:
        item = stack.pop()
        if isinstance(item, list):
            stack.extend(reversed(item))
        else:
            out.append(item)
    return out
