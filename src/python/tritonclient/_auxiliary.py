"""Client-side timing, statistics, and the shared retry policy.

Python twin of the reference C++ ``RequestTimers`` (6-point nanosecond
timestamps, common.h:523-603) and ``InferStat`` (common.h:94-115) so the
Python clients expose the same request-timing observability the C++ library
does.  ``RetryPolicy`` is shared by the HTTP and gRPC clients: one
definition of which failures are safely retryable and how to back off.
"""

import random
import threading
import time

#: Failure classes shared by the same-endpoint retry loop and the
#: multi-replica pool's failover/breaker logic (tritonclient._pool).
#: The split matters because retry and failover have different safety
#: requirements: a retry re-executes against the SAME server, failover
#: re-executes against a DIFFERENT one, and an "interrupted" request
#: (sent, outcome unknown) is only safe to re-execute anywhere when the
#: call is idempotent.
FAILURE_CONNECT = "connect"  # provably never reached a handler
FAILURE_OVERLOAD = "overload"  # typed shed-before-work (429/503/...)
FAILURE_INTERRUPTED = "interrupted"  # request sent, outcome unknown
FAILURE_OTHER = "other"  # typed non-overload response: server is alive

#: grpc-core detail strings that prove an UNAVAILABLE failed in the
#: connect phase (the request never left the client).  One definition
#: shared by the gRPC client's retry loop and the pool's failover
#: classifier — a marker added to one but not the other would make the
#: two layers classify the same error differently.
CONNECT_ERROR_DETAILS = (
    "failed to connect",
    "connection refused",
    "name resolution",
    "dns resolution failed",
)


class RetryPolicy:
    """Opt-in client retry policy: exponential backoff with full jitter.

    Deliberately narrow about WHAT retries — only failures where the
    server provably did not complete the request:

    - **connection errors** (refused/reset before a response): the
      request never reached a handler;
    - **overload codes** — HTTP 429/503, gRPC RESOURCE_EXHAUSTED/
      UNAVAILABLE: the server typed the rejection as shed-before-work.

    Timeouts are never retried (the server may have executed the
    request — resending a non-idempotent infer would double-execute it),
    and neither are 4xx/5xx outside the overload set.  A server-supplied
    ``Retry-After`` (HTTP header / gRPC ``retry-after`` trailing
    metadata) overrides the computed backoff for that attempt.

    Parameters
    ----------
    max_attempts : int
        Total tries including the first (so 4 = 1 try + 3 retries).
    initial_backoff_s / max_backoff_s / backoff_multiplier : float
        Exponential schedule: ``min(max, initial * multiplier**i)``.
    jitter : float
        Fraction of the backoff randomized away (0..1): with 0.25 the
        sleep is uniform in [0.75b, b], decorrelating retry storms.
    retry_connection_errors : bool
        Set False to retry only typed overload rejections.
    max_total_s : float or None
        Optional wall-clock budget for the whole logical call (all
        attempts plus their backoff sleeps).  When set, backoff sleeps
        are capped at the remaining budget and no retry starts past it,
        so a large server ``Retry-After`` hint can never park the
        caller beyond its own deadline.
    """

    #: HTTP statuses retried (gRPC maps RESOURCE_EXHAUSTED/UNAVAILABLE
    #: onto the same set)
    retryable_statuses = frozenset((429, 503))

    def __init__(self, max_attempts=4, initial_backoff_s=0.05,
                 max_backoff_s=2.0, backoff_multiplier=2.0, jitter=0.25,
                 retry_connection_errors=True, max_total_s=None):
        if max_attempts < 1:
            raise ValueError(
                "max_attempts must be >= 1 (got {})".format(max_attempts))
        self.max_attempts = int(max_attempts)
        self.initial_backoff_s = float(initial_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.jitter = float(jitter)
        self.retry_connection_errors = bool(retry_connection_errors)
        self.max_total_s = None if max_total_s is None else float(max_total_s)

    @staticmethod
    def parse_retry_after(value):
        """A server ``Retry-After`` hint as float seconds, or None.

        Only the non-negative delta-seconds integer form is accepted;
        HTTP-dates, negatives, fractions, and garbage return None so
        the exponential schedule takes over instead of a sleep the
        server never meant."""
        if value is None:
            return None
        try:
            seconds = int(str(value).strip())
        except (TypeError, ValueError):
            return None
        return float(seconds) if seconds >= 0 else None

    def backoff_s(self, attempt, retry_after=None, remaining_s=None):
        """Seconds to sleep before retry number ``attempt`` (0-based).

        A server-supplied ``retry_after`` wins over the schedule, but
        still gets jitter ADDED on top — the server hands every shed
        client the same number, and N clients sleeping exactly that
        long re-arrive as one synchronized storm that re-trips the
        cap.  ``remaining_s`` (the caller's leftover deadline budget)
        caps the final sleep: a large server hint must never park the
        client past its own timeout."""
        base = self.parse_retry_after(retry_after)
        if base is not None:
            sleep = base * (1.0 + self.jitter * random.random())
        else:
            base = min(
                self.max_backoff_s,
                self.initial_backoff_s * self.backoff_multiplier ** attempt,
            )
            sleep = base * (1.0 - self.jitter * random.random())
        if remaining_s is not None:
            sleep = min(sleep, max(0.0, remaining_s))
        return sleep

    # -- failure classification -------------------------------------------

    def classify_http_status(self, status):
        """Map an HTTP status to a failure kind (module constants)."""
        try:
            code = int(status)
        except (TypeError, ValueError):
            return FAILURE_OTHER
        return (
            FAILURE_OVERLOAD
            if code in self.retryable_statuses
            else FAILURE_OTHER
        )

    def should_retry(self, kind):
        """Same-endpoint retry decision: only failures where the server
        provably did not complete the request — typed overload, and
        connect-phase failures (when enabled).  Interrupted requests
        (sent, outcome unknown) are never retried here: a retry hits
        the SAME server that may have executed the request."""
        if kind == FAILURE_OVERLOAD:
            return True
        if kind == FAILURE_CONNECT:
            return self.retry_connection_errors
        return False

    def should_failover(self, kind, idempotent=False):
        """Cross-endpoint failover decision (tritonclient._pool).

        Typed-overload failures always fail over, connect-phase
        failures fail over unless ``retry_connection_errors=False``
        narrowed the policy to typed rejections only — either way the
        rejecting server did no work, so another replica may.  An
        interrupted request fails over only when the caller marks the
        call idempotent: the first server may have executed it, and a
        second execution elsewhere must be safe.  Typed non-overload
        responses (4xx/5xx outside the overload set) never fail over —
        every replica would answer the same."""
        if kind == FAILURE_CONNECT:
            return self.retry_connection_errors
        if kind == FAILURE_OVERLOAD:
            return True
        if kind == FAILURE_INTERRUPTED:
            return bool(idempotent)
        return False


class RequestTimers:
    """Nanosecond timestamps for one request: REQUEST/SEND/RECV start+end."""

    __slots__ = (
        "request_start_ns",
        "request_end_ns",
        "send_start_ns",
        "send_end_ns",
        "recv_start_ns",
        "recv_end_ns",
    )

    def __init__(self):
        self.request_start_ns = 0
        self.request_end_ns = 0
        self.send_start_ns = 0
        self.send_end_ns = 0
        self.recv_start_ns = 0
        self.recv_end_ns = 0

    def request_start(self):
        self.request_start_ns = time.monotonic_ns()

    def request_end(self):
        self.request_end_ns = time.monotonic_ns()

    def send_start(self):
        self.send_start_ns = time.monotonic_ns()

    def send_end(self):
        self.send_end_ns = time.monotonic_ns()

    def recv_start(self):
        self.recv_start_ns = time.monotonic_ns()

    def recv_end(self):
        self.recv_end_ns = time.monotonic_ns()

    def request_duration_ns(self):
        return self.request_end_ns - self.request_start_ns

    def send_duration_ns(self):
        return self.send_end_ns - self.send_start_ns

    def recv_duration_ns(self):
        return self.recv_end_ns - self.recv_start_ns


class InferStat:
    """Accumulated client-side statistics across requests (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed_request_count = 0
        self.cumulative_total_request_time_ns = 0
        self.cumulative_send_time_ns = 0
        self.cumulative_receive_time_ns = 0
        self.failed_request_count = 0

    def update(self, timers, success=True):
        with self._lock:
            if success:
                self.completed_request_count += 1
                self.cumulative_total_request_time_ns += (
                    timers.request_duration_ns()
                )
                self.cumulative_send_time_ns += timers.send_duration_ns()
                self.cumulative_receive_time_ns += timers.recv_duration_ns()
            else:
                self.failed_request_count += 1

    def __repr__(self):
        return (
            "InferStat(completed={}, failed={}, avg_request_us={:.1f})".format(
                self.completed_request_count,
                self.failed_request_count,
                (
                    self.cumulative_total_request_time_ns
                    / max(1, self.completed_request_count)
                )
                / 1e3,
            )
        )
