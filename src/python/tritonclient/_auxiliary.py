"""Client-side timing and statistics.

Python twin of the reference C++ ``RequestTimers`` (6-point nanosecond
timestamps, common.h:523-603) and ``InferStat`` (common.h:94-115) so the
Python clients expose the same request-timing observability the C++ library
does.
"""

import threading
import time


class RequestTimers:
    """Nanosecond timestamps for one request: REQUEST/SEND/RECV start+end."""

    __slots__ = (
        "request_start_ns",
        "request_end_ns",
        "send_start_ns",
        "send_end_ns",
        "recv_start_ns",
        "recv_end_ns",
    )

    def __init__(self):
        self.request_start_ns = 0
        self.request_end_ns = 0
        self.send_start_ns = 0
        self.send_end_ns = 0
        self.recv_start_ns = 0
        self.recv_end_ns = 0

    def request_start(self):
        self.request_start_ns = time.monotonic_ns()

    def request_end(self):
        self.request_end_ns = time.monotonic_ns()

    def send_start(self):
        self.send_start_ns = time.monotonic_ns()

    def send_end(self):
        self.send_end_ns = time.monotonic_ns()

    def recv_start(self):
        self.recv_start_ns = time.monotonic_ns()

    def recv_end(self):
        self.recv_end_ns = time.monotonic_ns()

    def request_duration_ns(self):
        return self.request_end_ns - self.request_start_ns

    def send_duration_ns(self):
        return self.send_end_ns - self.send_start_ns

    def recv_duration_ns(self):
        return self.recv_end_ns - self.recv_start_ns


class InferStat:
    """Accumulated client-side statistics across requests (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.completed_request_count = 0
        self.cumulative_total_request_time_ns = 0
        self.cumulative_send_time_ns = 0
        self.cumulative_receive_time_ns = 0
        self.failed_request_count = 0

    def update(self, timers, success=True):
        with self._lock:
            if success:
                self.completed_request_count += 1
                self.cumulative_total_request_time_ns += (
                    timers.request_duration_ns()
                )
                self.cumulative_send_time_ns += timers.send_duration_ns()
                self.cumulative_receive_time_ns += timers.recv_duration_ns()
            else:
                self.failed_request_count += 1

    def __repr__(self):
        return (
            "InferStat(completed={}, failed={}, avg_request_us={:.1f})".format(
                self.completed_request_count,
                self.failed_request_count,
                (
                    self.cumulative_total_request_time_ns
                    / max(1, self.completed_request_count)
                )
                / 1e3,
            )
        )
