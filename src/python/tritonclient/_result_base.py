"""Shared helpers for result objects across the HTTP and gRPC clients."""


def result_as_jax(result, name, device=None):
    """Convert ``result.as_numpy(name)`` into a ``jax.Array``.

    jax is imported lazily so the clients stay importable (and fast to
    import) on hosts without jax; bf16 numpy arrays (ml_dtypes) convert
    natively with no widening.
    """
    np_array = result.as_numpy(name)
    if np_array is None:
        return None
    import jax

    if device is not None:
        return jax.device_put(np_array, device)
    return jax.numpy.asarray(np_array)
