"""TPU-native client stack for Triton Inference Server (KServe v2 protocol).

A from-scratch implementation of the capabilities of the reference
`triton-inference-server/client` repository, designed TPU-first: tensors may be
numpy arrays *or* ``jax.Array``s, BF16 is a first-class dtype, and the CUDA
shared-memory data plane is generalized into an XLA/TPU shared-memory data
plane (``tritonclient.utils.xla_shared_memory``).

Subpackages
-----------
``tritonclient.http``    sync HTTP/REST client (+ ``.aio`` asyncio variant)
``tritonclient.grpc``    sync gRPC client (+ ``.aio`` asyncio variant)
``tritonclient.utils``   dtype helpers, tensor (de)serialization, exceptions,
                         and the shared-memory data planes
"""

__version__ = "0.1.0"
