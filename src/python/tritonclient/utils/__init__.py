"""Dtype mapping, tensor (de)serialization, and error types.

Re-implements the surface of the reference ``tritonclient.utils``
(reference src/python/library/tritonclient/utils/__init__.py:66-346) with a
TPU-first treatment of BF16: on TPU hosts ``ml_dtypes.bfloat16`` (the dtype
jax arrays use) is the native in-memory representation, so BF16 tensors move
to/from the wire without the fp32-truncation dance the reference requires.
The fp32-based helpers are still provided for API parity.
"""

import struct

import numpy as np

try:  # ml_dtypes ships with jaxlib; gives numpy a real bfloat16 dtype.
    import ml_dtypes

    _BF16_NP = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes is present wherever jax is
    ml_dtypes = None
    _BF16_NP = None

__all__ = [
    "InferenceServerException",
    "np_to_triton_dtype",
    "triton_to_np_dtype",
    "serialize_byte_tensor",
    "deserialize_bytes_tensor",
    "serialize_bf16_tensor",
    "deserialize_bf16_tensor",
    "serialized_byte_size",
    "raise_error",
]


class InferenceServerException(Exception):
    """Exception indicating a non-successful status from the server or client.

    Mirrors reference utils/__init__.py:66-125.
    """

    def __init__(self, msg, status=None, debug_details=None,
                 retry_after=None):
        self._msg = msg
        self._status = status
        self._debug_details = debug_details
        self._retry_after = retry_after
        super().__init__(msg)

    def __str__(self):
        msg = super().__str__() if self._msg is None else self._msg
        if self._status is not None:
            msg = "[" + self._status + "] " + msg
        return msg

    def message(self):
        """Get the exception message."""
        return self._msg

    def status(self):
        """Get the status of the exception, or None."""
        return self._status

    def debug_details(self):
        """Get the detailed information about the exception, or None."""
        return self._debug_details

    def retry_after(self):
        """The server's Retry-After backoff hint (HTTP header / gRPC
        trailing metadata) attached to this failure, or None.  Typed
        overload rejections carry it so retry/failover layers honor
        the server's own cooldown."""
        return self._retry_after


def raise_error(msg):
    """Raise an InferenceServerException with the given message."""
    raise InferenceServerException(msg=msg)


# Triton wire dtype string <-> numpy dtype. BF16 maps to ml_dtypes.bfloat16
# (jax-native) rather than being unsupported-in-numpy as in the reference
# (utils/__init__.py:128-185, where BF16 returns None).
_TRITON_TO_NP = {
    "BOOL": bool,
    "INT8": np.int8,
    "INT16": np.int16,
    "INT32": np.int32,
    "INT64": np.int64,
    "UINT8": np.uint8,
    "UINT16": np.uint16,
    "UINT32": np.uint32,
    "UINT64": np.uint64,
    "FP16": np.float16,
    "FP32": np.float32,
    "FP64": np.float64,
    "BYTES": np.object_,
}


def np_to_triton_dtype(np_dtype):
    """Map a numpy (or ml_dtypes) dtype to the Triton wire dtype string."""
    if np_dtype == bool:
        return "BOOL"
    elif np_dtype == np.int8:
        return "INT8"
    elif np_dtype == np.int16:
        return "INT16"
    elif np_dtype == np.int32:
        return "INT32"
    elif np_dtype == np.int64:
        return "INT64"
    elif np_dtype == np.uint8:
        return "UINT8"
    elif np_dtype == np.uint16:
        return "UINT16"
    elif np_dtype == np.uint32:
        return "UINT32"
    elif np_dtype == np.uint64:
        return "UINT64"
    elif np_dtype == np.float16:
        return "FP16"
    elif _BF16_NP is not None and np_dtype == _BF16_NP:
        return "BF16"
    elif np_dtype == np.float32:
        return "FP32"
    elif np_dtype == np.float64:
        return "FP64"
    elif np_dtype == np.object_ or np.dtype(np_dtype).type == np.bytes_ or (
        np.dtype(np_dtype).type == np.str_
    ):
        return "BYTES"
    return None


def triton_to_np_dtype(dtype):
    """Map a Triton wire dtype string to a numpy dtype.

    ``BF16`` maps to ``ml_dtypes.bfloat16`` (TPU-native); the reference
    returns None for BF16 (utils/__init__.py:180-182).
    """
    if dtype == "BF16":
        return _BF16_NP
    return _TRITON_TO_NP.get(dtype)


def serialize_byte_tensor(input_tensor):
    """Serialize a BYTES tensor into the 4-byte-length-prefixed flat buffer.

    Row-major (C-order) traversal; each element is a little-endian uint32
    length followed by the element bytes.  Mirrors reference
    utils/__init__.py:188-240.

    Returns a np.object_ scalar-less ``np.array`` wrapping the flat buffer
    (so ``.item()`` / ``.tobytes()`` yield the bytes), matching the
    reference's return convention.
    """
    if input_tensor.size == 0:
        return np.empty([0], dtype=np.object_)

    if (input_tensor.dtype != np.object_) and (
        input_tensor.dtype.type != np.bytes_
    ) and (input_tensor.dtype.type != np.str_):
        raise_error("cannot serialize bytes tensor: invalid datatype")

    flattened_ls = []
    # C-order flatten so multidimensional BYTES tensors round-trip with the
    # row-major layout the server expects.
    for obj in np.nditer(input_tensor, flags=["refs_ok"], order="C"):
        # If unicode, encode to utf-8; bytes pass through unchanged.
        s = obj.item()
        if type(s) == bytes:
            b = s
        else:
            b = str(s).encode("utf-8")
        flattened_ls.append(struct.pack("<I", len(b)))
        flattened_ls.append(b)
    flattened = b"".join(flattened_ls)
    flattened_array = np.asarray(flattened, dtype=np.object_)
    if not flattened_array.flags["C_CONTIGUOUS"]:
        flattened_array = np.ascontiguousarray(flattened_array, dtype=np.object_)
    return flattened_array


def deserialize_bytes_tensor(encoded_tensor):
    """Inverse of :func:`serialize_byte_tensor`: flat buffer -> 1-D np.object_
    array of ``bytes``.  Mirrors reference utils/__init__.py:243-273."""
    strs = []
    offset = 0
    val_buf = encoded_tensor
    while offset < len(val_buf):
        (length,) = struct.unpack_from("<I", val_buf, offset)
        offset += 4
        sb = struct.unpack_from("<{}s".format(length), val_buf, offset)[0]
        offset += length
        strs.append(sb)
    return np.array(strs, dtype=np.object_)


def serialize_bf16_tensor(input_tensor):
    """Serialize an fp32/bf16 tensor to raw BF16 little-endian bytes.

    The reference (utils/__init__.py:276-318) truncates fp32 bit patterns to
    their upper 16 bits.  Here: if ml_dtypes is available the conversion is a
    native astype (round-to-nearest-even, what the TPU itself does); tensors
    already in bfloat16 are serialized zero-conversion.
    """
    if input_tensor.size == 0:
        return np.empty([0], dtype=np.object_)

    if _BF16_NP is not None:
        if input_tensor.dtype == _BF16_NP:
            arr = np.ascontiguousarray(input_tensor)
        elif input_tensor.dtype in (np.float32, np.float16, np.float64):
            arr = np.ascontiguousarray(input_tensor).astype(_BF16_NP)
        else:
            raise_error(
                "cannot serialize bf16 tensor: invalid datatype "
                + str(input_tensor.dtype)
            )
        return np.asarray(arr.tobytes(), dtype=np.object_)

    # Fallback: bit-level truncation of fp32, as the reference does.
    if input_tensor.dtype != np.float32:
        raise_error("cannot serialize bf16 tensor: invalid datatype")
    u32 = np.ascontiguousarray(input_tensor, dtype=np.float32).view(np.uint32)
    u16 = (u32 >> 16).astype("<u2")
    return np.asarray(u16.tobytes(), dtype=np.object_)


def deserialize_bf16_tensor(encoded_tensor):
    """Deserialize raw BF16 bytes.

    With ml_dtypes present returns a 1-D ``bfloat16`` array (zero-copy view,
    TPU/jax-native); otherwise widens to fp32 as the reference does
    (utils/__init__.py:321-346).
    """
    if _BF16_NP is not None:
        return np.frombuffer(encoded_tensor, dtype=_BF16_NP)
    u16 = np.frombuffer(encoded_tensor, dtype="<u2")
    return (u16.astype(np.uint32) << 16).view(np.float32)


def serialized_byte_size(tensor_value):
    """Byte size a tensor occupies on the wire (after BYTES/BF16 encoding)."""
    if tensor_value.dtype == np.object_:
        total = 0
        for obj in np.nditer(tensor_value, flags=["refs_ok"], order="C"):
            s = obj.item()
            b = s if type(s) == bytes else str(s).encode("utf-8")
            total += 4 + len(b)
        return total
    return tensor_value.nbytes
