"""Locate (and if necessary build) the native helper libraries.

The reference wheels bundle prebuilt ``libcshm.so``/``libccudashm.so``
(reference setup.py:60-80); in this source tree the shims are compiled on
first use with g++ and cached under ``build/lib``.
"""

import os
import subprocess
import threading

_LOCK = threading.Lock()
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "..", "..")
)
_BUILD_DIR = os.path.join(_REPO_ROOT, "build", "lib")


def _source_path(*parts):
    return os.path.join(_REPO_ROOT, "src", "c++", *parts)


def load_or_build(lib_name, sources, extra_flags=()):
    """Return a ctypes.CDLL for ``lib_name``, compiling it if needed."""
    import ctypes

    lib_path = os.path.join(_BUILD_DIR, lib_name)
    with _LOCK:
        srcs = [_source_path(*s) if isinstance(s, tuple) else s
                for s in sources]
        needs_build = not os.path.exists(lib_path) or any(
            os.path.getmtime(s) > os.path.getmtime(lib_path) for s in srcs
        )
        if needs_build:
            os.makedirs(_BUILD_DIR, exist_ok=True)
            cmd = (
                ["g++", "-shared", "-fPIC", "-O2", "-o", lib_path]
                + srcs
                + list(extra_flags)
            )
            subprocess.run(cmd, check=True, capture_output=True)
    return ctypes.CDLL(lib_path)
