"""System (POSIX) shared-memory utilities.

Mirrors the API of reference tritonclient/utils/shared_memory/__init__.py:
94-287 — ctypes bindings over a small native shim (``libcshm.so``, built from
src/c++/library/cshm.cc) providing shm_open/mmap-backed regions that a
co-located server registers via ``register_system_shared_memory``.
"""

import ctypes

import numpy as np

from tritonclient.utils import (
    serialize_byte_tensor,
    serialized_byte_size,
    triton_to_np_dtype,
)
from tritonclient.utils._native import load_or_build

__all__ = [
    "SharedMemoryException",
    "SharedMemoryRegionHandle",
    "create_shared_memory_region",
    "set_shared_memory_region",
    "get_contents_as_numpy",
    "mapped_shared_memory_regions",
    "destroy_shared_memory_region",
]

_cshm = load_or_build("libcshm.so", [("library", "cshm.cc")], ["-lrt"])
_cshm.TpuShmRegionCreate.restype = ctypes.c_int
_cshm.TpuShmRegionCreate.argtypes = [
    ctypes.c_char_p,
    ctypes.c_size_t,
    ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_void_p),
]
_cshm.TpuShmRegionOpen.restype = ctypes.c_int
_cshm.TpuShmRegionOpen.argtypes = [
    ctypes.c_char_p,
    ctypes.c_size_t,
    ctypes.c_size_t,
    ctypes.POINTER(ctypes.c_int),
    ctypes.POINTER(ctypes.c_void_p),
]
_cshm.TpuShmRegionSet.restype = ctypes.c_int
_cshm.TpuShmRegionSet.argtypes = [
    ctypes.c_void_p,
    ctypes.c_size_t,
    ctypes.c_size_t,
    ctypes.c_void_p,
]
_cshm.TpuShmRegionGet.restype = ctypes.c_int
_cshm.TpuShmRegionGet.argtypes = [
    ctypes.c_void_p,
    ctypes.c_size_t,
    ctypes.c_size_t,
    ctypes.c_void_p,
]
_cshm.TpuShmRegionClose.restype = ctypes.c_int
_cshm.TpuShmRegionClose.argtypes = [
    ctypes.c_int,
    ctypes.c_void_p,
    ctypes.c_size_t,
]
_cshm.TpuShmRegionUnlink.restype = ctypes.c_int
_cshm.TpuShmRegionUnlink.argtypes = [ctypes.c_char_p]

_ERROR_STRINGS = {
    -1: "unable to open/create shared memory region",
    -2: "unable to size shared memory region",
    -3: "unable to map shared memory region",
    -4: "unable to unmap/close shared memory region",
    -5: "unable to unlink shared memory region",
}


class SharedMemoryException(Exception):
    """Exception indicating a shared-memory error."""

    def __init__(self, err):
        msg = _ERROR_STRINGS.get(err, str(err)) if isinstance(
            err, int
        ) else str(err)
        self._msg = msg
        super().__init__(msg)

    def __str__(self):
        return self._msg


class SharedMemoryRegionHandle:
    """Handle for a created/opened system shm region."""

    def __init__(self, triton_shm_name, shm_key, shm_fd, base, byte_size,
                 offset=0):
        self.triton_shm_name = triton_shm_name
        self.shm_key = shm_key
        self.shm_fd = shm_fd
        self.base = base
        self.byte_size = byte_size
        self.offset = offset
        self.closed = False


_mapped_regions = {}  # shm_key -> handle


def create_shared_memory_region(triton_shm_name, shm_key, byte_size,
                                create_only=False):
    """Create (or open existing, unless ``create_only``) a system shm region.

    Returns a SharedMemoryRegionHandle usable with the other functions here
    and registrable via ``client.register_system_shared_memory(name, key,
    byte_size)``.
    """
    fd = ctypes.c_int()
    base = ctypes.c_void_p()
    rc = _cshm.TpuShmRegionCreate(
        shm_key.encode("utf-8"), byte_size, ctypes.byref(fd),
        ctypes.byref(base)
    )
    if rc != 0:
        raise SharedMemoryException(rc)
    handle = SharedMemoryRegionHandle(
        triton_shm_name, shm_key, fd.value, base.value, byte_size
    )
    _mapped_regions[shm_key] = handle
    return handle


def set_shared_memory_region(shm_handle, input_values, offset=0):
    """Write the (list of) numpy/jax arrays consecutively into the region
    starting at ``offset``; BYTES tensors use their serialized form."""
    if not isinstance(input_values, (list, tuple)):
        raise SharedMemoryException(
            "input_values must be specified as a list/tuple of numpy arrays"
        )
    offset_current = offset
    for input_value in input_values:
        input_value = np.asarray(input_value)
        if input_value.dtype == np.object_ or input_value.dtype.type in (
            np.bytes_,
            np.str_,
        ):
            serialized = serialize_byte_tensor(input_value)
            data = serialized.item() if serialized.size > 0 else b""
        else:
            data = np.ascontiguousarray(input_value).tobytes()
        if offset_current + len(data) > shm_handle.byte_size:
            raise SharedMemoryException(
                "unable to set shared memory region: data exceeds region size"
            )
        rc = _cshm.TpuShmRegionSet(
            shm_handle.base, offset_current, len(data), data
        )
        if rc != 0:
            raise SharedMemoryException(rc)
        offset_current += len(data)


def get_contents_as_numpy(shm_handle, datatype, shape, offset=0):
    """Read a tensor of the given numpy datatype/shape out of the region."""
    from tritonclient.utils import deserialize_bytes_tensor

    np_dtype = np.dtype(datatype) if not isinstance(
        datatype, np.dtype
    ) else datatype
    if np_dtype == np.object_:
        nbytes = shm_handle.byte_size - offset
        buf = (ctypes.c_char * nbytes)()
        rc = _cshm.TpuShmRegionGet(shm_handle.base, offset, nbytes, buf)
        if rc != 0:
            raise SharedMemoryException(rc)
        return deserialize_bytes_tensor(bytes(buf))[
            : int(np.prod(shape))
        ].reshape(shape)
    count = int(np.prod(shape)) if len(shape) > 0 else 1
    nbytes = count * np_dtype.itemsize
    buf = (ctypes.c_char * nbytes)()
    rc = _cshm.TpuShmRegionGet(shm_handle.base, offset, nbytes, buf)
    if rc != 0:
        raise SharedMemoryException(rc)
    return np.frombuffer(bytes(buf), dtype=np_dtype).reshape(shape)


def mapped_shared_memory_regions():
    """List the shm keys of regions mapped in this process."""
    return list(_mapped_regions.keys())


def destroy_shared_memory_region(shm_handle):
    """Unmap and unlink the region."""
    if shm_handle.closed:
        return
    rc = _cshm.TpuShmRegionClose(
        shm_handle.shm_fd, shm_handle.base, shm_handle.byte_size
    )
    shm_handle.closed = True
    _mapped_regions.pop(shm_handle.shm_key, None)
    rc2 = _cshm.TpuShmRegionUnlink(shm_handle.shm_key.encode("utf-8"))
    if rc != 0:
        raise SharedMemoryException(rc)
    if rc2 != 0:
        raise SharedMemoryException(rc2)
