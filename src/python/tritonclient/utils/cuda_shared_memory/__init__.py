"""CUDA shared-memory utilities — API-parity module.

The reference implements this over the CUDA runtime
(cuda_shared_memory/__init__.py:97-295).  This TPU-native stack targets hosts
without CUDA; the module keeps the reference API importable and raises a
descriptive error on use, pointing at ``tritonclient.utils.xla_shared_memory``
(the TPU generalization of this data plane).  If a CUDA runtime is present
(dual-accelerator host), the calls fail with the dlopen error instead.
"""

import ctypes
import ctypes.util

__all__ = [
    "CudaSharedMemoryException",
    "create_shared_memory_region",
    "get_raw_handle",
    "set_shared_memory_region",
    "get_contents_as_numpy",
    "allocated_shared_memory_regions",
    "destroy_shared_memory_region",
]


class CudaSharedMemoryException(Exception):
    """Exception indicating a CUDA shared-memory error."""


def _unavailable(*_args, **_kwargs):
    libcudart = ctypes.util.find_library("cudart")
    if libcudart is None:
        raise CudaSharedMemoryException(
            "CUDA shared memory is unavailable: no CUDA runtime on this "
            "host. On TPU hosts use tritonclient.utils.xla_shared_memory, "
            "which provides the same region/handle workflow over TPU HBM."
        )
    raise CudaSharedMemoryException(
        "CUDA shared memory support is not built into this TPU-native "
        "client (found {}).".format(libcudart)
    )


create_shared_memory_region = _unavailable
get_raw_handle = _unavailable
set_shared_memory_region = _unavailable
get_contents_as_numpy = _unavailable
destroy_shared_memory_region = _unavailable


def allocated_shared_memory_regions():
    return []
