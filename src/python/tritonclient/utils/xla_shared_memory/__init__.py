"""XLA/TPU shared-memory utilities — the TPU-native generalization of the
reference's ``tritonclient.utils.cuda_shared_memory`` (reference
cuda_shared_memory/__init__.py:97-295, cuda_shared_memory.cc:62-217).

Where CUDA shm is ``cudaMalloc`` + a ``cudaIpcMemHandle_t`` serialized into
the register RPC, public libtpu/PjRt exposes no cross-process HBM export, so
an XLA region is a *pair*:

- a **device segment map**: live ``jax.Array``s in TPU HBM, keyed by region
  offset.  When client and server share a process (the ``triton_c_api``-style
  in-process mode, and the north-star bench configuration) tensors pass as
  device buffers with **zero host copies** — request and response data never
  leave HBM.
- a **host staging window**: a POSIX-shm mapping (same ``libcshm.so`` shim as
  system shm) used when the server lives in another process.  Cross-process,
  a tensor costs exactly one host write + one ``device_put`` DMA — the same
  single-staging cost profile as CUDA IPC's peer mapping, which is the best
  the public PjRt surface allows.

``get_raw_handle`` serializes {uuid, shm key, byte size, device ordinal} —
base64-able, mirroring the reference's base64'd cudaIpc handle
(cuda_shared_memory.cc:98-127) — and ``attach_from_raw_handle`` is the
server-side entry point used by ``RegisterXlaSharedMemory``.
"""

import base64
import json
import uuid as _uuid

import numpy as np

from tritonclient.utils import (
    deserialize_bytes_tensor,
    serialize_byte_tensor,
    triton_to_np_dtype,
)
from tritonclient.utils import shared_memory as _sysshm

__all__ = [
    "XlaSharedMemoryException",
    "XlaShmHandle",
    "create_shared_memory_region",
    "get_raw_handle",
    "attach_from_raw_handle",
    "set_shared_memory_region",
    "set_shared_memory_region_from_jax",
    "get_contents_as_numpy",
    "get_contents_as_jax",
    "allocated_shared_memory_regions",
    "destroy_shared_memory_region",
]


class XlaSharedMemoryException(Exception):
    """Exception indicating an XLA shared-memory error."""


# uuid -> owner XlaShmHandle, enabling the zero-copy in-process attach path.
_LOCAL_REGIONS = {}


def _device(device_ordinal):
    import jax

    devices = jax.devices()
    if device_ordinal >= len(devices):
        raise XlaSharedMemoryException(
            "device ordinal {} out of range ({} jax devices)".format(
                device_ordinal, len(devices)
            )
        )
    return devices[device_ordinal]


class XlaShmHandle:
    """A region of TPU-addressable shared memory.

    Owner handles (from ``create_shared_memory_region``) hold the host
    window and the device segment map.  Attached handles (from
    ``attach_from_raw_handle``) either alias the owner in-process — zero-copy
    — or map only the host window cross-process.
    """

    def __init__(self, triton_shm_name, byte_size, device_ordinal, shm_key,
                 region_uuid, owner, host_window, local_owner=None):
        self._name = triton_shm_name
        self.byte_size = byte_size
        self.device_ordinal = device_ordinal
        self.shm_key = shm_key
        self.uuid = region_uuid
        self._owner = owner
        self._host = host_window  # SharedMemoryRegionHandle or None
        self._local_owner = local_owner  # set on in-process attached views
        self._segments = {}  # offset -> (jax.Array, host_synced: bool)
        self._inproc_attached = False
        self.closed = False

    # -- internal ----------------------------------------------------------

    def _root(self):
        return self._local_owner if self._local_owner is not None else self

    def _sync_segment_to_host(self, offset):
        root = self._root()
        seg = root._segments.get(offset)
        if seg is None or seg[1]:
            return
        array, _ = seg
        np_arr = np.asarray(array)
        root._write_host(offset, np.ascontiguousarray(np_arr).tobytes())
        root._segments[offset] = (array, True)

    def _write_host(self, offset, data):
        if self._host is None:
            raise XlaSharedMemoryException("region has no host window")
        if offset + len(data) > self.byte_size:
            raise XlaSharedMemoryException(
                "write of {} bytes at offset {} exceeds region size {}".format(
                    len(data), offset, self.byte_size
                )
            )
        import ctypes

        from tritonclient.utils.shared_memory import _cshm

        rc = _cshm.TpuShmRegionSet(self._host.base, offset, len(data), data)
        if rc != 0:
            raise XlaSharedMemoryException(
                "unable to write host window: {}".format(rc)
            )

    def _read_host(self, offset, nbytes):
        import ctypes

        from tritonclient.utils.shared_memory import _cshm

        buf = (ctypes.c_char * nbytes)()
        rc = _cshm.TpuShmRegionGet(self._host.base, offset, nbytes, buf)
        if rc != 0:
            raise XlaSharedMemoryException(
                "unable to read host window: {}".format(rc)
            )
        return bytes(buf)

    # -- server-facing interface (used by _XlaShmRegion in tpuserver) ------

    def read_bytes(self, offset, nbytes):
        root = self._root()
        for seg_off in list(root._segments):
            if seg_off >= offset and seg_off < offset + nbytes:
                self._sync_segment_to_host(seg_off)
        return root._read_host(offset, nbytes)

    def write_bytes(self, offset, data):
        root = self._root()
        root._segments.pop(offset, None)
        root._write_host(offset, data)

    def get_jax_segment(self, offset):
        """Public accessor: the device-resident ``jax.Array`` parked at
        ``offset``, or None when the slot holds no live segment."""
        seg = self._root()._segments.get(offset)
        return seg[0] if seg is not None else None

    def put_jax(self, offset, array):
        """Store a device array at ``offset``.  Returns True if it could stay
        on device (in-process), False if the caller must write bytes."""
        root = self._root()
        if root.closed:
            return False
        if self._local_owner is None and not self._inproc_attached and (
            not self._owner
        ):
            return False
        root._segments[offset] = (array, False)
        return True

    def detach(self):
        if self._local_owner is not None:
            root = self._root()
            root._inproc_attached = False
            return
        if not self._owner and self._host is not None and not self.closed:
            self.closed = True
            import ctypes

            from tritonclient.utils.shared_memory import _cshm

            _cshm.TpuShmRegionClose(
                self._host.shm_fd, self._host.base, self.byte_size
            )


def create_shared_memory_region(triton_shm_name, byte_size, device_ordinal=0):
    """Create an XLA shared-memory region of ``byte_size`` bytes addressable
    by TPU device ``device_ordinal``.  Returns an XlaShmHandle."""
    region_uuid = _uuid.uuid4().hex[:16]
    shm_key = "/xlashm_" + region_uuid
    host = _sysshm.create_shared_memory_region(
        triton_shm_name, shm_key, byte_size
    )
    handle = XlaShmHandle(
        triton_shm_name, byte_size, device_ordinal, shm_key, region_uuid,
        owner=True, host_window=host,
    )
    _LOCAL_REGIONS[region_uuid] = handle
    return handle


def get_raw_handle(handle):
    """Serialized, base64-encoded handle for the register RPC (mirrors the
    base64'd cudaIpcMemHandle_t of reference cuda_shared_memory.cc:98-127)."""
    payload = json.dumps(
        {
            "uuid": handle.uuid,
            "shm_key": handle.shm_key,
            "byte_size": handle.byte_size,
            "device_ordinal": handle.device_ordinal,
        }
    ).encode("utf-8")
    return base64.b64encode(payload)


def attach_from_raw_handle(raw_handle):
    """Attach to a region from its raw handle (server side of
    ``RegisterXlaSharedMemory``).  In-process attach aliases the owner's
    device segments — the zero-copy path; cross-process attach maps the host
    window."""
    if isinstance(raw_handle, str):
        raw_handle = raw_handle.encode("utf-8")
    try:
        info = json.loads(base64.b64decode(raw_handle))
    except Exception as e:
        raise XlaSharedMemoryException(
            "invalid xla shared memory raw handle: {}".format(e)
        )
    owner = _LOCAL_REGIONS.get(info["uuid"])
    if owner is not None:
        owner._inproc_attached = True
        return XlaShmHandle(
            owner._name, owner.byte_size, owner.device_ordinal,
            owner.shm_key, owner.uuid, owner=False, host_window=owner._host,
            local_owner=owner,
        )
    # Cross-process: open the host staging window.
    import ctypes

    from tritonclient.utils.shared_memory import (
        SharedMemoryRegionHandle,
        _cshm,
    )

    fd = ctypes.c_int()
    base = ctypes.c_void_p()
    rc = _cshm.TpuShmRegionOpen(
        info["shm_key"].encode("utf-8"), info["byte_size"], 0,
        ctypes.byref(fd), ctypes.byref(base),
    )
    if rc != 0:
        raise XlaSharedMemoryException(
            "unable to open host window for region {}: {}".format(
                info["shm_key"], rc
            )
        )
    host = SharedMemoryRegionHandle(
        "attached", info["shm_key"], fd.value, base.value, info["byte_size"]
    )
    return XlaShmHandle(
        "attached", info["byte_size"], info["device_ordinal"],
        info["shm_key"], info["uuid"], owner=False, host_window=host,
    )


def set_shared_memory_region(handle, input_values, offset=0):
    """Write arrays consecutively into the region starting at ``offset``.

    numpy arrays go to the host window (and to the device lazily on first
    use); ``jax.Array``s stay device-resident when an in-process server is
    attached (zero host copies), otherwise they are staged through the host
    window exactly once.
    """
    if not isinstance(input_values, (list, tuple)):
        raise XlaSharedMemoryException(
            "input_values must be specified as a list/tuple of arrays"
        )
    import jax

    root = handle._root()
    cur = offset
    for value in input_values:
        if isinstance(value, jax.Array):
            root._segments[cur] = (value, False)
            if not root._inproc_attached:
                # No in-process consumer known: stage eagerly so a
                # cross-process server sees the data.
                handle._sync_segment_to_host(cur)
            cur += int(value.size) * value.dtype.itemsize
        else:
            value = np.asarray(value)
            if value.dtype == np.object_ or value.dtype.type in (
                np.bytes_,
                np.str_,
            ):
                serialized = serialize_byte_tensor(value)
                data = serialized.item() if serialized.size > 0 else b""
            else:
                data = np.ascontiguousarray(value).tobytes()
            root._segments.pop(cur, None)
            root._write_host(cur, data)
            cur += len(data)


def set_shared_memory_region_from_jax(handle, arrays, offset=0):
    """Explicit jax.Array variant of :func:`set_shared_memory_region`."""
    import jax

    for a in arrays:
        if not isinstance(a, jax.Array):
            raise XlaSharedMemoryException(
                "set_shared_memory_region_from_jax requires jax.Array inputs"
            )
    set_shared_memory_region(handle, list(arrays), offset)


def _np_dtype_of(datatype):
    """Accept a numpy dtype or a Triton wire datatype string ('INT32')."""
    if isinstance(datatype, str):
        resolved = triton_to_np_dtype(datatype)
        if resolved is not None:
            return np.dtype(resolved) if datatype != "BYTES" else np.dtype(
                np.object_
            )
    return np.dtype(datatype)


def get_contents_as_numpy(handle, datatype, shape, offset=0):
    """Read region contents as a numpy array (one device->host fetch when the
    segment is device-resident, mirroring the staging copy of reference
    cuda_shared_memory.cc:160-179).  ``datatype`` may be a numpy dtype (as
    in the reference cuda API) or a Triton datatype string."""
    datatype = _np_dtype_of(datatype)
    root = handle._root()
    seg = root._segments.get(offset)
    if seg is not None:
        return np.asarray(seg[0]).astype(
            np.dtype(datatype), copy=False
        ).reshape(shape)
    np_dtype = np.dtype(datatype)
    if np_dtype == np.object_:
        raw = root._read_host(offset, root.byte_size - offset)
        return deserialize_bytes_tensor(raw)[: int(np.prod(shape))].reshape(
            shape
        )
    count = int(np.prod(shape)) if len(shape) else 1
    raw = root._read_host(offset, count * np_dtype.itemsize)
    return np.frombuffer(raw, dtype=np_dtype).reshape(shape)


def get_contents_as_jax(handle, datatype, shape, offset=0):
    """Read region contents as a jax.Array — zero-copy if device-resident."""
    root = handle._root()
    seg = root._segments.get(offset)
    if seg is not None:
        array = seg[0]
        return array.reshape(shape) if list(array.shape) != list(
            shape
        ) else array
    import jax

    return jax.device_put(
        get_contents_as_numpy(handle, datatype, shape, offset),
        _device(root.device_ordinal),
    )


def allocated_shared_memory_regions():
    """List handles of regions created by this process."""
    return list(_LOCAL_REGIONS.values())


def destroy_shared_memory_region(handle):
    """Release the region: device segments dropped, host window unlinked."""
    root = handle._root()
    if root.closed:
        return
    root.closed = True
    root._segments.clear()
    _LOCAL_REGIONS.pop(root.uuid, None)
    _sysshm.destroy_shared_memory_region(root._host)
