from tritonclient.utils.xla_shared_memory import *  # noqa: F401,F403
