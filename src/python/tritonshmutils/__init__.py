"""Deprecated alias of the shared-memory utility modules (reference
tritonshmutils shim)."""

import warnings

warnings.warn(
    "The package `tritonshmutils` is deprecated; use "
    "`tritonclient.utils.shared_memory` / "
    "`tritonclient.utils.xla_shared_memory` instead.",
    DeprecationWarning,
    stacklevel=2,
)

import tritonclient.utils.shared_memory as shared_memory  # noqa: F401,E402
import tritonclient.utils.xla_shared_memory as xla_shared_memory  # noqa: F401,E402
