#!/usr/bin/env python3
"""Build the client wheel with the native shm shim bundled (role of
reference src/python/library/build_wheel.py: compile artifacts, copy
into the package tree, invoke setup.py).

Usage: python build_wheel.py [--dest-dir DIR]
"""

import argparse
import os
import shutil
import subprocess
import sys

THIS_DIR = os.path.dirname(os.path.abspath(__file__))


def build_cshm():
    """Compile libcshm.so next to its ctypes loader so the wheel ships a
    prebuilt binary (the loader falls back to on-demand compilation when
    the bundled library is missing)."""
    src = os.path.join(
        os.path.dirname(THIS_DIR), "c++", "library", "cshm.cc"
    )
    dest = os.path.join(
        THIS_DIR, "tritonclient", "utils", "shared_memory", "libcshm.so"
    )
    if not os.path.exists(src):
        print("cshm.cc not found; wheel will compile on first import")
        return None
    gxx = shutil.which("g++")
    if gxx is None:
        print("g++ not found; wheel will compile on first import")
        return None
    subprocess.run(
        [gxx, "-O2", "-fPIC", "-shared", "-o", dest, src, "-lrt"],
        check=True,
    )
    return dest


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dest-dir", default=os.path.join(THIS_DIR, "dist"))
    args = parser.parse_args()

    bundled = build_cshm()
    try:
        subprocess.run(
            [sys.executable, "setup.py", "bdist_wheel",
             "--dist-dir", args.dest_dir],
            cwd=THIS_DIR, check=True,
        )
    finally:
        if bundled and os.path.exists(bundled):
            os.unlink(bundled)  # keep the source tree clean
    wheels = [
        f for f in os.listdir(args.dest_dir) if f.endswith(".whl")
    ]
    print("built: {}".format(sorted(wheels)[-1] if wheels else "nothing"))


if __name__ == "__main__":
    main()
