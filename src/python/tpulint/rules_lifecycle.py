"""R5 thread-lifecycle discipline.

Every ``threading.Thread`` created in server code must be either
``daemon=True`` (dies with its owner — the invariant the test suite's
thread-leak guard checks dynamically) or joined on a reachable
shutdown path: a method of the same class named ``close``/``stop``/
``drain``/``shutdown``/``__exit__`` that calls ``.join(...)`` and
mentions the attribute the thread was stored into.  A non-daemon
thread with neither wedges interpreter shutdown the first time its
loop outlives the owner.
"""

import ast

from tpulint.findings import Finding
from tpulint.rules_locks import _is_thread_join

_STOP_NAMES = ("close", "stop", "drain", "shutdown", "__exit__",
               "join", "_stop_sender", "_stop_workers")


def _method_joins_attr(fn, attr):
    """Does this method call ``.join`` and reference ``self.<attr>``?"""
    mentions_attr = False
    joins = False
    for node in ast.walk(fn.node):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr == attr):
            mentions_attr = True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and _is_thread_join(node)):
            joins = True
    return mentions_attr and joins


class ThreadLifecycleRule:
    id = "R5"
    name = "thread-lifecycle"

    def check(self, modules, config):
        findings = []
        for mod in modules:
            for tc in mod.thread_creations:
                if tc.daemon is True:
                    continue
                if tc.cls is not None and tc.target_attr is not None:
                    if any(
                        _method_joins_attr(fn, tc.target_attr)
                        for name, fn in tc.cls.methods.items()
                        if name in _STOP_NAMES
                    ):
                        continue
                where = "{}.{}".format(
                    tc.cls.name if tc.cls else "<module>",
                    tc.func.name if tc.func else "<module>")
                if tc.daemon is None:
                    detail = "has no daemon=True"
                else:
                    detail = "is daemon={!r}".format(tc.daemon)
                findings.append(Finding(
                    self.id, self.name, mod.relpath, tc.lineno,
                    "threading.Thread created in {}() {} and is not "
                    "joined in a close()/stop()/drain() path — it will "
                    "outlive its owner and wedge interpreter shutdown"
                    .format(where, detail),
                ))
        return findings
