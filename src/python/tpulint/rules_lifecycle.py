"""R5 thread-lifecycle discipline.

Every ``threading.Thread`` created in server code must be either
``daemon=True`` (dies with its owner — the invariant the test suite's
thread-leak guard checks dynamically) or joined on a reachable
shutdown path: a method of the same class named ``close``/``stop``/
``drain``/``shutdown``/``__exit__`` that calls ``.join(...)`` and
mentions the attribute the thread was stored into.  A non-daemon
thread with neither wedges interpreter shutdown the first time its
loop outlives the owner.

Companion check — crash-log WRITER threads (``name=`` contains
``"writer"``: the router-journal and fleet-manifest appenders) must
be BOTH: ``daemon=True`` so a crashing owner dies instead of wedging
on its writer (crash durability is the whole point of those logs —
the torn tail is recoverable, a hung process is not), AND joined on a
shutdown path so a CLEAN close drains the queued tail before the fd
goes away.  Either half alone silently weakens a durability story the
chaos suites depend on.
"""

import ast

from tpulint.findings import Finding
from tpulint.rules_locks import _is_thread_join

_STOP_NAMES = ("close", "stop", "drain", "shutdown", "__exit__",
               "join", "_stop_sender", "_stop_workers")


def _method_joins_attr(fn, attr):
    """Does this method call ``.join`` and reference ``self.<attr>``?"""
    mentions_attr = False
    joins = False
    for node in ast.walk(fn.node):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and node.attr == attr):
            mentions_attr = True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and _is_thread_join(node)):
            joins = True
    return mentions_attr and joins


class ThreadLifecycleRule:
    id = "R5"
    name = "thread-lifecycle"

    def check(self, modules, config):
        findings = []
        for mod in modules:
            for tc in mod.thread_creations:
                if tc.name is not None and "writer" in tc.name:
                    findings.extend(self._check_writer(mod, tc))
                    continue
                if tc.daemon is True:
                    continue
                if tc.cls is not None and tc.target_attr is not None:
                    if any(
                        _method_joins_attr(fn, tc.target_attr)
                        for name, fn in tc.cls.methods.items()
                        if name in _STOP_NAMES
                    ):
                        continue
                where = "{}.{}".format(
                    tc.cls.name if tc.cls else "<module>",
                    tc.func.name if tc.func else "<module>")
                if tc.daemon is None:
                    detail = "has no daemon=True"
                else:
                    detail = "is daemon={!r}".format(tc.daemon)
                findings.append(Finding(
                    self.id, self.name, mod.relpath, tc.lineno,
                    "threading.Thread created in {}() {} and is not "
                    "joined in a close()/stop()/drain() path — it will "
                    "outlive its owner and wedge interpreter shutdown"
                    .format(where, detail),
                ))
        return findings

    def _check_writer(self, mod, tc):
        """A thread named ``*writer*`` appends a crash log: it must be
        daemon=True AND joined — daemon alone drops the queued tail on
        clean close, joined alone wedges a crashing owner on its
        writer."""
        joined = tc.cls is not None and tc.target_attr is not None and any(
            _method_joins_attr(fn, tc.target_attr)
            for name, fn in tc.cls.methods.items()
            if name in _STOP_NAMES)
        where = "{}.{}".format(
            tc.cls.name if tc.cls else "<module>",
            tc.func.name if tc.func else "<module>")
        missing = []
        if tc.daemon is not True:
            missing.append(
                "daemon=True (a crashing owner must die, not wedge on "
                "its writer)")
        if not joined:
            missing.append(
                "a join in a close()/stop()/drain() path (a clean "
                "close must drain the queued tail)")
        if not missing:
            return []
        return [Finding(
            self.id, self.name, mod.relpath, tc.lineno,
            "writer thread {!r} created in {}() needs BOTH halves of "
            "the crash-log discipline; missing {}".format(
                tc.name, where, " and ".join(missing)),
        )]
