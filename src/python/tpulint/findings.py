"""Findings, suppression filtering, and the checked-in baseline.

A finding renders as ``path:line RULE(name) message``.  Its *fingerprint*
deliberately omits the line number — ``path|RULE|message`` — so a
baselined finding survives unrelated edits above it; messages are
written to be stable (they name attributes/classes, never positions).

Baseline file format: one fingerprint per line, ``#`` comments and blank
lines ignored.  Matching is multiset semantics — two identical findings
need two identical baseline lines.  Entries that no longer match any
finding are *stale* and reported for expiry (``--update-baseline``
rewrites the file from the current findings).
"""


class Finding:
    __slots__ = ("rule", "rule_name", "path", "lineno", "message")

    def __init__(self, rule, rule_name, path, lineno, message):
        self.rule = rule            # 'R1'..'R6'
        self.rule_name = rule_name  # 'guarded-by', ...
        self.path = path            # repo-relative
        self.lineno = lineno
        self.message = message

    @property
    def fingerprint(self):
        return "{}|{}|{}".format(self.path, self.rule, self.message)

    def render(self):
        return "{}:{} {}({}) {}".format(
            self.path, self.lineno, self.rule, self.rule_name, self.message
        )

    def __repr__(self):
        return "<Finding {}>".format(self.render())

    def sort_key(self):
        return (self.path, self.lineno, self.rule, self.message)


def filter_suppressed(findings, modules_by_path):
    """Drop findings carrying a ``# tpulint: disable=`` on their line
    (or the line above).  Rule id and rule name both work as tokens."""
    kept = []
    for f in findings:
        mod = modules_by_path.get(f.path)
        tokens = {f.rule.lower(), f.rule_name.lower()}
        if mod is not None and mod.suppressed(f.lineno, tokens):
            continue
        kept.append(f)
    return kept


def load_baseline(path):
    """Baseline fingerprints as an ordered list (multiset semantics)."""
    entries = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line and not line.startswith("#"):
                    entries.append(line)
    except FileNotFoundError:
        pass
    return entries


def apply_baseline(findings, baseline_entries):
    """Split findings into (new, grandfathered) and report stale
    baseline entries: ``(new_findings, grandfathered, stale_entries)``."""
    budget = {}
    for entry in baseline_entries:
        budget[entry] = budget.get(entry, 0) + 1
    new, grandfathered = [], []
    for f in sorted(findings, key=Finding.sort_key):
        fp = f.fingerprint
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            grandfathered.append(f)
        else:
            new.append(f)
    stale = []
    for entry in baseline_entries:
        if budget.get(entry, 0) > 0:
            budget[entry] -= 1
            stale.append(entry)
    return new, grandfathered, stale


def write_baseline(path, findings, header=""):
    lines = ["# tpulint baseline — grandfathered findings.",
             "# One fingerprint (path|RULE|message) per line; regenerate",
             "# with: python tools/tpulint.py --update-baseline"]
    if header:
        lines.append("# " + header)
    for f in sorted(findings, key=Finding.sort_key):
        lines.append(f.fingerprint)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
