"""R6 fault-point registry.

``tpuserver.faults.POINTS`` is the single source of truth for injection
-point names: the fault table in ``docs/resilience.md`` is checked
against it (tests/test_static_analysis.py) and chaos tooling enumerates
it.  This rule keeps the code in sync with the registry:

- every ``faults.fire("<name>", ...)`` site must use a **string
  literal** name that is a registered key (a typo'd point silently
  never fires — the chaos test arms a point production never hits);
- every registered point must have **exactly one** fire site in the
  analyzed tree (zero = dead registry entry the docs still advertise;
  two = one armed fault trips an unintended second site).

The rule only runs when the registry module (``faults.py`` defining
``POINTS``) is part of the analyzed set, so single-file lint runs stay
quiet.
"""

import ast

from tpulint.findings import Finding

REGISTRY_NAME = "POINTS"


class FaultRegistryRule:
    id = "R6"
    name = "fault-registry"

    def check(self, modules, config):
        registry_mod = None
        registry = None
        for mod in modules:
            if mod.relpath.endswith("faults.py") and \
                    REGISTRY_NAME in mod.dict_assignments:
                registry_mod = mod
                registry = {}
                node = mod.dict_assignments[REGISTRY_NAME]
                for k in node.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        registry[k.value] = k.lineno
        if registry is None:
            return []

        findings = []
        fire_sites = {}  # name -> [(mod, lineno)]
        for mod in modules:
            if mod is registry_mod:
                continue  # faults.fire's own definition/docs
            for site in mod.call_sites:
                if not (site.dotted.endswith(".fire")
                        or site.dotted == "fire"):
                    continue
                if not site.node.args:
                    continue
                arg = site.node.args[0]
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    findings.append(Finding(
                        self.id, self.name, mod.relpath, site.lineno,
                        "faults.fire() must be called with a string-"
                        "literal point name (dynamic names defeat the "
                        "registry check)",
                    ))
                    continue
                name = arg.value
                fire_sites.setdefault(name, []).append(
                    (mod, site.lineno))
                if name not in registry:
                    findings.append(Finding(
                        self.id, self.name, mod.relpath, site.lineno,
                        "fault point '{}' is not registered in "
                        "faults.POINTS — register it (and document it "
                        "in the resilience fault table) or fix the "
                        "name".format(name),
                    ))
        for name, lineno in sorted(registry.items()):
            sites = fire_sites.get(name, [])
            if not sites:
                findings.append(Finding(
                    self.id, self.name, registry_mod.relpath, lineno,
                    "registered fault point '{}' has no faults.fire() "
                    "site in the analyzed tree — dead registry entry"
                    .format(name),
                ))
            elif len(sites) > 1:
                extra_mod, extra_line = sites[1]
                findings.append(Finding(
                    self.id, self.name, extra_mod.relpath, extra_line,
                    "fault point '{}' fires at {} sites — one armed "
                    "fault would trip unintended sites; give each site "
                    "its own registered name".format(name, len(sites)),
                ))
        return findings
