"""File discovery, rule dispatch, and the lint entry point."""

import fnmatch
import os

from tpulint.analysis import analyze_file
from tpulint.findings import (
    apply_baseline,
    filter_suppressed,
    load_baseline,
)
from tpulint.rules_clocks import MonotonicClockRule
from tpulint.rules_faults import FaultRegistryRule
from tpulint.rules_lifecycle import ThreadLifecycleRule
from tpulint.rules_locks import BlockingUnderLockRule, GuardedByRule
from tpulint.rules_wiremap import WireMapRule

#: Registration order is report order within a line.
ALL_RULES = (
    GuardedByRule(),
    BlockingUnderLockRule(),
    MonotonicClockRule(),
    WireMapRule(),
    ThreadLifecycleRule(),
    FaultRegistryRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
RULES_BY_NAME = {r.name: r for r in ALL_RULES}

#: Generated / vendored files never linted.
EXCLUDE_PATTERNS = ("*_pb2.py", "*_pb2_grpc.py")


class LintConfig:
    def __init__(self, docs_path=None):
        self.docs_path = docs_path


class LintResult:
    def __init__(self, new, grandfathered, stale, modules):
        self.new = new                    # findings not in the baseline
        self.grandfathered = grandfathered  # baseline-matched findings
        self.stale = stale                # baseline entries with no match
        self.modules = modules

    @property
    def all_findings(self):
        return sorted(self.new + self.grandfathered,
                      key=lambda f: f.sort_key())


def discover(paths):
    """Every lintable .py under the given files/directories."""
    files = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = [d for d in sorted(dirs)
                       if d not in ("__pycache__",)]
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                if any(fnmatch.fnmatch(name, pat)
                       for pat in EXCLUDE_PATTERNS):
                    continue
                files.append(os.path.join(root, name))
    return files


def _relpath(path, root):
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def select_rules(spec):
    """``spec`` is None (all rules) or an iterable of ids/names."""
    if spec is None:
        return list(ALL_RULES)
    selected = []
    for token in spec:
        rule = RULES_BY_ID.get(token.upper()) or RULES_BY_NAME.get(
            token.lower())
        if rule is None:
            raise ValueError(
                "unknown rule {!r} (known: {})".format(
                    token, ", ".join(sorted(RULES_BY_ID))))
        if rule not in selected:
            selected.append(rule)
    return selected


def lint_paths(paths, rules=None, baseline_path=None, docs_path=None,
               repo_root=None):
    """Run the selected rules over ``paths``; returns a LintResult.

    Files that fail to parse produce a synthetic finding rather than
    aborting the run (a syntax error in one module must not unlint the
    rest of the tree).
    """
    from tpulint.findings import Finding

    root = repo_root or os.getcwd()
    config = LintConfig(docs_path=docs_path)
    modules = []
    parse_findings = []
    for path in discover(paths):
        rel = _relpath(path, root)
        try:
            modules.append(analyze_file(path, rel))
        except SyntaxError as e:
            parse_findings.append(Finding(
                "R0", "parse", rel, e.lineno or 0,
                "file does not parse: {}".format(e.msg)))
    findings = list(parse_findings)
    for rule in select_rules(rules):
        findings.extend(rule.check(modules, config))
    modules_by_path = {m.relpath: m for m in modules}
    findings = filter_suppressed(findings, modules_by_path)
    baseline_entries = (
        load_baseline(baseline_path) if baseline_path else [])
    new, grandfathered, stale = apply_baseline(findings, baseline_entries)
    return LintResult(new, grandfathered, stale, modules)
