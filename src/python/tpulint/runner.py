"""File discovery, rule dispatch, per-file caching, and the lint
entry point."""

import fnmatch
import os

from tpulint.analysis import analyze_file
from tpulint.callgraph import build_call_graph
from tpulint.findings import (
    apply_baseline,
    filter_suppressed,
    load_baseline,
)
from tpulint.rules_atomicity import AtomicityRule
from tpulint.rules_clocks import MonotonicClockRule
from tpulint.rules_faults import FaultRegistryRule
from tpulint.rules_lifecycle import ThreadLifecycleRule
from tpulint.rules_locks import BlockingUnderLockRule, GuardedByRule
from tpulint.rules_protocol import ProtocolParityRule
from tpulint.rules_wiremap import WireMapRule

#: Registration order is report order within a line.
ALL_RULES = (
    GuardedByRule(),
    BlockingUnderLockRule(),
    MonotonicClockRule(),
    WireMapRule(),
    ThreadLifecycleRule(),
    FaultRegistryRule(),
    AtomicityRule(),
    ProtocolParityRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
RULES_BY_NAME = {r.name: r for r in ALL_RULES}

#: Generated / vendored files never linted.
EXCLUDE_PATTERNS = ("*_pb2.py", "*_pb2_grpc.py")

#: Per-file ModuleInfo cache keyed by (abs path, repo-relative path):
#: an entry is valid while the file's (mtime_ns, size) is unchanged.
#: ModuleInfos are immutable once the shared pass finishes (rules only
#: read them), so one process can lint the same tree many times — the
#: tier-1 gate runs lint_paths per fixture and once over the real tree
#: — and pay the AST walk once per file.
_MODULE_CACHE = {}

#: Cold/warm observability for the cache behavior test.
CACHE_STATS = {"hits": 0, "misses": 0}


def clear_module_cache():
    _MODULE_CACHE.clear()
    CACHE_STATS["hits"] = CACHE_STATS["misses"] = 0


def _analyze_cached(path, rel):
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        stamp = None
    key = (path, rel)
    if stamp is not None:
        cached = _MODULE_CACHE.get(key)
        if cached is not None and cached[0] == stamp:
            CACHE_STATS["hits"] += 1
            return cached[1]
    CACHE_STATS["misses"] += 1
    info = analyze_file(path, rel)
    if stamp is not None:
        _MODULE_CACHE[key] = (stamp, info)
    return info


class LintConfig:
    """Per-run context handed to every rule.  ``callgraph`` builds
    lazily on first access, so runs selecting only intraprocedural
    rules (single-rule fixtures, ``--rules R1``) never pay for the
    whole-program pass."""

    def __init__(self, docs_path=None, modules=()):
        self.docs_path = docs_path
        self._modules = list(modules)
        self._callgraph = None

    @property
    def callgraph(self):
        if self._callgraph is None:
            self._callgraph = build_call_graph(self._modules)
        return self._callgraph


class LintResult:
    def __init__(self, new, grandfathered, stale, modules):
        self.new = new                    # findings not in the baseline
        self.grandfathered = grandfathered  # baseline-matched findings
        self.stale = stale                # baseline entries with no match
        self.modules = modules

    @property
    def all_findings(self):
        return sorted(self.new + self.grandfathered,
                      key=lambda f: f.sort_key())


def discover(paths):
    """Every lintable .py under the given files/directories."""
    files = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = [d for d in sorted(dirs)
                       if d not in ("__pycache__",)]
            for name in sorted(names):
                if not name.endswith(".py"):
                    continue
                if any(fnmatch.fnmatch(name, pat)
                       for pat in EXCLUDE_PATTERNS):
                    continue
                files.append(os.path.join(root, name))
    return files


def _relpath(path, root):
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def select_rules(spec):
    """``spec`` is None (all rules) or an iterable of ids/names."""
    if spec is None:
        return list(ALL_RULES)
    selected = []
    for token in spec:
        rule = RULES_BY_ID.get(token.upper()) or RULES_BY_NAME.get(
            token.lower())
        if rule is None:
            raise ValueError(
                "unknown rule {!r} (known: {})".format(
                    token, ", ".join(sorted(RULES_BY_ID))))
        if rule not in selected:
            selected.append(rule)
    return selected


def lint_paths(paths, rules=None, baseline_path=None, docs_path=None,
               repo_root=None):
    """Run the selected rules over ``paths``; returns a LintResult.

    Files that fail to parse produce a synthetic finding rather than
    aborting the run (a syntax error in one module must not unlint the
    rest of the tree).
    """
    from tpulint.findings import Finding

    root = repo_root or os.getcwd()
    modules = []
    parse_findings = []
    for path in discover(paths):
        rel = _relpath(path, root)
        try:
            modules.append(_analyze_cached(path, rel))
        except SyntaxError as e:
            parse_findings.append(Finding(
                "R0", "parse", rel, e.lineno or 0,
                "file does not parse: {}".format(e.msg)))
    # one whole-program call graph per run (built lazily by the
    # config), shared by every interprocedural rule (R2i today)
    config = LintConfig(docs_path=docs_path, modules=modules)
    findings = list(parse_findings)
    for rule in select_rules(rules):
        findings.extend(rule.check(modules, config))
    modules_by_path = {m.relpath: m for m in modules}
    findings = filter_suppressed(findings, modules_by_path)
    baseline_entries = (
        load_baseline(baseline_path) if baseline_path else [])
    new, grandfathered, stale = apply_baseline(findings, baseline_entries)
    return LintResult(new, grandfathered, stale, modules)
