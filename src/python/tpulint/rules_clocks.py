"""R3 monotonic-clock discipline.

Every deadline, timeout, liveness stamp, and interval in this codebase
is ``time.monotonic()`` math — wall clocks jump (NTP steps, suspend)
and a jumped deadline either fires years early or never.  The rule:

1. **wall-clock reads are banned** in linted code: any call to
   ``time.time`` / ``time.time_ns`` / ``datetime.now`` /
   ``datetime.utcnow`` is a finding.  The single sanctioned wall-clock
   site is ``tpuserver._clock.wall_clock_ms()`` — the wire-format
   reporting boundary, suppressed inline where it is defined.
2. **flow check**: a name assigned from a wall-clock call must not be
   compared, used in arithmetic, passed to a ``timeout=``/``deadline=``
   parameter or a ``.wait(...)`` call, or stored into a deadline-named
   target — each such use is its own finding (the fixture suite's
   taint cases; on a clean tree check 1 already keeps these at zero).
"""

import ast

from tpulint.analysis import _dotted
from tpulint.findings import Finding

_WALL_CALLS = {
    "time.time", "time.time_ns",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
}

_SINK_NAME = ("deadline", "expire", "expiry", "until", "timeout")


def _is_wall_call(node):
    return isinstance(node, ast.Call) and _dotted(node.func) in _WALL_CALLS


def _nested_def(node):
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))


def _walk_own_scope(fn_node):
    """Every node of a function's OWN body, in DOCUMENT order — the
    taint pass needs an assignment yielded before every later use,
    regardless of how deeply the assignment is nested (pre-order DFS;
    breadth-first would pop a shallow sink before a deeper, lexically
    earlier assignment).  Nested def subtrees are pruned, not just
    skipped: they have their own FunctionInfo, and analyzing them here
    would double-report their defects and leak the outer scope's taint
    into a different runtime scope."""
    stack = [n for n in reversed(fn_node.body) if not _nested_def(n)]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(
            child for child in
            reversed(list(ast.iter_child_nodes(node)))
            if not _nested_def(child))


class MonotonicClockRule:
    id = "R3"
    name = "monotonic-clock"

    def check(self, modules, config):
        findings = []
        for mod in modules:
            findings.extend(self._check_module(mod))
        return findings

    def _check_module(self, mod):
        findings = []
        # check 1: ban the calls outright
        for site in mod.call_sites:
            if site.dotted in _WALL_CALLS:
                findings.append(Finding(
                    self.id, self.name, mod.relpath, site.lineno,
                    "wall-clock read {}(): deadlines/timeouts/liveness "
                    "must use time.monotonic(); wire-format wall-clock "
                    "stamps go through tpuserver._clock.wall_clock_ms()"
                    .format(site.dotted),
                ))

        # check 2: per-function taint of wall-clock values into
        # deadline/timeout sinks
        for fn in mod.functions:
            findings.extend(self._check_flow(mod, fn))
        return findings

    def _check_flow(self, mod, fn):
        findings = []
        tainted = set()

        def value_tainted(node):
            for sub in ast.walk(node):
                if _is_wall_call(sub):
                    return True
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
            return False

        def flag(node, how):
            findings.append(Finding(
                self.id, self.name, mod.relpath, node.lineno,
                "wall-clock-derived value {} in {}(): deadline/timeout "
                "arithmetic must originate from time.monotonic()".format(
                    how, fn.name),
            ))

        for node in _walk_own_scope(fn.node):
            if isinstance(node, ast.Assign) and value_tainted(node.value):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        tainted.add(target.id)
                    name = getattr(target, "attr",
                                   getattr(target, "id", ""))
                    if any(s in name.lower() for s in _SINK_NAME):
                        flag(node, "stored into deadline-named "
                                   "'{}'".format(name))
            elif isinstance(node, ast.Compare):
                if value_tainted(node):
                    flag(node, "used in a comparison")
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg in ("timeout", "deadline",
                                  "timeout_s", "deadline_s") and \
                            value_tainted(kw.value):
                        flag(node, "passed as {}=".format(kw.arg))
                if not _is_wall_call(node) and \
                        _dotted(node.func).endswith(".wait"):
                    for arg in node.args:
                        if value_tainted(arg):
                            flag(node, "passed to .wait()")
        return findings
