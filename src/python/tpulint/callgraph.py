"""Project-wide call graph over the shared pass's ModuleInfos.

The lexical facts ``tpulint.analysis`` computes stop at function
boundaries: a ``with self._lock: self._helper()`` looks innocent even
when ``_helper`` reaches ``time.sleep`` three calls down.  This module
builds one best-effort call graph across every analyzed module and
derives two transitive properties the interprocedural rules consume:

- **blocking-ness** (R2i): a function *blocks* when it directly calls a
  blocking primitive (``time.sleep`` / ``Thread.join`` /
  ``Future.result()`` / socket-HTTP I/O — the same set the lexical R2
  check uses; ``Condition.wait`` stays a purely lexical concern because
  its legality depends on the caller's held locks) or when any resolved
  callee blocks.  Reported findings carry the witness chain
  (``_helper -> _deep -> time.sleep``).
- **lock acquisitions** (R2i's lock-order graph): the set of locks a
  function acquires anywhere in its call tree, so an AB/BA deadlock
  split across ``a(): with _x: self.b()`` / ``b(): with _y: ...`` in
  two different methods is an edge, not a blind spot.

Call resolution is *name-based and best-effort* (this is Python):

- ``self.method()`` resolves in the receiver class, then its base
  classes (name-resolved across the analyzed set, the R4 hierarchy
  index).
- ``name()`` resolves to a module-level function of the same module,
  else — only when the calling module has ``from <m> import name`` —
  to the module-level ``name`` of the analyzed module whose basename
  is ``<m>`` (an imported helper).  A bare name with no matching
  import stays unresolved: binding by name alone could attach an
  unrelated same-named function from another module and fabricate a
  witness chain.
- ``Class.method()`` resolves when ``Class`` is an analyzed class;
  ``module.func()`` resolves when ``module`` matches an analyzed
  module's basename and defines ``func`` at top level.
- Everything else (``obj.attr.method()``, dynamic dispatch) stays
  unresolved — unresolved calls are assumed non-blocking, so the
  analysis under-reports rather than false-positives.

Two annotation escape hatches close the gaps (on the ``def`` line or
alone on the line above):

- ``# tpulint: blocks`` — force the function blocking (e.g. a wrapper
  around an unanalyzed C extension that sleeps).
- ``# tpulint: nonblocking`` — force it non-blocking (e.g. a callee
  that only ever runs with a bounded, sub-millisecond timeout).
"""

import re

from tpulint.analysis import CONVENTION

BLOCKS_RE = re.compile(r"#\s*tpulint:\s*(blocks|nonblocking)\b")


def _annotation(mod, fn):
    """'blocks' / 'nonblocking' / None for a function, read from the
    def line's trailing comment or a comment-only line above it."""
    for ln in (fn.lineno, fn.lineno - 1):
        if ln != fn.lineno and ln not in mod.comment_only_lines:
            continue
        comment = mod.comments.get(ln)
        if comment:
            m = BLOCKS_RE.search(comment)
            if m:
                return m.group(1)
    return None


class CallGraph:
    """Nodes are FunctionInfos; edges are resolved call sites."""

    def __init__(self, modules):
        self.modules = list(modules)
        # (class name, method name) -> FunctionInfo (first definition
        # wins, matching the one-definition rule R4 enforces)
        self.methods = {}
        # (module relpath, func name) -> FunctionInfo  (module-level)
        self.module_funcs = {}
        # module basename (no .py) -> ModuleInfo
        self.mod_by_basename = {}
        # class name -> ClassInfo (flat, first wins)
        self.classes = {}
        self.mod_of = {}          # FunctionInfo -> ModuleInfo
        self.annotations = {}     # FunctionInfo -> 'blocks'/'nonblocking'
        self.edges = {}           # FunctionInfo -> [(CallSite, callee)]
        self._blocking = None     # FunctionInfo -> witness chain list
        self._acquires = None     # FunctionInfo -> set(lock ids)
        self._build()

    # -- construction ------------------------------------------------------

    def _build(self):
        for mod in self.modules:
            base = mod.relpath.rsplit("/", 1)[-1]
            if base.endswith(".py"):
                base = base[:-3]
            self.mod_by_basename.setdefault(base, mod)
            for cls in mod.classes.values():
                self.classes.setdefault(cls.name, cls)
                for name, fn in cls.methods.items():
                    self.methods.setdefault((cls.name, name), fn)
            for fn in mod.functions:
                self.mod_of[fn] = mod
                ann = _annotation(mod, fn)
                if ann:
                    self.annotations[fn] = ann
                if fn.cls is None:
                    self.module_funcs.setdefault((mod.relpath, fn.name), fn)
        for mod in self.modules:
            for site in mod.call_sites:
                if site.func is None:
                    continue
                callee = self.resolve(site, mod)
                if callee is not None:
                    self.edges.setdefault(site.func, []).append(
                        (site, callee))

    def _method_in_hierarchy(self, cls, name, seen=None):
        """Resolve a method in ``cls`` or its (name-resolved) bases."""
        seen = seen if seen is not None else set()
        while cls is not None and cls.name not in seen:
            seen.add(cls.name)
            fn = cls.methods.get(name)
            if fn is not None:
                return fn
            nxt = None
            for base in cls.bases:
                cand = self.classes.get(base.rsplit(".", 1)[-1])
                if cand is not None:
                    nxt = cand
                    break
            cls = nxt
        return None

    def resolve(self, site, mod):
        """The FunctionInfo a call site dispatches to, or None."""
        dotted = site.dotted
        if dotted.startswith("self."):
            rest = dotted[len("self."):]
            if "." in rest or site.cls is None:
                return None  # self.attr.method(): unresolvable receiver
            return self._method_in_hierarchy(site.cls, rest)
        if "." not in dotted:
            fn = self.module_funcs.get((mod.relpath, dotted))
            if fn is not None:
                return fn
            # cross-module only through an explicit `from X import name`
            # in the CALLING module — by-name binding alone could attach
            # an unrelated same-named function and fabricate a chain
            src = mod.from_imports.get(dotted)
            if src:
                target_mod = self.mod_by_basename.get(src)
                if target_mod is not None:
                    return self.module_funcs.get(
                        (target_mod.relpath, dotted))
            return None
        head, _, tail = dotted.partition(".")
        if "." in tail:
            return None
        cls = self.classes.get(head)
        if cls is not None:
            return self._method_in_hierarchy(cls, tail)
        target_mod = self.mod_by_basename.get(head)
        if target_mod is not None:
            return self.module_funcs.get((target_mod.relpath, tail))
        return None

    # -- transitive blocking-ness ------------------------------------------

    def _ensure_blocking(self):
        """Least-fixpoint blocking set with witness chains.

        Computed whole-graph rather than per-query recursion so the
        result is order-independent: a member of a call cycle is
        blocking iff anything reachable from the cycle blocks, no
        matter which function a rule happens to ask about first (a
        recursive memo would finalize "non-blocking" for a node whose
        only callee was still open on the stack)."""
        if self._blocking is not None:
            return
        from tpulint.rules_locks import _is_blocking_call

        blocking = {}  # FunctionInfo -> witness chain
        for fn, ann in self.annotations.items():
            if ann == "blocks":
                blocking[fn] = ["(annotated '# tpulint: blocks')"]
        for mod in self.modules:
            for site in mod.call_sites:
                fn = site.func
                if (fn is None or fn in blocking
                        or self.annotations.get(fn) == "nonblocking"):
                    continue
                desc = _is_blocking_call(site)
                if desc is not None:
                    blocking[fn] = [desc]
        changed = True
        while changed:
            changed = False
            for fn in self.mod_of:
                if fn in blocking or \
                        self.annotations.get(fn) == "nonblocking":
                    continue
                for site, callee in self.edges.get(fn, ()):
                    sub = blocking.get(callee)
                    if sub is not None:
                        # extends a FINAL chain, so chains stay finite
                        # and end in a primitive/annotation witness
                        blocking[fn] = [site.dotted] + sub
                        changed = True
                        break
        self._blocking = blocking

    def blocking_chain(self, fn):
        """None when ``fn`` cannot be shown to block; else the witness
        chain ``['helper', '_deep', 'time.sleep']`` (call names ending
        in the blocking primitive's description)."""
        self._ensure_blocking()
        return self._blocking.get(fn)

    # -- transitive lock acquisition ---------------------------------------

    @staticmethod
    def _lock_id(name, cls, mod):
        # mirror rules_locks: Condition-over-lock aliases collapse to
        # the underlying lock so the two names cannot fabricate edges
        if cls is not None:
            name = cls.lock_aliases.get(name, name)
        return (cls.name if cls is not None else mod.relpath, name)

    def acquires(self, fn):
        """Every lock id ``fn`` acquires directly or via resolved
        callees, as ``frozenset((scope, lock))``.

        Least fixpoint over the whole graph (not per-query recursion)
        so call cycles cannot drop acquisitions depending on which
        function is asked about first."""
        if self._acquires is None:
            result = {f: set() for f in self.mod_of}
            for mod in self.modules:
                for wl in mod.with_locks:
                    if wl.func is not None:
                        result.setdefault(wl.func, set()).add(
                            self._lock_id(wl.lock, wl.cls, mod))
            changed = True
            while changed:
                changed = False
                for f in self.mod_of:
                    acc = result[f]
                    before = len(acc)
                    for _site, callee in self.edges.get(f, ()):
                        acc |= result.get(callee, set())
                    if len(acc) != before:
                        changed = True
            self._acquires = result
        return frozenset(self._acquires.get(fn, ()))

    def acquisition_edges(self):
        """Interprocedural lock-order edges: for every call site made
        while lock(s) are lexically held, an edge from each held lock
        to every lock the callee's call tree acquires.  Returns
        ``{(held_id, acquired_id): (relpath, lineno)}`` (first witness
        wins).  Walks the already-resolved ``self.edges`` — no second
        resolution pass over the tree."""
        edges = {}
        for fn, pairs in self.edges.items():
            mod = self.mod_of.get(fn)
            if mod is None:
                continue
            for site, callee in pairs:
                if not site.locks:
                    continue
                targets = self.acquires(callee)
                if not targets:
                    continue
                for held in site.locks:
                    if held == CONVENTION:
                        continue
                    held_id = self._lock_id(held, site.cls, mod)
                    for tgt in targets:
                        if held_id != tgt:
                            edges.setdefault(
                                (held_id, tgt), (mod.relpath, site.lineno))
        return edges


def build_call_graph(modules):
    return CallGraph(modules)
