"""R1 (guarded-by) and R2 (no-blocking-under-lock + lock-order graph).

Both rules read the lexical lock contexts the shared pass computed; see
docs/static_analysis.md for the annotation and suppression contract.
"""

import ast

from tpulint.analysis import CONVENTION
from tpulint.findings import Finding


def _lock_satisfied(name, held, cls):
    """Whether lock ``name`` is covered by the held set, following the
    class's Condition-over-lock aliases in both directions."""
    if name in held or CONVENTION in held:
        return True
    aliases = cls.lock_aliases if cls is not None else {}
    if aliases.get(name) in held:
        return True
    return any(aliases.get(h) == name for h in held)


class GuardedByRule:
    """R1 guarded-by: a field declared ``# guarded-by: _lock`` may only
    be read or written inside a ``with self._lock:`` block (or a
    ``*_locked``-suffix method, which the project convention defines as
    "called with the class's locks held") in its class's methods.

    ``__init__`` is exempt (object construction happens-before any
    sharing).  Double-checked-locking fields and cross-object protocols
    stay UNannotated — annotation is the opt-in that turns the
    convention into a checked invariant.
    """

    id = "R1"
    name = "guarded-by"

    def check(self, modules, config):
        findings = []
        for mod in modules:
            for cls in mod.classes.values():
                if not cls.guarded:
                    continue
                for acc in mod.attr_accesses:
                    if acc.cls is not cls or acc.attr not in cls.guarded:
                        continue
                    if acc.func is not None and acc.func.name in (
                            "__init__", "__new__"):
                        continue
                    lock, _decl_line = cls.guarded[acc.attr]
                    if _lock_satisfied(lock, acc.locks, cls):
                        continue
                    findings.append(Finding(
                        self.id, self.name, mod.relpath, acc.lineno,
                        "{}.{} is declared guarded-by {} but is {} "
                        "outside a 'with self.{}' block in {}()".format(
                            cls.name, acc.attr, lock,
                            "written" if acc.is_store else "read",
                            lock,
                            acc.func.name if acc.func else "<module>",
                        ),
                    ))
        return findings


#: Call patterns that block the calling thread.  ``.join()`` with zero
#: positional args is a thread/process join (``str.join`` always takes
#: the iterable positionally); ``.result()`` is a future wait.
_BLOCKING_DOTTED = {"time.sleep", "sleep"}
_BLOCKING_SUFFIXES = (
    ".recv", ".recvfrom", ".accept", ".connect", ".sendall",
    ".getresponse", ".urlopen",
)
_BLOCKING_NAMES = {"urlopen"}


def _is_thread_join(node):
    """``x.join()`` / ``x.join(5)`` / ``x.join(timeout=...)`` is a
    thread/process join; ``str.join`` always takes a non-numeric
    iterable positionally."""
    if not node.args:
        return True
    return len(node.args) == 1 and isinstance(
        node.args[0], ast.Constant) and isinstance(
        node.args[0].value, (int, float))


def _is_blocking_call(site):
    dotted = site.dotted
    if dotted in _BLOCKING_DOTTED or dotted in _BLOCKING_NAMES:
        return "time.sleep" if "sleep" in dotted else dotted
    if dotted.endswith(".join") and _is_thread_join(site.node):
        return "Thread.join"
    if dotted.endswith(".result"):
        return "Future.result"
    if dotted.endswith(_BLOCKING_SUFFIXES):
        return "socket/HTTP call " + dotted
    if dotted.startswith("requests."):
        return "HTTP call " + dotted
    return None


def _wait_on_held_lock(site):
    """``self._cond.wait(...)`` / ``.wait_for`` on a lock that is
    lexically held (directly or via a Condition-over-lock alias) — the
    one sanctioned block-under-lock."""
    dotted = site.dotted
    for suffix in (".wait", ".wait_for"):
        if dotted.endswith(suffix):
            receiver = dotted[: -len(suffix)]
            if receiver.startswith("self."):
                receiver = receiver[len("self."):]
            return _lock_satisfied(receiver, site.locks, site.cls)
    return False


class BlockingUnderLockRule:
    """R2 no-blocking-under-lock: no ``time.sleep``, ``Thread.join``,
    socket/HTTP call, or ``Future.result()`` lexically inside a held-
    lock block — every other thread needing that lock stalls for the
    full blocking duration.  ``Condition.wait`` on the *held* lock is
    the one exemption (it releases the lock while waiting).

    The check is **interprocedural** (R2i): blocking-ness propagates
    through the project call graph (``tpulint.callgraph``), so ``with
    self._lock: self._helper()`` is a finding when ``_helper`` reaches
    ``time.sleep`` / socket I/O / ``Future.result()`` at ANY depth —
    the finding names the witness chain.  ``# tpulint: nonblocking``
    on the callee's ``def`` line vouches for a callee the resolver
    over-approximates; ``# tpulint: blocks`` forces one it cannot see
    into (an unanalyzed extension that sleeps).

    The rule also builds a lock-acquisition-order graph — an edge for
    every lock acquired while another is lexically held, plus every
    lock the call graph shows a callee's call TREE acquiring — and
    requires it to be acyclic: a cycle is a latent AB/BA deadlock,
    even when the two acquisition chains live in different methods or
    modules.
    """

    id = "R2"
    name = "no-blocking-under-lock"

    def check(self, modules, config):
        from tpulint.callgraph import build_call_graph

        graph = getattr(config, "callgraph", None)
        if graph is None:
            graph = build_call_graph(modules)
        findings = []
        for mod in modules:
            for site in mod.call_sites:
                if not site.locks:
                    continue
                if _wait_on_held_lock(site):
                    continue
                desc = _is_blocking_call(site)
                via = None
                if desc is None:
                    # .wait on something that is NOT the held lock
                    # (e.g. an Event) blocks without releasing it
                    if (site.dotted.endswith(".wait")
                            or site.dotted.endswith(".wait_for")):
                        desc = "wait on {} (not the held lock)".format(
                            site.dotted.rsplit(".", 1)[0])
                    else:
                        # R2i: does the callee's call tree block?
                        callee = graph.resolve(site, mod)
                        if callee is None:
                            continue
                        chain = graph.blocking_chain(callee)
                        if chain is None:
                            continue
                        desc = "call"
                        via = " -> ".join([site.dotted] + chain)
                held = sorted(x for x in site.locks if x != CONVENTION)
                findings.append(Finding(
                    self.id, self.name, mod.relpath, site.lineno,
                    "blocking {}{} while holding lock(s) {} in "
                    "{}.{}()".format(
                        desc,
                        " ({})".format(via) if via else "",
                        "/".join(held) if held else
                        "(held by *_locked convention)",
                        site.cls.name if site.cls else "<module>",
                        site.func.name if site.func else "<module>",
                    ),
                ))
        findings.extend(self._check_lock_order(modules, graph))
        return findings

    # -- lock-acquisition-order graph --------------------------------------

    def _lock_id(self, name, cls, mod):
        # Condition-over-lock aliases collapse to the underlying lock:
        # `_cond = threading.Condition(self._lock)` is ONE lock, and
        # treating the two names as distinct would fabricate orderings
        if cls is not None:
            name = cls.lock_aliases.get(name, name)
        return (cls.name if cls is not None else mod.relpath, name)

    def _check_lock_order(self, modules, graph):
        edges = {}  # (from_id, to_id) -> (relpath, lineno)

        def add_edge(a, b, relpath, lineno):
            if a != b:
                edges.setdefault((a, b), (relpath, lineno))

        for mod in modules:
            for wl in mod.with_locks:
                inner = self._lock_id(wl.lock, wl.cls, mod)
                for held in wl.held:
                    if held == CONVENTION:
                        continue
                    add_edge(self._lock_id(held, wl.cls, mod), inner,
                             mod.relpath, wl.lineno)
        # interprocedural edges: a call made under a held lock orders
        # that lock before every lock the callee's call tree acquires
        for (a, b), where in graph.acquisition_edges().items():
            add_edge(a, b, *where)

        return self._report_cycles(edges)

    def _report_cycles(self, edges):
        graph = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        findings = []
        seen_cycles = set()
        state = {}

        def dfs(node, stack):
            state[node] = 1
            stack.append(node)
            for nxt in sorted(graph.get(node, ())):
                if state.get(nxt, 0) == 1:
                    cycle = tuple(stack[stack.index(nxt):] + [nxt])
                    key = frozenset(cycle)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        relpath, lineno = edges[(cycle[-2], cycle[-1])]
                        findings.append(Finding(
                            self.id, self.name, relpath, lineno,
                            "lock-acquisition-order cycle: {}".format(
                                " -> ".join(
                                    "{}.{}".format(scope, lock)
                                    for scope, lock in cycle
                                )),
                        ))
                elif state.get(nxt, 0) == 0:
                    dfs(nxt, stack)
            stack.pop()
            state[node] = 2

        for node in sorted(graph):
            if state.get(node, 0) == 0:
                dfs(node, [])
        return findings
