"""R7 atomicity-violation (check-then-act across a lock release).

The torn shape that caused PR 6's real ``_beat``/``healthy`` findings,
caught structurally: within ONE function, a ``# guarded-by:`` field is
read under its lock, the lock is released, and the stale value then
either

- **guards a branch** that re-acquires the lock and stores to guarded
  state (check-then-act: the state may have changed between the two
  critical sections), or
- **feeds the value stored back** into guarded state under a later
  re-acquisition (read-modify-write torn in half: a concurrent update
  between the sections is silently lost).

Either way the decision rests on a value another thread may have
invalidated.  The fix is almost always to widen the critical section
(one ``with`` around read + decide + act) or to re-read under the
second acquisition.  Deliberate snapshot-then-act protocols (DCL,
cross-object handoffs) stay out of scope the same way they do for R1:
their fields are deliberately NOT ``# guarded-by:``-annotated —
annotation is the opt-in.

Scope and precision:

- Only **top-level** (non-nested) ``with <lock>`` regions of one
  function body are paired; the lock is provably released between two
  disjoint regions.
- The read must bind a **local name** inside region A (``x =
  self._state`` or any assignment whose right side mentions the
  guarded read); taint follows plain local assignments between
  regions.
- Region B must acquire the **same lock** (Condition aliases count)
  and store to a field guarded by it.
- "Guards a branch" means region B sits inside an ``if``/``while``
  whose test mentions a tainted name; "feeds the store" means the
  stored value does.
"""

import ast

from tpulint.analysis import _lock_name
from tpulint.findings import Finding
from tpulint.rules_locks import _lock_satisfied


class _Region:
    """One top-level ``with <lock>`` region of a function body."""

    __slots__ = ("lock", "node", "lineno", "reads", "writes", "bound",
                 "tests")

    def __init__(self, lock, node, tests):
        self.lock = lock
        self.node = node
        self.lineno = node.lineno
        self.reads = set()    # guarded attrs loaded inside
        self.writes = {}      # guarded attr -> store lineno
        self.bound = {}       # local name -> guarded attr it snapshots
        self.tests = tests    # enclosing if/while test nodes (lexical)


def _nested_def(node):
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda))


def _guarded_loads(node, guarded):
    """Guarded ``self.X`` attrs loaded anywhere under ``node``."""
    found = set()
    for sub in ast.walk(node):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and isinstance(sub.ctx, ast.Load)
                and sub.attr in guarded):
            found.add(sub.attr)
    return found


def _names_in(node):
    return {sub.id for sub in ast.walk(node) if isinstance(sub, ast.Name)}


def _collect_regions(fn_node, cls):
    """Top-level lock regions of a function, in document order, each
    carrying the ``if``/``while`` tests that lexically enclose it
    (shape A's "decide" step)."""
    regions = []

    def scan(body, tests):
        for stmt in body:
            if _nested_def(stmt):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                lock = None
                for item in stmt.items:
                    name = _lock_name(item.context_expr)
                    if name is not None:
                        lock = name
                        break
                if lock is not None:
                    region = _Region(lock, stmt, list(tests))
                    _fill_region(region, stmt, cls)
                    regions.append(region)
                else:
                    # a non-lock with (file, injected(...)): transparent
                    scan(stmt.body, tests)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                scan(stmt.body, tests + [stmt.test])
                scan(stmt.orelse, tests + [stmt.test])
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan(stmt.body, tests)
                scan(stmt.orelse, tests)
            elif isinstance(stmt, ast.Try):
                scan(stmt.body, tests)
                for handler in stmt.handlers:
                    scan(handler.body, tests)
                scan(stmt.orelse, tests)
                scan(stmt.finalbody, tests)

    scan(fn_node.body, [])
    return regions


def _walk_no_defs(root):
    """Pre-order walk that prunes nested def/lambda subtrees (their
    bodies run later, without the lock)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        stack.extend(child for child in ast.iter_child_nodes(node)
                     if not _nested_def(child))


def _fill_region(region, with_node, cls):
    guarded = {a for a, (lock, _ln) in cls.guarded.items()
               if _lock_satisfied(lock, frozenset([region.lock]), cls)}
    for sub in _walk_no_defs(with_node):
        if (isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and sub.attr in guarded):
            if isinstance(sub.ctx, ast.Load):
                region.reads.add(sub.attr)
            else:
                region.writes.setdefault(sub.attr, sub.lineno)
        if isinstance(sub, ast.Assign):
            loads = _guarded_loads(sub.value, guarded)
            if loads:
                for target in sub.targets:
                    if isinstance(target, ast.Name):
                        region.bound[target.id] = sorted(loads)[0]


class AtomicityRule:
    id = "R7"
    name = "atomicity"

    def check(self, modules, config):
        findings = []
        for mod in modules:
            for cls in mod.classes.values():
                if not cls.guarded:
                    continue
                for name, fn in cls.methods.items():
                    if name in ("__init__", "__new__") or \
                            name.endswith("_locked"):
                        continue
                    findings.extend(self._check_function(mod, cls, fn))
        return findings

    def _check_function(self, mod, cls, fn):
        regions = _collect_regions(fn.node, cls)
        if len(regions) < 2:
            return []
        findings = []
        for i, first in enumerate(regions):
            if not first.bound:
                continue
            # taint: locals snapshotting guarded state in region i,
            # widened through plain assignments later in the function
            tainted = dict(first.bound)  # name -> source attr
            for later in regions[i + 1:]:
                if later.lock != first.lock and not (
                        _lock_satisfied(later.lock,
                                        frozenset([first.lock]), cls)):
                    continue
                if not later.writes:
                    continue
                self._propagate_taint(fn.node, first, later, tainted)
                hit = self._torn_pair(first, later, tainted, cls)
                if hit is not None:
                    findings.append(Finding(
                        self.id, self.name, mod.relpath, hit["lineno"],
                        "check-then-act across a lock release in "
                        "{}.{}(): {}.{} is read under {} into '{}' and "
                        "{} after the lock is released — widen the "
                        "critical section or re-read under the second "
                        "acquisition".format(
                            cls.name, fn.name, cls.name, hit["attr"],
                            first.lock, hit["local"], hit["how"]),
                    ))
        return findings

    def _propagate_taint(self, fn_node, first, later, tainted):
        """Follow ``y = f(x)`` assignments lexically between the two
        regions (outside any lock region)."""
        for stmt in ast.walk(fn_node):
            if not isinstance(stmt, ast.Assign):
                continue
            if not (first.node.end_lineno < stmt.lineno
                    < later.node.lineno):
                continue
            if _names_in(stmt.value) & set(tainted):
                src = next(iter(_names_in(stmt.value) & set(tainted)))
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        tainted.setdefault(target.id, tainted[src])

    def _torn_pair(self, first, later, tainted, cls):
        """A (read-region, act-region) pair is torn when the act is
        conditioned on, or computed from, the stale snapshot."""
        # shape B: the stored value is computed from the snapshot
        for sub in ast.walk(later.node):
            if isinstance(sub, ast.Assign):
                stores = [
                    t for t in sub.targets
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr in later.writes
                ]
                if stores and _names_in(sub.value) & set(tainted):
                    local = next(iter(_names_in(sub.value) & set(tainted)))
                    return {
                        "attr": tainted[local], "local": local,
                        "lineno": sub.lineno,
                        "how": "the value stored into guarded "
                               "'{}' is computed from it".format(
                                   stores[0].attr),
                    }
        # shape A: the act region sits inside a branch testing the
        # snapshot
        for test in later.tests:
            hit = _names_in(test) & set(tainted)
            if not hit:
                continue
            local = next(iter(hit))
            attr = sorted(later.writes.items(), key=lambda kv: kv[1])[0][0]
            return {
                "attr": tainted[local], "local": local,
                "lineno": later.lineno,
                "how": "the branch guarding the store to '{}' "
                       "tests it".format(attr),
            }
        return None
