"""The shared AST pass every tpulint rule plugs into.

One walk per module produces a :class:`ModuleInfo`: class/function scopes,
the *lexical lock context* of every attribute access and call (which
``with self._lock`` / ``with self._cond`` blocks enclose it), ``#
guarded-by:`` annotations, and ``# tpulint: disable=`` suppressions.
Rules (tpulint.rules_*) consume the finished ModuleInfos — they never
re-walk the AST — so adding a rule costs one function over pre-indexed
facts, not another traversal.

Conventions the pass encodes (see docs/static_analysis.md):

- A ``with self.X:`` / ``with X:`` statement whose context expression is
  a bare name or ``self`` attribute is treated as acquiring lock ``X``
  (locks are objects used as context managers without a call — files,
  ``injected(...)`` and friends are calls and don't count).
- A method whose name ends in ``_locked`` is *called with its class's
  locks held* by project convention; accesses inside it satisfy R1 and
  its body counts as lock context for R2's blocking-call check.
- Lock context is **lexical**: a nested ``def`` (closure/callback) does
  not inherit the enclosing ``with`` — its body runs later, on another
  thread, without the lock.
"""

import ast
import re
import tokenize

GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_]\w*)")
SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\- ]+)")

#: Synthetic lock token for ``*_locked``-suffix methods (convention:
#: caller holds the class's locks).
CONVENTION = "<locked-suffix>"


class AttrAccess:
    """One ``self.X`` load/store with its lexical lock context."""

    __slots__ = ("attr", "lineno", "col", "is_store", "locks", "cls",
                 "func")

    def __init__(self, attr, lineno, col, is_store, locks, cls, func):
        self.attr = attr
        self.lineno = lineno
        self.col = col
        self.is_store = is_store
        self.locks = locks  # frozenset of held lock names ('_cond', ...)
        self.cls = cls      # ClassInfo or None
        self.func = func    # FunctionInfo or None


class CallSite:
    """One call expression with its lexical lock context."""

    __slots__ = ("node", "dotted", "lineno", "locks", "cls", "func")

    def __init__(self, node, dotted, lineno, locks, cls, func):
        self.node = node
        self.dotted = dotted  # best-effort dotted repr ('time.sleep',
        #                       'self._cond.wait', 'thread.join', ...)
        self.lineno = lineno
        self.locks = locks
        self.cls = cls
        self.func = func


class WithLock:
    """One ``with <lock>:`` acquisition and the locks already held."""

    __slots__ = ("lock", "lineno", "held", "cls", "func")

    def __init__(self, lock, lineno, held, cls, func):
        self.lock = lock  # lock name ('_cond', module-level '_lock', ...)
        self.lineno = lineno
        self.held = held  # frozenset held at acquisition time
        self.cls = cls
        self.func = func


class ThreadCreation:
    """One ``threading.Thread(...)`` call."""

    __slots__ = ("node", "lineno", "daemon", "name", "target_attr",
                 "cls", "func")

    def __init__(self, node, lineno, daemon, name, target_attr, cls,
                 func):
        self.node = node
        self.lineno = lineno
        self.daemon = daemon  # True / False / None (absent or dynamic)
        self.name = name  # the name= kwarg when a string literal
        # the self attribute the Thread object lands in (best effort):
        # 'self.X = Thread(...)', 'self.X = [Thread(...) ...]', or
        # 'self.X.append(Thread(...))'
        self.target_attr = target_attr
        self.cls = cls
        self.func = func


class FunctionInfo:
    __slots__ = ("name", "lineno", "node", "cls", "assume_locked")

    def __init__(self, name, lineno, node, cls):
        self.name = name
        self.lineno = lineno
        self.node = node
        self.cls = cls
        self.assume_locked = name.endswith("_locked")


class ClassInfo:
    __slots__ = ("name", "lineno", "node", "module", "bases", "methods",
                 "guarded", "init_code_kw", "lock_aliases")

    def __init__(self, name, lineno, node, module, bases):
        self.name = name
        self.lineno = lineno
        self.node = node
        self.module = module  # ModuleInfo backref
        self.bases = bases    # list of dotted base names
        self.methods = {}     # name -> FunctionInfo
        self.guarded = {}     # attr -> (lock name, declaring lineno)
        # code= kwarg of super().__init__(...) in this class's __init__,
        # when it is a literal (R4's wire-code extraction)
        self.init_code_kw = None
        # 'self._cond = threading.Condition(self._lock)' makes _cond and
        # _lock the SAME lock: holding either satisfies waits/guards on
        # the other
        self.lock_aliases = {}  # attr -> aliased attr


class ModuleInfo:
    """Everything one rule could need about one source file."""

    def __init__(self, path, relpath):
        self.path = path
        self.relpath = relpath
        self.tree = None
        self.source = ""
        self.comments = {}      # lineno -> full comment text
        self.comment_only_lines = set()  # lines holding ONLY a comment
        self.suppressions = {}  # lineno -> set of rule tokens (lowercase)
        self.classes = {}       # name -> ClassInfo
        self.functions = []     # every FunctionInfo (methods included)
        self.attr_accesses = []  # [AttrAccess]
        self.call_sites = []     # [CallSite]
        self.with_locks = []     # [WithLock]
        self.thread_creations = []  # [ThreadCreation]
        self.dict_assignments = {}  # NAME -> dict literal node (top level)
        self.func_dicts = {}     # func name -> first dict literal inside
        # local name -> source-module basename, from `from X import name`
        # (the call graph only cross-module-resolves imported names)
        self.from_imports = {}

    def suppressed(self, lineno, rule_tokens):
        """Whether a finding of a rule (any of its name tokens) is
        suppressed on this line, or on a comment-only line directly
        above (a trailing comment annotates ONLY its own line — it must
        never leak onto the next statement)."""
        for ln in (lineno, lineno - 1):
            if ln != lineno and ln not in self.comment_only_lines:
                continue
            tokens = self.suppressions.get(ln)
            if not tokens:
                continue
            if "all" in tokens:
                return True
            if tokens & rule_tokens:
                return True
        return False


def _dotted(node):
    """Best-effort dotted repr of a call target expression."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif isinstance(node, ast.Call):
        parts.append(_dotted(node.func) + "()")
    else:
        parts.append("<expr>")
    return ".".join(reversed(parts))


def _lock_name(expr):
    """The lock name of a with-item context expression, or None.

    ``with self._lock:`` -> '_lock'; ``with _lock:`` (module-level) ->
    '_lock'.  Calls (``with injected(...):``), subscripts, and chained
    attributes are not lock acquisitions.
    """
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _collect_comments(source, info):
    lines = source.splitlines()
    try:
        tokens = tokenize.generate_tokens(iter(source.splitlines(True)).__next__)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                lineno = tok.start[0]
                info.comments[lineno] = tok.string
                if lineno <= len(lines) and \
                        lines[lineno - 1].lstrip().startswith("#"):
                    info.comment_only_lines.add(lineno)
    except (tokenize.TokenError, IndentationError):
        pass
    for lineno, text in info.comments.items():
        m = SUPPRESS_RE.search(text)
        if m:
            info.suppressions[lineno] = {
                t.strip().lower() for t in m.group(1).split(",") if t.strip()
            }


class _Walker(ast.NodeVisitor):
    def __init__(self, info):
        self.info = info
        self.cls = None        # innermost ClassInfo
        self.func = None       # innermost FunctionInfo
        self.locks = []        # held lock-name stack (lexical)

    # -- scopes ------------------------------------------------------------

    def visit_ClassDef(self, node):
        bases = [_dotted(b) for b in node.bases]
        cls = ClassInfo(node.name, node.lineno, node, self.info, bases)
        # nested classes register flat by name; duplicates keep the first
        self.info.classes.setdefault(node.name, cls)
        prev_cls, prev_func, prev_locks = self.cls, self.func, self.locks
        self.cls, self.func, self.locks = cls, None, []
        self.generic_visit(node)
        self.cls, self.func, self.locks = prev_cls, prev_func, prev_locks

    def _visit_function(self, node):
        fn = FunctionInfo(node.name, node.lineno, node, self.cls)
        self.info.functions.append(fn)
        if self.cls is not None and self.func is None:
            self.cls.methods.setdefault(node.name, fn)
        prev_func, prev_locks = self.func, self.locks
        # lexical lock context does NOT cross a def boundary: the body
        # runs later, possibly on another thread, without the lock
        self.func, self.locks = fn, []
        self.generic_visit(node)
        self.func, self.locks = prev_func, prev_locks

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    # -- lock context ------------------------------------------------------

    def _held(self):
        held = set(self.locks)
        if self.func is not None and self.func.assume_locked:
            held.add(CONVENTION)
        return frozenset(held)

    def visit_With(self, node):
        # items acquire SEQUENTIALLY: in `with self._a, self._b:` the
        # second item's acquisition (and its context expression) runs
        # with the first already held — so each item is recorded, and
        # visited, under the locks of the items before it, building the
        # a->b order edge a flattened treatment would miss
        acquired = 0
        for item in node.items:
            name = _lock_name(item.context_expr)
            # the context expression evaluates BEFORE its own lock is
            # taken, but under every earlier item's lock
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            if name is not None:
                self.info.with_locks.append(WithLock(
                    name, item.context_expr.lineno, self._held(),
                    self.cls, self.func,
                ))
                self.locks.append(name)
                acquired += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(acquired):
            self.locks.pop()

    visit_AsyncWith = visit_With

    # -- facts -------------------------------------------------------------

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            self.info.attr_accesses.append(AttrAccess(
                node.attr, node.lineno, node.col_offset,
                isinstance(node.ctx, (ast.Store, ast.Del)),
                self._held(), self.cls, self.func,
            ))
        self.generic_visit(node)

    def visit_Call(self, node):
        dotted = _dotted(node.func)
        self.info.call_sites.append(CallSite(
            node, dotted, node.lineno, self._held(), self.cls, self.func,
        ))
        if dotted in ("threading.Thread", "Thread", "_threading.Thread"):
            daemon = None
            name = None
            for kw in node.keywords:
                if kw.arg == "daemon":
                    daemon = (kw.value.value
                              if isinstance(kw.value, ast.Constant)
                              else None)
                if (kw.arg == "name"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)):
                    name = kw.value.value
            self.info.thread_creations.append(ThreadCreation(
                node, node.lineno, daemon, name, None, self.cls,
                self.func,
            ))
        # R4: super().__init__(msg, code=N) inside an __init__
        if (dotted.endswith("super().__init__")
                and self.cls is not None
                and self.func is not None
                and self.func.name == "__init__"):
            for kw in node.keywords:
                if kw.arg == "code" and isinstance(kw.value, ast.Constant):
                    self.cls.init_code_kw = kw.value.value
        self.generic_visit(node)
        # R5: `self.X.append(threading.Thread(...))` stores the thread
        # in self.X just like `self.X = Thread(...)` — attribute it so
        # a close() that joins the collection counts (generic_visit
        # above already recorded the ThreadCreation nodes inside args)
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "add")
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"):
            for tc in self.info.thread_creations:
                if tc.target_attr is None and any(
                        _contains(arg, tc.node) for arg in node.args):
                    tc.target_attr = node.func.value.attr

    def visit_ImportFrom(self, node):
        if node.module:
            base = node.module.rsplit(".", 1)[-1]
            for alias in node.names:
                self.info.from_imports[alias.asname or alias.name] = base
        self.generic_visit(node)

    def visit_Assign(self, node):
        # guarded-by annotations: trailing comment on the assignment's
        # first line, or an annotation comment on its own line above
        for target in node.targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                    and self.cls is not None):
                for ln in (node.lineno, node.lineno - 1):
                    if ln != node.lineno and \
                            ln not in self.info.comment_only_lines:
                        continue  # a trailing comment annotates only
                        #           its OWN line's assignment
                    comment = self.info.comments.get(ln)
                    if comment:
                        m = GUARDED_BY_RE.search(comment)
                        if m:
                            self.cls.guarded.setdefault(
                                target.attr, (m.group(1), node.lineno))
                            break
                # Condition-over-explicit-lock aliasing
                if (isinstance(node.value, ast.Call)
                        and _dotted(node.value.func).endswith("Condition")
                        and node.value.args
                        and isinstance(node.value.args[0], ast.Attribute)
                        and isinstance(node.value.args[0].value, ast.Name)
                        and node.value.args[0].value.id == "self"):
                    self.cls.lock_aliases[target.attr] = (
                        node.value.args[0].attr)
        # top-level dict literals by name (R6's POINTS registry, R4's
        # _STATUS_LINE map)
        if (self.cls is None and self.func is None
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Dict)):
            self.info.dict_assignments[node.targets[0].id] = node.value
        self.generic_visit(node)
        # late: Thread creations inside node.value were recorded by the
        # generic visit above; attribute them now
        for target in node.targets:
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                for tc in self.info.thread_creations:
                    if tc.target_attr is None and _contains(node.value,
                                                            tc.node):
                        tc.target_attr = target.attr


def _contains(root, needle):
    for sub in ast.walk(root):
        if sub is needle:
            return True
    return False


def _index_func_dicts(info):
    """First dict literal returned/used inside each module-level
    function (R4 reads the gRPC ``_status_code`` mapping this way)."""
    for fn in info.functions:
        if fn.cls is not None:
            continue
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Dict):
                info.func_dicts.setdefault(fn.name, sub)
                break


def analyze_source(source, path, relpath):
    """Parse one file into a ModuleInfo (raises SyntaxError upward)."""
    info = ModuleInfo(path, relpath)
    info.source = source
    _collect_comments(source, info)
    info.tree = ast.parse(source, filename=path)
    _Walker(info).visit(info.tree)
    _index_func_dicts(info)
    return info


def analyze_file(path, relpath):
    with open(path, "r", encoding="utf-8") as fh:
        return analyze_source(fh.read(), path, relpath)


def resolve_hierarchy(modules, root_name):
    """Map class name -> ClassInfo for every class whose base chain
    (resolved by name across ``modules``) reaches ``root_name``.

    The root class itself is excluded.  Name resolution is flat — this
    codebase keeps exception hierarchies unique by class name, which is
    exactly what rule R4's twin-definition check enforces.
    """
    by_name = {}
    for mod in modules:
        for cls in mod.classes.values():
            by_name.setdefault(cls.name, cls)
    result = {}

    def reaches_root(name, seen):
        if name == root_name:
            return True
        cls = by_name.get(name)
        if cls is None or name in seen:
            return False
        seen.add(name)
        return any(
            reaches_root(base.rsplit(".", 1)[-1], seen)
            for base in cls.bases
        )

    for mod in modules:
        for cls in mod.classes.values():
            if cls.name == root_name:
                continue
            if any(reaches_root(b.rsplit(".", 1)[-1], {cls.name})
                   for b in cls.bases):
                result.setdefault(cls.name, []).append(cls)
    return result


def resolve_wire_code(cls, hierarchy_modules):
    """The HTTP code a ServerError subclass carries: its own literal
    ``code=`` kwarg, or the nearest ancestor's.  None when dynamic."""
    by_name = {}
    for mod in hierarchy_modules:
        for c in mod.classes.values():
            by_name.setdefault(c.name, c)
    seen = set()
    cur = cls
    while cur is not None and cur.name not in seen:
        seen.add(cur.name)
        if cur.init_code_kw is not None:
            return cur.init_code_kw
        nxt = None
        for base in cur.bases:
            cand = by_name.get(base.rsplit(".", 1)[-1])
            if cand is not None:
                nxt = cand
                break
        cur = nxt
    return None
