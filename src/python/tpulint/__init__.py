"""tpulint — project-specific concurrency & protocol-invariant static
analysis for the tpuserver/tritonclient/perfanalyzer stack.

The serving stack's correctness rests on conventions a type checker
cannot see: which fields a lock guards, that nothing blocks while
holding one, that every deadline is monotonic-clock math, that every
typed error is mapped on both wire protocols and documented, that every
thread dies with its owner, and that every fault-injection point is
registered.  tpulint turns those conventions into a tier-1 gate: one
shared AST pass (tpulint.analysis) plus one whole-program call graph
(tpulint.callgraph) feed eight rules, findings are suppressible inline
(``# tpulint: disable=R1``) or via a checked-in baseline, and
``tools/tpulint.py`` is the CLI front door.

Rule catalog (details + examples: docs/static_analysis.md):

====  ======================  ============================================
R1    guarded-by              annotated fields only touched under their
                              lock (``# guarded-by: _lock``)
R2    no-blocking-under-lock  no sleep/join/socket/Future.result inside a
                              held-lock block, at ANY call depth via the
                              project call graph; interprocedural
                              lock-order graph acyclic
R3    monotonic-clock         no wall-clock reads; deadline math is
                              time.monotonic() only
R4    wire-map                every ServerError subclass mapped in HTTP +
                              gRPC maps + docs table; one definition each
R5    thread-lifecycle        every Thread daemon=True or joined on a
                              close()/stop()/drain() path
R6    fault-registry          every faults.fire() site registered in
                              faults.POINTS, exactly one site per point
R7    atomicity               no check-then-act split across a lock
                              release on guarded state
R8    protocol-parity         router re-serves the replica's surface:
                              routes, verbs, status lines, SSE/resume
                              grammar, HTTP<->gRPC code maps
====  ======================  ============================================
"""

from tpulint.findings import Finding
from tpulint.runner import (
    ALL_RULES,
    CACHE_STATS,
    RULES_BY_ID,
    LintResult,
    clear_module_cache,
    lint_paths,
    select_rules,
)

__all__ = [
    "ALL_RULES", "CACHE_STATS", "Finding", "LintResult", "RULES_BY_ID",
    "clear_module_cache", "lint_paths", "select_rules",
]
