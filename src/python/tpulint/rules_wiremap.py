"""R4 typed-error wire-map completeness.

A typed error is only useful if every surface agrees on it.  For every
class in the ``ServerError`` hierarchy (resolved by name across the
analyzed modules) the rule requires its HTTP code to appear in:

- the HTTP frontend's ``_STATUS_LINE`` map (else the wire falls back to
  a blanket 500 status line),
- the gRPC frontend's ``_status_code`` mapping dict (else the RPC
  surfaces as UNKNOWN),
- the status table in ``docs/resilience.md`` (else the contract is
  undocumented).

It also enforces the **one-definition rule** that replaced the old
scheduler/core twin exceptions: a class name that appears in the
ServerError hierarchy may be *defined* in exactly one analyzed module —
other modules import/alias it (``tpuserver.errors`` is the canonical
home).  Two same-named classes kept consistent only by convention is
precisely the drift this rule exists to stop.
"""

import ast
import re

from tpulint.analysis import resolve_hierarchy, resolve_wire_code
from tpulint.findings import Finding

ROOT = "ServerError"
HTTP_MAP_NAME = "_STATUS_LINE"
GRPC_MAP_FUNC = "_status_code"


def _dict_int_keys(dict_node):
    keys = set()
    for k in dict_node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, int):
            keys.add(k.value)
    return keys


def _docs_codes(docs_path):
    """HTTP codes present in the resilience doc's status table rows."""
    codes = set()
    try:
        with open(docs_path, "r", encoding="utf-8") as fh:
            for line in fh:
                stripped = line.strip()
                if stripped.startswith("|"):
                    for m in re.finditer(r"\b([1-5]\d\d)\b", stripped):
                        codes.add(int(m.group(1)))
    except FileNotFoundError:
        return None
    return codes


class WireMapRule:
    id = "R4"
    name = "wire-map"

    def check(self, modules, config):
        findings = []
        hierarchy = resolve_hierarchy(modules, ROOT)
        if not hierarchy:
            return findings

        http_codes = None
        grpc_codes = None
        for mod in modules:
            if HTTP_MAP_NAME in mod.dict_assignments:
                http_codes = _dict_int_keys(
                    mod.dict_assignments[HTTP_MAP_NAME])
            if GRPC_MAP_FUNC in mod.func_dicts:
                grpc_codes = _dict_int_keys(mod.func_dicts[GRPC_MAP_FUNC])
        docs_codes = (
            _docs_codes(config.docs_path)
            if config.docs_path is not None else None
        )

        # a hierarchy with no discoverable wire map must FAIL, not
        # silently degrade: renaming _STATUS_LINE (or moving the gRPC
        # dict out of _status_code) would otherwise disable this rule
        # with no signal.  Anchor at the hierarchy root's definition.
        # An explicitly absent docs path (--docs '') is a deliberate
        # opt-out and stays quiet; a CONFIGURED docs path that cannot
        # be read is a finding.
        anchor = None
        for mod in modules:
            if ROOT in mod.classes:
                anchor = mod.classes[ROOT]
                break
        if anchor is not None:
            for label, codeset in (
                ("HTTP status map '{}'".format(HTTP_MAP_NAME),
                 http_codes),
                ("gRPC code map '{}()'".format(GRPC_MAP_FUNC),
                 grpc_codes),
            ):
                if codeset is None:
                    findings.append(Finding(
                        self.id, self.name, anchor.module.relpath,
                        anchor.lineno,
                        "a {} hierarchy is defined but no {} exists in "
                        "the analyzed set — wire-map completeness "
                        "cannot be checked (renamed/moved map, or a "
                        "partial lint run)".format(ROOT, label),
                    ))
            if config.docs_path is not None and docs_codes is None:
                findings.append(Finding(
                    self.id, self.name, anchor.module.relpath,
                    anchor.lineno,
                    "configured docs status table '{}' cannot be read "
                    "— wire-map completeness against the docs cannot "
                    "be checked".format(config.docs_path),
                ))

        for name, defs in sorted(hierarchy.items()):
            # one-definition rule (incl. same-named non-ServerError
            # classes anywhere else in the tree)
            all_defs = list(defs)
            for mod in modules:
                cls = mod.classes.get(name)
                if cls is not None and cls not in all_defs:
                    all_defs.append(cls)
            if len(all_defs) > 1:
                canonical = defs[0]
                for extra in all_defs:
                    if extra is canonical:
                        continue
                    findings.append(Finding(
                        self.id, self.name, extra.module.relpath,
                        extra.lineno,
                        "duplicate definition of wire-mapped error "
                        "'{}' (canonical definition lives in {}); alias "
                        "or import it instead — twin classes stay "
                        "consistent only by convention".format(
                            name, canonical.module.relpath),
                    ))

            cls = defs[0]
            code = resolve_wire_code(cls, modules)
            if code is None:
                findings.append(Finding(
                    self.id, self.name, cls.module.relpath, cls.lineno,
                    "cannot statically resolve the HTTP code of "
                    "ServerError subclass '{}' (pass code=<literal> to "
                    "super().__init__)".format(name),
                ))
                continue
            for label, codeset in (
                ("HTTP status map ({})".format(HTTP_MAP_NAME), http_codes),
                ("gRPC code map ({}())".format(GRPC_MAP_FUNC), grpc_codes),
                ("status table in docs", docs_codes),
            ):
                if codeset is None:
                    continue  # surface not in the analyzed set
                if code not in codeset:
                    findings.append(Finding(
                        self.id, self.name, cls.module.relpath,
                        cls.lineno,
                        "ServerError subclass '{}' carries HTTP code {} "
                        "which is missing from the {}".format(
                            name, code, label),
                    ))
        return findings
