"""R8 cross-surface protocol parity.

The fleet router (``tpuserver/router.py``) deliberately re-serves the
replica frontend's surface (``tpuserver/http_frontend.py``): same
routes, same SSE resume grammar, same status lines — that identity is
what lets a plain client point at either tier unchanged, and what the
shared ``_http_base`` handler now carries structurally.  The parts
that *cannot* be shared (the router's route table, the literals it
keys relaying on) can still drift silently; this rule extracts both
surfaces statically and fails on divergence:

- **Health-route parity** — every ``/v2/health/*`` route the replica
  serves must be served by the router itself (routers stack: a router
  is probed exactly like a replica), and the router must re-serve the
  ``generate_stream`` streaming surface.
- **Verb parity** — the router dispatches every HTTP verb the
  replica's route table keys on.
- **Status-line parity** — when the two surfaces carry separate
  status-line maps, the router's must contain every code the replica
  can emit (a missing code relays as a blanket 500).  With the shared
  ``_http_base`` map this is structural; the check guards a future
  re-fork.
- **HTTP/gRPC code parity** — every code in the gRPC frontend's
  ``_status_code()`` map must have an HTTP status line, and every
  HTTP status-line code must be gRPC-mapped unless it is framing-only
  (``200``/``405``/``502`` — success, method-not-allowed raised below
  the typed-error layer, and the router's own bad-gateway answer,
  none of which exist on a gRPC stream).
- **SSE grammar parity** — the replica and the router must build
  ``id:`` lines from the same ``gen/seq`` format and emit the
  byte-identical terminal ``{"final": true}`` event; a resuming
  client's ``Last-Event-ID`` must parse the same against either tier.
- **Resume-grammar parity** — every resume/stream parameter key the
  replica surface uses (``generation_id``, ``seq``,
  ``resume_generation_id``, ``resume_from_seq``, ``Last-Event-ID``)
  must be used by the router too, and the generation-parameter keys a
  producer publishes under ``core.RESPONSE_PARAMS_KEY`` must be among
  the keys both tiers read.
- **Admin-surface coverage** — the router's own declared admin routes
  (``ROUTER_ADMIN_ROUTES``: ``/router/stats``, ``/router/replicas``,
  ``/router/partition`` — the horizontal tier's map/epoch surface
  every active must serve)
  must all be served, and the membership route must reference both
  ``add`` and ``remove`` verbs: the fleet supervisor and ops tooling
  drive elastic scaling and planned replacement through exactly this
  surface, so a dropped route or verb silently strands them.

Surfaces are identified by module basename (``http_frontend.py`` /
``router.py`` / ``grpc_frontend.py``) *and* shape: the HTTP surfaces
must define a class with a ``_route`` method (so ``tools/router.py``,
the CLI, is not a surface), the gRPC surface a ``_status_code``
mapping.  When a surface is absent from the analyzed set the
comparisons that need it are skipped — partial runs stay quiet, the
full gate checks everything.
"""

import ast

from tpulint.findings import Finding

HTTP_BASENAME = "http_frontend.py"
ROUTER_BASENAME = "router.py"
GRPC_BASENAME = "grpc_frontend.py"
STATUS_MAP_NAME = "_STATUS_LINE"
GRPC_MAP_FUNC = "_status_code"

#: HTTP codes with no gRPC twin by design: 200 (success is not an
#: error mapping), 405 (raised by the framing layer below typed
#: errors), 502 (the router's own mid-request-loss answer; gRPC
#: streams surface that in-band).
FRAMING_ONLY_CODES = frozenset({200, 405, 502})

#: The resume grammar the replica and router must agree on.
RESUME_KEYS = ("generation_id", "seq", "resume_generation_id",
               "resume_from_seq")
RESUME_HEADER = "last-event-id"

HEALTH_PREFIX = "/v2/health/"
STREAM_ROUTE_TOKEN = "generate_stream"
#: The shared-memory mutation verbs of the data plane.  When the
#: replica serves the shm register/unregister routes, the router's
#: route set must reference the same tokens: these verbs BROADCAST to
#: every replica (a region registered on one replica only would desync
#: the fleet the moment a failover or handoff lands a shm-referencing
#: request elsewhere), so a router that stops naming them silently
#: strands the zero-copy data plane.
SHM_ROUTE_TOKENS = ("sharedmemory", "register", "unregister")
#: The telemetry scrape surface: served by BOTH HTTP tiers (the
#: replica's own exposition; the router re-serves it fleet-aggregated)
#: so observability tooling points at either address unchanged.
METRICS_ROUTE = "/metrics"

#: The router's declared admin surface.  Every route here must be
#: served by the real router module; ``/router/replicas`` must also
#: reference both membership actions — the fleet supervisor
#: (``tpuserver.fleet``) and ops tooling key on exactly this contract.
ROUTER_ADMIN_ROUTES = ("/router/stats", "/router/replicas",
                       "/router/partition")
MEMBERSHIP_ROUTE = "/router/replicas"
MEMBERSHIP_ACTIONS = ("add", "remove")


def _has_route_method(mod):
    return any("_route" in cls.methods for cls in mod.classes.values())


def _str_constants(mod):
    """Every string constant in the module (the literal surface)."""
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
    return out


def _bytes_constants(mod):
    out = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
            out.add(node.value)
    return out


def _routes(mod):
    """Path literals the surface serves locally (``/v2...``,
    ``/metrics``, ``/router/...``), regex route patterns (``^/v2...``),
    and simple path suffixes the dispatcher endswith-matches
    (``/generate_stream``)."""
    lits = _str_constants(mod)
    return {s for s in lits
            if s.startswith("/v2") or s == "/metrics"
            or s.startswith("/router") or s.startswith("^/v2")
            or (s.startswith("/") and s[1:].replace("_", "").isalnum())}


def _verbs(mod):
    """HTTP verb literals the module's route code compares against."""
    verbs = set()
    known = {"GET", "POST", "PUT", "DELETE", "HEAD", "PATCH", "OPTIONS"}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Compare):
            parts = [node.left] + list(node.comparators)
            names = set()
            consts = set()
            for part in parts:
                for sub in ast.walk(part):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
                    elif isinstance(sub, ast.Constant) and \
                            isinstance(sub.value, str):
                        consts.add(sub.value)
            if "method" in names:
                verbs |= consts & known
    return verbs


def _status_map_keys(mod):
    node = mod.dict_assignments.get(STATUS_MAP_NAME)
    if node is None:
        return None
    return {k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, int)}


def _sse_id_formats(mod):
    """Format-string literals that build SSE ``id:`` lines."""
    return {s for s in _str_constants(mod) if s.startswith("id: ")}


def _final_markers(mod):
    """The terminal-event byte literals (``{"final": true}``)."""
    return {b for b in _bytes_constants(mod) if b'"final"' in b}


def _response_params_keys(modules):
    """Keys of every dict literal published under the
    ``RESPONSE_PARAMS_KEY`` name (the generation producers' parameter
    grammar, e.g. ``{"generation_id": ..., "seq": ...}``)."""
    keys = set()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if (isinstance(k, ast.Name)
                        and k.id == "RESPONSE_PARAMS_KEY"
                        and isinstance(v, ast.Dict)):
                    for vk in v.keys:
                        if isinstance(vk, ast.Constant) and \
                                isinstance(vk.value, str):
                            keys.add(vk.value)
    return keys


class ProtocolParityRule:
    id = "R8"
    name = "protocol-parity"

    def check(self, modules, config):
        http_mod = router_mod = grpc_mod = None
        for mod in modules:
            base = mod.relpath.rsplit("/", 1)[-1]
            if base == HTTP_BASENAME and _has_route_method(mod):
                http_mod = http_mod or mod
            elif base == ROUTER_BASENAME and _has_route_method(mod):
                router_mod = router_mod or mod
            elif base == GRPC_BASENAME and GRPC_MAP_FUNC in mod.func_dicts:
                grpc_mod = grpc_mod or mod

        findings = []
        if router_mod is not None:
            findings.extend(self._check_admin_surface(router_mod))
        if http_mod is not None and router_mod is not None:
            findings.extend(self._check_router_parity(http_mod, router_mod))
            findings.extend(self._check_resume_grammar(
                modules, http_mod, router_mod))
        if http_mod is not None and grpc_mod is not None:
            findings.extend(self._check_code_parity(
                modules, http_mod, grpc_mod))
        return findings

    # -- the router's own admin surface ------------------------------------

    def _check_admin_surface(self, router_mod):
        findings = []
        anchor = self._route_anchor(router_mod)
        routes = _routes(router_mod)
        lits = _str_constants(router_mod)
        for route in ROUTER_ADMIN_ROUTES:
            if route not in routes:
                findings.append(Finding(
                    self.id, self.name, router_mod.relpath, anchor,
                    "router does not serve its declared admin route "
                    "'{}' — the fleet supervisor and ops tooling key "
                    "on the admin surface".format(route),
                ))
        if MEMBERSHIP_ROUTE in routes:
            for action in MEMBERSHIP_ACTIONS:
                if action not in lits:
                    findings.append(Finding(
                        self.id, self.name, router_mod.relpath, anchor,
                        "router serves '{}' but never references "
                        "membership action '{}' — add/remove are the "
                        "route's contract (elastic scaling and planned "
                        "replacement drive it)".format(
                            MEMBERSHIP_ROUTE, action),
                    ))
        return findings

    # -- router vs replica frontend ----------------------------------------

    def _check_router_parity(self, http_mod, router_mod):
        findings = []
        anchor = self._route_anchor(router_mod)

        http_routes = _routes(http_mod)
        router_routes = _routes(router_mod)
        for route in sorted(http_routes):
            if route.startswith(HEALTH_PREFIX) and \
                    route not in router_routes:
                findings.append(Finding(
                    self.id, self.name, router_mod.relpath, anchor,
                    "router does not serve replica health route "
                    "'{}' — routers must stack (a router is probed "
                    "exactly like a replica)".format(route),
                ))
        if any(STREAM_ROUTE_TOKEN in r for r in http_routes) and not any(
                STREAM_ROUTE_TOKEN in r for r in router_routes):
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router does not re-serve the replica's "
                "generate_stream streaming surface (no route literal "
                "or pattern mentions '{}')".format(STREAM_ROUTE_TOKEN),
            ))
        if all(any(tok in r for r in http_routes)
               for tok in SHM_ROUTE_TOKENS):
            missing_tokens = [
                tok for tok in SHM_ROUTE_TOKENS
                if not any(tok in r for r in router_routes)
            ]
            if missing_tokens:
                findings.append(Finding(
                    self.id, self.name, router_mod.relpath, anchor,
                    "router route set never references shm verb "
                    "token(s) {} the replica serves — shm "
                    "register/unregister must broadcast to every "
                    "replica or the zero-copy data plane desyncs on "
                    "failover".format("/".join(missing_tokens)),
                ))
        if METRICS_ROUTE in http_routes and \
                METRICS_ROUTE not in router_routes:
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router does not serve the replica's '{}' telemetry "
                "route — both HTTP surfaces must expose the scrape "
                "surface (the router re-serves it "
                "fleet-aggregated)".format(METRICS_ROUTE),
            ))

        missing_verbs = _verbs(http_mod) - _verbs(router_mod)
        if missing_verbs:
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router route table never dispatches on verb(s) {} "
                "that the replica frontend keys on".format(
                    "/".join(sorted(missing_verbs))),
            ))

        http_codes = _status_map_keys(http_mod)
        router_codes = _status_map_keys(router_mod)
        if http_codes is not None and router_codes is not None:
            missing = http_codes - router_codes
            if missing:
                findings.append(Finding(
                    self.id, self.name, router_mod.relpath, anchor,
                    "router status-line map is missing code(s) {} the "
                    "replica frontend can emit — they would relay as a "
                    "blanket 500".format(
                        ", ".join(str(c) for c in sorted(missing))),
                ))

        http_ids = _sse_id_formats(http_mod)
        router_ids = _sse_id_formats(router_mod)
        if http_ids and router_ids and not (http_ids & router_ids):
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router SSE id-line format(s) {} share nothing with "
                "the replica's {} — a client's Last-Event-ID would "
                "parse differently per tier".format(
                    sorted(router_ids), sorted(http_ids)),
            ))
        http_final = _final_markers(http_mod)
        router_final = _final_markers(router_mod)
        if http_final and not router_final:
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router never emits the replica's terminal SSE event "
                "{} — clients key stream completion on the exact "
                "marker".format(sorted(http_final)),
            ))
        elif http_final and router_final and not (http_final & router_final):
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router terminal SSE event {} differs from the "
                "replica's {} — clients key stream completion on the "
                "exact marker".format(
                    sorted(router_final), sorted(http_final)),
            ))
        return findings

    def _check_resume_grammar(self, modules, http_mod, router_mod):
        findings = []
        anchor = self._route_anchor(router_mod)
        http_lits = _str_constants(http_mod)
        router_lits = _str_constants(router_mod)
        router_lits_lower = {s.lower() for s in router_lits}
        for key in RESUME_KEYS:
            if key in http_lits and key not in router_lits:
                findings.append(Finding(
                    self.id, self.name, router_mod.relpath, anchor,
                    "router never references resume-grammar key '{}' "
                    "that the replica frontend keys on — sticky resume "
                    "would silently drift".format(key),
                ))
        http_has_header = any(
            s.lower() == RESUME_HEADER for s in http_lits)
        if http_has_header and RESUME_HEADER not in router_lits_lower:
            findings.append(Finding(
                self.id, self.name, router_mod.relpath, anchor,
                "router never reads the replica's resume header "
                "'Last-Event-ID'",
            ))
        produced = _response_params_keys(modules)
        for surface, lits in (("replica frontend", http_lits),
                              ("router", router_lits)):
            missing = {k for k in produced if k not in lits}
            if missing:
                mod = http_mod if surface == "replica frontend" \
                    else router_mod
                findings.append(Finding(
                    self.id, self.name, mod.relpath,
                    self._route_anchor(mod),
                    "{} never references generation parameter key(s) "
                    "{} that a producer publishes under "
                    "RESPONSE_PARAMS_KEY".format(
                        surface, ", ".join(sorted(missing))),
                ))
        return findings

    # -- http vs grpc typed-code maps --------------------------------------

    def _check_code_parity(self, modules, http_mod, grpc_mod):
        findings = []
        http_codes = _status_map_keys(http_mod)
        if http_codes is None:
            # shared framing module: find the one _STATUS_LINE in the set
            for mod in modules:
                http_codes = _status_map_keys(mod)
                if http_codes is not None:
                    break
        if http_codes is None:
            return findings  # R4 already reports the missing map
        grpc_dict = grpc_mod.func_dicts[GRPC_MAP_FUNC]
        grpc_codes = {k.value for k in grpc_dict.keys
                      if isinstance(k, ast.Constant)
                      and isinstance(k.value, int)}
        anchor = grpc_dict.lineno
        unrenderable = grpc_codes - http_codes
        if unrenderable:
            findings.append(Finding(
                self.id, self.name, grpc_mod.relpath, anchor,
                "gRPC code map translates HTTP code(s) {} that have no "
                "HTTP status line — the same typed error would render "
                "as a blanket 500 on the HTTP surface".format(
                    ", ".join(str(c) for c in sorted(unrenderable))),
            ))
        unmapped = http_codes - grpc_codes - FRAMING_ONLY_CODES
        if unmapped:
            findings.append(Finding(
                self.id, self.name, grpc_mod.relpath, anchor,
                "HTTP status-line code(s) {} have no gRPC mapping in "
                "{}() and are not framing-only — the same typed error "
                "would surface as UNKNOWN on gRPC".format(
                    ", ".join(str(c) for c in sorted(unmapped)),
                    GRPC_MAP_FUNC),
            ))
        return findings

    @staticmethod
    def _route_anchor(mod):
        """Anchor surface-level findings at the handler's _route."""
        for cls in mod.classes.values():
            fn = cls.methods.get("_route")
            if fn is not None:
                return fn.lineno
        return 1
