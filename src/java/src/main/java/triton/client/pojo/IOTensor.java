// JSON-shaped tensor descriptor of the KServe v2 protocol (role of
// reference src/java/.../pojo/IOTensor.java: the wire form of an
// input/output tensor in request and response bodies).
package triton.client.pojo;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

/**
 * One {@code {"name", "datatype", "shape", "parameters", "data"}} tensor
 * object as it appears in v2 JSON bodies. {@code data} is the row-major
 * flattened value list and is absent when the tensor rides the binary
 * extension or shared memory.
 */
public class IOTensor {
  private String name;
  private String datatype;
  private long[] shape;
  private Parameters parameters = new Parameters();
  private List<Object> data;

  public IOTensor() {}

  public IOTensor(String name, String datatype, long[] shape) {
    this.name = name;
    this.datatype = datatype;
    this.shape = shape == null ? null : shape.clone();
  }

  public String getName() {
    return name;
  }

  public void setName(String name) {
    this.name = name;
  }

  public String getDatatype() {
    return datatype;
  }

  public void setDatatype(String datatype) {
    this.datatype = datatype;
  }

  public long[] getShape() {
    return shape == null ? null : shape.clone();
  }

  public void setShape(long[] shape) {
    this.shape = shape == null ? null : shape.clone();
  }

  public Parameters getParameters() {
    return parameters;
  }

  public List<Object> getData() {
    return data;
  }

  public void setData(List<Object> data) {
    this.data = data;
  }

  /** Element count implied by the shape (1 for rank 0). */
  public long elementCount() {
    long n = 1;
    if (shape != null) {
      for (long d : shape) {
        n *= d;
      }
    }
    return n;
  }

  /** Wire-form map for JSON serialization. */
  public Map<String, Object> toMap() {
    Map<String, Object> out = new LinkedHashMap<>();
    out.put("name", name);
    if (datatype != null) {
      out.put("datatype", datatype);
    }
    if (shape != null) {
      List<Object> dims = new ArrayList<>(shape.length);
      for (long d : shape) {
        dims.add(d);
      }
      out.put("shape", dims);
    }
    if (!parameters.isEmpty()) {
      out.put("parameters", parameters.toMap());
    }
    if (data != null) {
      out.put("data", data);
    }
    return out;
  }

  /** Parse one tensor object out of a decoded JSON map. */
  @SuppressWarnings("unchecked")
  public static IOTensor fromMap(Map<String, Object> map) {
    IOTensor t = new IOTensor();
    t.name = (String) map.get("name");
    t.datatype = (String) map.get("datatype");
    Object dims = map.get("shape");
    if (dims instanceof List) {
      List<Object> list = (List<Object>) dims;
      t.shape = new long[list.size()];
      for (int i = 0; i < list.size(); i++) {
        t.shape[i] = ((Number) list.get(i)).longValue();
      }
    }
    Object params = map.get("parameters");
    if (params instanceof Map) {
      t.parameters = new Parameters((Map<String, Object>) params);
    }
    Object values = map.get("data");
    if (values instanceof List) {
      t.data = (List<Object>) values;
    }
    return t;
  }
}
