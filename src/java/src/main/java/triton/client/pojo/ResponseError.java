// The v2 protocol's error body ({"error": "..."}) (role of reference
// src/java/.../pojo/ResponseError.java).
package triton.client.pojo;

import java.util.Map;

/** Parsed {@code {"error": "..."}} payload of a non-2xx response. */
public class ResponseError {
  private final String error;

  public ResponseError(String error) {
    this.error = error;
  }

  public String getError() {
    return error;
  }

  public static ResponseError fromMap(Map<String, Object> map) {
    Object msg = map == null ? null : map.get("error");
    return new ResponseError(msg == null ? "unknown error" : msg.toString());
  }

  @Override
  public String toString() {
    return "ResponseError{" + error + "}";
  }
}
