// Parsed v2 inference-response body (role of reference
// src/java/.../pojo/InferenceResponse.java).
package triton.client.pojo;

import java.util.ArrayList;
import java.util.List;
import java.util.Map;

/**
 * The JSON header of a ModelInfer response: model identity, request id,
 * response parameters, and output tensor descriptors. Binary-extension
 * payload bytes live outside this object (see
 * {@link triton.client.BinaryProtocol}).
 */
public class InferenceResponse {
  private String modelName;
  private String modelVersion;
  private String id;
  private Parameters parameters = new Parameters();
  private List<IOTensor> outputs = new ArrayList<>();

  public String getModelName() {
    return modelName;
  }

  public String getModelVersion() {
    return modelVersion;
  }

  public String getId() {
    return id;
  }

  public Parameters getParameters() {
    return parameters;
  }

  public List<IOTensor> getOutputs() {
    return outputs;
  }

  public IOTensor getOutput(String name) {
    for (IOTensor t : outputs) {
      if (t.getName().equals(name)) {
        return t;
      }
    }
    return null;
  }

  @SuppressWarnings("unchecked")
  public static InferenceResponse fromMap(Map<String, Object> map) {
    InferenceResponse r = new InferenceResponse();
    r.modelName = (String) map.get("model_name");
    r.modelVersion = (String) map.get("model_version");
    r.id = (String) map.get("id");
    Object params = map.get("parameters");
    if (params instanceof Map) {
      r.parameters = new Parameters((Map<String, Object>) params);
    }
    Object outs = map.get("outputs");
    if (outs instanceof List) {
      for (Object o : (List<Object>) outs) {
        if (o instanceof Map) {
          r.outputs.add(IOTensor.fromMap((Map<String, Object>) o));
        }
      }
    }
    return r;
  }
}
