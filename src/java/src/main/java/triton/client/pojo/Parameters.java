// Typed view over the v2 protocol's free-form "parameters" objects
// (role of reference src/java/.../pojo/Parameters.java).
package triton.client.pojo;

import java.util.LinkedHashMap;
import java.util.Map;

/**
 * Request/response/tensor parameter map with convenience getters for the
 * JSON scalar types the protocol allows (bool, int64, double, string).
 */
public class Parameters {
  private final Map<String, Object> values;

  public Parameters() {
    this.values = new LinkedHashMap<>();
  }

  public Parameters(Map<String, Object> values) {
    this.values = new LinkedHashMap<>(values);
  }

  public boolean isEmpty() {
    return values.isEmpty();
  }

  public boolean contains(String key) {
    return values.containsKey(key);
  }

  public Object get(String key) {
    return values.get(key);
  }

  public Parameters put(String key, Object value) {
    values.put(key, value);
    return this;
  }

  public Boolean getBool(String key) {
    Object v = values.get(key);
    return v instanceof Boolean ? (Boolean) v : null;
  }

  public Long getLong(String key) {
    Object v = values.get(key);
    return v instanceof Number ? ((Number) v).longValue() : null;
  }

  public Double getDouble(String key) {
    Object v = values.get(key);
    return v instanceof Number ? ((Number) v).doubleValue() : null;
  }

  public String getString(String key) {
    Object v = values.get(key);
    return v instanceof String ? (String) v : null;
  }

  /** Live view used for JSON serialization. */
  public Map<String, Object> toMap() {
    return values;
  }
}
