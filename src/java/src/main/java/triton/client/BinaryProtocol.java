// Binary-tensor-extension framing helpers (role of reference
// src/java/.../BinaryProtocol.java: the byte-level encoding that rides
// after the JSON header when Inference-Header-Content-Length is set).
package triton.client;

import java.io.ByteArrayOutputStream;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.List;

/**
 * Encoders/decoders for the v2 binary tensor extension: fixed-width
 * types are raw little-endian element bytes; BYTES elements are each
 * framed with a 4-byte little-endian length prefix.
 */
public final class BinaryProtocol {
  private BinaryProtocol() {}

  // -- fixed-width encode ----------------------------------------------

  public static byte[] encode(int[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (int v : values) {
      buf.putInt(v);
    }
    return buf.array();
  }

  public static byte[] encode(long[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (long v : values) {
      buf.putLong(v);
    }
    return buf.array();
  }

  public static byte[] encode(float[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (float v : values) {
      buf.putFloat(v);
    }
    return buf.array();
  }

  public static byte[] encode(double[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (double v : values) {
      buf.putDouble(v);
    }
    return buf.array();
  }

  // -- fixed-width decode ----------------------------------------------

  public static int[] decodeInt32(byte[] raw) {
    ByteBuffer buf = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    int[] out = new int[raw.length / 4];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getInt();
    }
    return out;
  }

  public static long[] decodeInt64(byte[] raw) {
    ByteBuffer buf = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    long[] out = new long[raw.length / 8];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getLong();
    }
    return out;
  }

  public static float[] decodeFp32(byte[] raw) {
    ByteBuffer buf = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    float[] out = new float[raw.length / 4];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getFloat();
    }
    return out;
  }

  public static double[] decodeFp64(byte[] raw) {
    ByteBuffer buf = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    double[] out = new double[raw.length / 8];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getDouble();
    }
    return out;
  }

  // -- BYTES framing ----------------------------------------------------

  /** Length-prefix frame a list of byte-string elements. */
  public static byte[] encodeBytes(List<byte[]> elements) {
    ByteArrayOutputStream out = new ByteArrayOutputStream();
    ByteBuffer len = ByteBuffer.allocate(4).order(ByteOrder.LITTLE_ENDIAN);
    for (byte[] element : elements) {
      len.clear();
      len.putInt(element.length);
      out.write(len.array(), 0, 4);
      out.write(element, 0, element.length);
    }
    return out.toByteArray();
  }

  /** Convenience: UTF-8 string elements. */
  public static byte[] encodeStrings(List<String> elements) {
    List<byte[]> raw = new ArrayList<>(elements.size());
    for (String s : elements) {
      raw.add(s.getBytes(StandardCharsets.UTF_8));
    }
    return encodeBytes(raw);
  }

  /** Split a length-prefixed BYTES section back into elements. */
  public static List<byte[]> decodeBytes(byte[] raw)
      throws InferenceException {
    List<byte[]> out = new ArrayList<>();
    ByteBuffer buf = ByteBuffer.wrap(raw).order(ByteOrder.LITTLE_ENDIAN);
    while (buf.remaining() >= 4) {
      int n = buf.getInt();
      if (n < 0 || n > buf.remaining()) {
        throw new InferenceException(
            "malformed BYTES tensor: element length " + n + " with "
            + buf.remaining() + " bytes left");
      }
      byte[] element = new byte[n];
      buf.get(element);
      out.add(element);
    }
    if (buf.remaining() != 0) {
      throw new InferenceException(
          "malformed BYTES tensor: " + buf.remaining() + " trailing bytes");
    }
    return out;
  }
}
