// Small shared helpers (role of reference src/java/.../Util.java).
package triton.client;

/** Conversions the typed getters and examples share. */
public final class Util {
  private Util() {}

  /** IEEE 754 half-precision bits -> float (FP16 tensors arrive as raw
   *  2-byte elements; Java has no primitive half type). */
  public static float fp16BitsToFloat(short bits) {
    int sign = (bits >> 15) & 0x1;
    int exp = (bits >> 10) & 0x1f;
    int frac = bits & 0x3ff;
    float value;
    if (exp == 0) {
      value = (float) (frac * Math.pow(2, -24));
    } else if (exp == 0x1f) {
      value = frac == 0 ? Float.POSITIVE_INFINITY : Float.NaN;
    } else {
      value = (float) ((1 + frac / 1024.0) * Math.pow(2, exp - 15));
    }
    return sign == 0 ? value : -value;
  }

  /** float -> IEEE 754 half bits (round-to-nearest-even via the float
   *  intermediate; sufficient for test tensors). */
  public static short floatToFp16Bits(float value) {
    int fbits = Float.floatToIntBits(value);
    int sign = (fbits >>> 16) & 0x8000;
    int val = (fbits & 0x7fffffff) + 0x1000;  // rounding
    if (val >= 0x47800000) {  // overflow -> inf (or NaN preserved)
      if ((fbits & 0x7fffffff) >= 0x47800000) {
        if ((fbits & 0x7fffffff) < 0x7f800000) {
          return (short) (sign | 0x7c00);
        }
        return (short) (sign | 0x7c00 | ((fbits & 0x007fffff) >>> 13));
      }
      return (short) (sign | 0x7bff);
    }
    if (val >= 0x38800000) {  // normal
      return (short) (sign | ((val - 0x38000000) >>> 13));
    }
    if (val < 0x33000000) {  // underflow -> zero
      return (short) sign;
    }
    val = (fbits & 0x7fffffff) >>> 23;  // subnormal
    return (short) (sign
        | ((((fbits & 0x7fffff) | 0x800000) + (0x800000 >>> (val - 102)))
            >>> (126 - val)));
  }

  /** Human-readable byte count for perf/memory reporting. */
  public static String formatBytes(long bytes) {
    if (bytes < 1024) {
      return bytes + " B";
    }
    int unit = (63 - Long.numberOfLeadingZeros(bytes)) / 10;
    return String.format(
        "%.1f %sB", (double) bytes / (1L << (unit * 10)), "KMGTPE".charAt(
            unit - 1));
  }

  /** Monotonic milliseconds (examples measure with this). */
  public static long nowMs() {
    return System.nanoTime() / 1_000_000L;
  }
}
