// Minimal dependency-free JSON reader/writer used by the client (the
// reference's Java client pulls Jackson; this recipe stays stdlib-only).
package triton.client;

import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

final class Json {
  private Json() {}

  // -- writing ------------------------------------------------------------

  static void escape(String s, StringBuilder out) {
    out.append('"');
    for (int i = 0; i < s.length(); i++) {
      char c = s.charAt(i);
      switch (c) {
        case '"' -> out.append("\\\"");
        case '\\' -> out.append("\\\\");
        case '\n' -> out.append("\\n");
        case '\r' -> out.append("\\r");
        case '\t' -> out.append("\\t");
        default -> {
          if (c < 0x20) {
            out.append(String.format("\\u%04x", (int) c));
          } else {
            out.append(c);
          }
        }
      }
    }
    out.append('"');
  }

  static void write(Object value, StringBuilder out) {
    if (value == null) {
      out.append("null");
    } else if (value instanceof String s) {
      escape(s, out);
    } else if (value instanceof Boolean || value instanceof Number) {
      out.append(value.toString());
    } else if (value instanceof Map<?, ?> map) {
      out.append('{');
      boolean first = true;
      for (Map.Entry<?, ?> e : map.entrySet()) {
        if (!first) {
          out.append(',');
        }
        first = false;
        escape(e.getKey().toString(), out);
        out.append(':');
        write(e.getValue(), out);
      }
      out.append('}');
    } else if (value instanceof List<?> list) {
      out.append('[');
      boolean first = true;
      for (Object e : list) {
        if (!first) {
          out.append(',');
        }
        first = false;
        write(e, out);
      }
      out.append(']');
    } else if (value instanceof long[] arr) {
      out.append('[');
      for (int i = 0; i < arr.length; i++) {
        if (i > 0) {
          out.append(',');
        }
        out.append(arr[i]);
      }
      out.append(']');
    } else {
      escape(value.toString(), out);
    }
  }

  static String write(Object value) {
    StringBuilder out = new StringBuilder();
    write(value, out);
    return out.toString();
  }

  // -- parsing ------------------------------------------------------------

  private static final class Parser {
    private final String text;
    private int pos;

    Parser(String text) {
      this.text = text;
    }

    void ws() {
      while (pos < text.length()
          && Character.isWhitespace(text.charAt(pos))) {
        pos++;
      }
    }

    char next() {
      if (pos >= text.length()) {
        throw new IllegalArgumentException("unexpected end of JSON");
      }
      char c = text.charAt(pos);
      pos++;
      return c;
    }

    Object value() {
      ws();
      if (pos >= text.length()) {
        throw new IllegalArgumentException("unexpected end of JSON");
      }
      char c = text.charAt(pos);
      switch (c) {
        case '{':
          return object();
        case '[':
          return array();
        case '"':
          return string();
        case 't':
          expect("true");
          return Boolean.TRUE;
        case 'f':
          expect("false");
          return Boolean.FALSE;
        case 'n':
          expect("null");
          return null;
        default:
          return number();
      }
    }

    void expect(String literal) {
      if (!text.startsWith(literal, pos)) {
        throw new IllegalArgumentException(
            "bad JSON literal at " + pos);
      }
      pos += literal.length();
    }

    Map<String, Object> object() {
      Map<String, Object> out = new LinkedHashMap<>();
      pos++; // '{'
      ws();
      if (pos < text.length() && text.charAt(pos) == '}') {
        pos++;
        return out;
      }
      while (true) {
        ws();
        String key = string();
        ws();
        if (next() != ':') {
          throw new IllegalArgumentException("expected ':' at " + pos);
        }
        out.put(key, value());
        ws();
        char c = next();
        if (c == '}') {
          return out;
        }
        if (c != ',') {
          throw new IllegalArgumentException(
              "expected ',' or '}' at " + pos);
        }
      }
    }

    List<Object> array() {
      List<Object> out = new ArrayList<>();
      pos++; // '['
      ws();
      if (pos < text.length() && text.charAt(pos) == ']') {
        pos++;
        return out;
      }
      while (true) {
        out.add(value());
        ws();
        char c = next();
        if (c == ']') {
          return out;
        }
        if (c != ',') {
          throw new IllegalArgumentException(
              "expected ',' or ']' at " + pos);
        }
      }
    }

    String string() {
      if (next() != '"') {
        throw new IllegalArgumentException("expected string at " + pos);
      }
      StringBuilder out = new StringBuilder();
      while (true) {
        char c = next();
        if (c == '"') {
          return out.toString();
        }
        if (c == '\\') {
          char esc = next();
          switch (esc) {
            case 'n' -> out.append('\n');
            case 'r' -> out.append('\r');
            case 't' -> out.append('\t');
            case 'b' -> out.append('\b');
            case 'f' -> out.append('\f');
            case 'u' -> {
              if (pos + 4 > text.length()) {
                throw new IllegalArgumentException(
                    "unexpected end of JSON");
              }
              out.append(
                  (char) Integer.parseInt(
                      text.substring(pos, pos + 4), 16));
              pos += 4;
            }
            default -> out.append(esc);
          }
        } else {
          out.append(c);
        }
      }
    }

    Object number() {
      int start = pos;
      while (pos < text.length()
          && "+-0123456789.eE".indexOf(text.charAt(pos)) >= 0) {
        pos++;
      }
      String token = text.substring(start, pos);
      if (token.contains(".") || token.contains("e")
          || token.contains("E")) {
        return Double.parseDouble(token);
      }
      return Long.parseLong(token);
    }
  }

  static Object parse(String text) {
    return new Parser(text).value();
  }

  @SuppressWarnings("unchecked")
  static Map<String, Object> parseObject(String text) {
    return (Map<String, Object>) parse(text);
  }
}
