// HTTP client for the KServe v2 inference protocol with the binary
// tensor extension (role of reference
// src/java/.../InferenceServerClient.java:26-60 — async Apache
// HttpAsyncClient there; this design rides the JDK's built-in
// java.net.http.HttpClient, sync + CompletableFuture async).
package triton.client;

import java.io.ByteArrayOutputStream;
import java.io.IOException;
import java.net.URI;
import java.net.http.HttpClient;
import java.net.http.HttpRequest;
import java.net.http.HttpResponse;
import java.nio.charset.StandardCharsets;
import java.time.Duration;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;
import java.util.concurrent.CompletableFuture;

public class InferenceServerClient implements AutoCloseable {
  private final String baseUrl;  // null when endpoint-driven
  private final triton.client.endpoint.AbstractEndpoint endpoint;
  private final HttpClient http;
  private final Duration requestTimeout;

  public InferenceServerClient(String url) {
    this(url, Duration.ofSeconds(60), Duration.ofSeconds(60));
  }

  public InferenceServerClient(
      String url, Duration connectTimeout, Duration requestTimeout) {
    this.baseUrl = normalize(url);
    this.endpoint = null;
    this.requestTimeout = requestTimeout;
    this.http =
        HttpClient.newBuilder().connectTimeout(connectTimeout).build();
  }

  /** Endpoint-abstraction constructor (role of the reference's
   *  endpoint tier): {@code endpoint.getUrl()} is consulted for EVERY
   *  request, so rotating/failover endpoints see each call and get
   *  {@code markFailure} feedback on transport errors. */
  public InferenceServerClient(
      triton.client.endpoint.AbstractEndpoint endpoint) {
    this.baseUrl = null;
    this.endpoint = endpoint;
    this.requestTimeout = Duration.ofSeconds(60);
    this.http = HttpClient.newBuilder()
        .connectTimeout(Duration.ofSeconds(60)).build();
  }

  private static String normalize(String url) {
    return url.startsWith("http://") || url.startsWith("https://")
        ? url
        : "http://" + url;
  }

  private String resolveUrl() throws InferenceException {
    return baseUrl != null ? baseUrl : normalize(endpoint.getUrl());
  }

  private void reportFailure(String url, Exception cause) {
    if (endpoint != null) {
      endpoint.markFailure(url, cause);
    }
  }

  // -- health / metadata ---------------------------------------------------

  public boolean isServerLive() throws InferenceException {
    return get("/v2/health/live").statusCode() == 200;
  }

  public boolean isServerReady() throws InferenceException {
    return get("/v2/health/ready").statusCode() == 200;
  }

  public boolean isModelReady(String modelName) throws InferenceException {
    return get("/v2/models/" + modelName + "/ready").statusCode() == 200;
  }

  public Map<String, Object> getServerMetadata() throws InferenceException {
    return getJson("/v2");
  }

  public Map<String, Object> getModelMetadata(String modelName)
      throws InferenceException {
    return getJson("/v2/models/" + modelName);
  }

  public Map<String, Object> getModelConfig(String modelName)
      throws InferenceException {
    return getJson("/v2/models/" + modelName + "/config");
  }

  public Map<String, Object> getInferenceStatistics(String modelName)
      throws InferenceException {
    return getJson("/v2/models/" + modelName + "/stats");
  }

  // -- model control -------------------------------------------------------

  public void loadModel(String modelName) throws InferenceException {
    post("/v2/repository/models/" + modelName + "/load", new byte[0], null);
  }

  public void unloadModel(String modelName) throws InferenceException {
    post(
        "/v2/repository/models/" + modelName + "/unload", new byte[0], null);
  }

  // -- shared memory -------------------------------------------------------

  public void registerSystemSharedMemory(
      String name, String key, long byteSize) throws InferenceException {
    Map<String, Object> body = new LinkedHashMap<>();
    body.put("key", key);
    body.put("offset", 0L);
    body.put("byte_size", byteSize);
    post(
        "/v2/systemsharedmemory/region/" + name + "/register",
        Json.write(body).getBytes(StandardCharsets.UTF_8),
        "application/json");
  }

  public void unregisterSystemSharedMemory(String name)
      throws InferenceException {
    post(
        "/v2/systemsharedmemory/region/" + name + "/unregister",
        new byte[0], null);
  }

  // -- inference -----------------------------------------------------------

  public InferResult infer(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) throws InferenceException {
    RequestBody body = buildRequestBody(inputs, outputs);
    String url = resolveUrl();
    HttpRequest request =
        requestBuilder(url, "/v2/models/" + modelName + "/infer")
            .header("Content-Type", "application/octet-stream")
            .header(
                "Inference-Header-Content-Length",
                Integer.toString(body.jsonLength))
            .POST(HttpRequest.BodyPublishers.ofByteArray(body.bytes))
            .build();
    HttpResponse<byte[]> response;
    try {
      response =
          http.send(request, HttpResponse.BodyHandlers.ofByteArray());
    } catch (IOException | InterruptedException e) {
      reportFailure(url, e);
      throw new InferenceException("infer request failed", e);
    }
    return toResult(response);
  }

  /** Asynchronous infer on the JDK client's executor. */
  public CompletableFuture<InferResult> inferAsync(
      String modelName, List<InferInput> inputs,
      List<InferRequestedOutput> outputs) {
    RequestBody body;
    try {
      body = buildRequestBody(inputs, outputs);
    } catch (InferenceException e) {
      return CompletableFuture.failedFuture(e);
    }
    String url;
    try {
      url = resolveUrl();
    } catch (InferenceException e) {
      return CompletableFuture.failedFuture(e);
    }
    HttpRequest request =
        requestBuilder(url, "/v2/models/" + modelName + "/infer")
            .header("Content-Type", "application/octet-stream")
            .header(
                "Inference-Header-Content-Length",
                Integer.toString(body.jsonLength))
            .POST(HttpRequest.BodyPublishers.ofByteArray(body.bytes))
            .build();
    return http.sendAsync(request, HttpResponse.BodyHandlers.ofByteArray())
        .whenComplete(
            (response, failure) -> {
              if (failure != null) {
                // transport failure feedback mirrors the sync paths
                reportFailure(
                    url,
                    failure instanceof Exception ? (Exception) failure
                                                 : new RuntimeException(
                                                     failure));
              }
            })
        .thenApply(
            response -> {
              try {
                return toResult(response);
              } catch (InferenceException e) {
                throw new RuntimeException(e);
              }
            });
  }

  // -- internals -----------------------------------------------------------

  private record RequestBody(byte[] bytes, int jsonLength) {}

  private RequestBody buildRequestBody(
      List<InferInput> inputs, List<InferRequestedOutput> outputs)
      throws InferenceException {
    Map<String, Object> header = new LinkedHashMap<>();
    List<Object> inputEntries = new ArrayList<>();
    for (InferInput input : inputs) {
      Map<String, Object> entry = new LinkedHashMap<>();
      entry.put("name", input.getName());
      entry.put("shape", input.getShape());
      entry.put("datatype", input.getDatatype().name());
      Map<String, Object> params = new LinkedHashMap<>();
      if (input.getSharedMemoryRegion() != null) {
        params.put(
            "shared_memory_region", input.getSharedMemoryRegion());
        params.put(
            "shared_memory_byte_size", input.getSharedMemoryByteSize());
        if (input.getSharedMemoryOffset() != 0) {
          params.put(
              "shared_memory_offset", input.getSharedMemoryOffset());
        }
      } else {
        if (input.getData() == null) {
          throw new InferenceException(
              "input '" + input.getName() + "' has no data");
        }
        params.put("binary_data_size", input.getData().length);
      }
      entry.put("parameters", params);
      inputEntries.add(entry);
    }
    header.put("inputs", inputEntries);
    if (outputs != null && !outputs.isEmpty()) {
      List<Object> outputEntries = new ArrayList<>();
      for (InferRequestedOutput output : outputs) {
        Map<String, Object> entry = new LinkedHashMap<>();
        entry.put("name", output.getName());
        Map<String, Object> params = new LinkedHashMap<>();
        if (output.getSharedMemoryRegion() != null) {
          params.put(
              "shared_memory_region", output.getSharedMemoryRegion());
          params.put(
              "shared_memory_byte_size",
              output.getSharedMemoryByteSize());
          if (output.getSharedMemoryOffset() != 0) {
            params.put(
                "shared_memory_offset", output.getSharedMemoryOffset());
          }
        } else {
          params.put("binary_data", output.isBinaryData());
          if (output.getClassCount() > 0) {
            params.put("classification", output.getClassCount());
          }
        }
        entry.put("parameters", params);
        outputEntries.add(entry);
      }
      header.put("outputs", outputEntries);
    }
    byte[] json = Json.write(header).getBytes(StandardCharsets.UTF_8);
    ByteArrayOutputStream body = new ByteArrayOutputStream();
    body.writeBytes(json);
    for (InferInput input : inputs) {
      if (input.getSharedMemoryRegion() == null) {
        body.writeBytes(input.getData());
      }
    }
    return new RequestBody(body.toByteArray(), json.length);
  }

  private InferResult toResult(HttpResponse<byte[]> response)
      throws InferenceException {
    if (response.statusCode() != 200) {
      throw new InferenceException(
          "infer failed: HTTP " + response.statusCode() + ": "
              + new String(response.body(), StandardCharsets.UTF_8));
    }
    Integer headerLength =
        response.headers()
            .firstValue("Inference-Header-Content-Length")
            .map(Integer::parseInt)
            .orElse(null);
    return new InferResult(response.body(), headerLength);
  }

  private HttpRequest.Builder requestBuilder(String url, String path) {
    return HttpRequest.newBuilder()
        .uri(URI.create(url + path))
        .timeout(requestTimeout);
  }

  private HttpResponse<byte[]> get(String path) throws InferenceException {
    String url = resolveUrl();
    try {
      return http.send(
          requestBuilder(url, path).GET().build(),
          HttpResponse.BodyHandlers.ofByteArray());
    } catch (IOException | InterruptedException e) {
      reportFailure(url, e);
      throw new InferenceException("request failed: " + path, e);
    }
  }

  private Map<String, Object> getJson(String path)
      throws InferenceException {
    HttpResponse<byte[]> response = get(path);
    String body = new String(response.body(), StandardCharsets.UTF_8);
    if (response.statusCode() != 200) {
      throw new InferenceException(
          "request failed: HTTP " + response.statusCode() + ": " + body);
    }
    return Json.parseObject(body);
  }

  private void post(String path, byte[] body, String contentType)
      throws InferenceException {
    String url = resolveUrl();
    HttpRequest.Builder builder = requestBuilder(url, path);
    if (contentType != null) {
      builder.header("Content-Type", contentType);
    }
    HttpResponse<byte[]> response;
    try {
      response =
          http.send(
              builder.POST(HttpRequest.BodyPublishers.ofByteArray(body))
                  .build(),
              HttpResponse.BodyHandlers.ofByteArray());
    } catch (IOException | InterruptedException e) {
      reportFailure(url, e);
      throw new InferenceException("request failed: " + path, e);
    }
    if (response.statusCode() != 200) {
      throw new InferenceException(
          "request failed: HTTP " + response.statusCode() + ": "
              + new String(response.body(), StandardCharsets.UTF_8));
    }
  }

  @Override
  public void close() {
    // JDK HttpClient needs no explicit shutdown
  }
}
