// Requested-output descriptor (role of reference
// src/java/.../InferRequestedOutput.java).
package triton.client;

public class InferRequestedOutput {
  private final String name;
  private final boolean binaryData;
  private final int classCount;
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferRequestedOutput(String name) {
    this(name, true, 0);
  }

  public InferRequestedOutput(String name, boolean binaryData) {
    this(name, binaryData, 0);
  }

  public InferRequestedOutput(String name, boolean binaryData, int classCount) {
    this.name = name;
    this.binaryData = binaryData;
    this.classCount = classCount;
  }

  public String getName() {
    return name;
  }

  public boolean isBinaryData() {
    return binaryData;
  }

  public int getClassCount() {
    return classCount;
  }

  String getSharedMemoryRegion() {
    return sharedMemoryRegion;
  }

  long getSharedMemoryByteSize() {
    return sharedMemoryByteSize;
  }

  long getSharedMemoryOffset() {
    return sharedMemoryOffset;
  }

  public void setSharedMemory(String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
  }
}
