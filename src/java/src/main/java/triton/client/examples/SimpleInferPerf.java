// Throughput/latency micro-driver over the Java client (role of
// reference src/java/.../examples/SimpleInferPerf.java).
package triton.client.examples;

import java.util.ArrayList;
import java.util.Collections;
import java.util.List;
import triton.client.DataType;
import triton.client.InferInput;
import triton.client.InferRequestedOutput;
import triton.client.InferResult;
import triton.client.InferenceServerClient;
import triton.client.Util;

/**
 * Drives the {@code simple} add/sub model in a timed loop and reports
 * infer/sec plus p50/p99 latency — the Java-side analogue of the
 * quick-start perf_analyzer measurement.
 *
 * <p>Usage: {@code SimpleInferPerf [url] [seconds]}
 */
public final class SimpleInferPerf {
  private SimpleInferPerf() {}

  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    long seconds = args.length > 1 ? Long.parseLong(args[1]) : 5;

    int[] a = new int[16];
    int[] b = new int[16];
    for (int i = 0; i < 16; i++) {
      a[i] = i;
      b[i] = 2 * i;
    }
    InferInput in0 = new InferInput("INPUT0", new long[] {1, 16},
        DataType.INT32);
    in0.setData(a);
    InferInput in1 = new InferInput("INPUT1", new long[] {1, 16},
        DataType.INT32);
    in1.setData(b);
    List<InferInput> inputs = List.of(in0, in1);
    List<InferRequestedOutput> outputs = List.of(
        new InferRequestedOutput("OUTPUT0", true),
        new InferRequestedOutput("OUTPUT1", true));

    try (InferenceServerClient client = new InferenceServerClient(url)) {
      // warmup + correctness
      InferResult result = client.infer("simple", inputs, outputs);
      int[] sum = result.getOutputAsInt("OUTPUT0");
      for (int i = 0; i < 16; i++) {
        if (sum[i] != a[i] + b[i]) {
          throw new IllegalStateException("OUTPUT0[" + i + "] wrong");
        }
      }

      List<Long> latenciesUs = new ArrayList<>();
      long deadline = Util.nowMs() + seconds * 1000;
      long count = 0;
      long start = Util.nowMs();
      while (Util.nowMs() < deadline) {
        long t0 = System.nanoTime();
        client.infer("simple", inputs, outputs);
        latenciesUs.add((System.nanoTime() - t0) / 1000);
        count++;
      }
      double elapsed = (Util.nowMs() - start) / 1000.0;
      Collections.sort(latenciesUs);
      System.out.printf(
          "Throughput: %.1f infer/sec%n", count / elapsed);
      System.out.printf(
          "Latency: p50 %d us, p99 %d us%n",
          latenciesUs.get(latenciesUs.size() / 2),
          latenciesUs.get((int) (latenciesUs.size() * 0.99)));
    }
  }
}
