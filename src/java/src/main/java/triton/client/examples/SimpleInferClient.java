// Sync + async infer on the `simple` add/sub model (role of the
// reference's Java examples directory).
package triton.client.examples;

import java.util.List;
import triton.client.DataType;
import triton.client.InferInput;
import triton.client.InferRequestedOutput;
import triton.client.InferResult;
import triton.client.InferenceServerClient;

public class SimpleInferClient {
  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    try (InferenceServerClient client = new InferenceServerClient(url)) {
      if (!client.isServerLive()) {
        System.err.println("server is not live");
        System.exit(1);
      }

      int[] input0 = new int[16];
      int[] input1 = new int[16];
      for (int i = 0; i < 16; i++) {
        input0[i] = i;
        input1[i] = 1;
      }
      InferInput in0 =
          new InferInput("INPUT0", new long[] {1, 16}, DataType.INT32);
      in0.setData(input0);
      InferInput in1 =
          new InferInput("INPUT1", new long[] {1, 16}, DataType.INT32);
      in1.setData(input1);
      List<InferRequestedOutput> outputs =
          List.of(
              new InferRequestedOutput("OUTPUT0"),
              new InferRequestedOutput("OUTPUT1"));

      InferResult result =
          client.infer("simple", List.of(in0, in1), outputs);
      int[] sums = result.getOutputAsInt("OUTPUT0");
      int[] diffs = result.getOutputAsInt("OUTPUT1");
      for (int i = 0; i < 16; i++) {
        if (sums[i] != input0[i] + input1[i]
            || diffs[i] != input0[i] - input1[i]) {
          System.err.println("wrong result at " + i);
          System.exit(1);
        }
      }

      // async path
      InferResult asyncResult =
          client.inferAsync("simple", List.of(in0, in1), outputs).join();
      if (asyncResult.getOutputAsInt("OUTPUT0")[0] != 1) {
        System.err.println("wrong async result");
        System.exit(1);
      }
      System.out.println("PASS: java infer");
    }
  }
}
