// Long-running leak check over the Java client (role of reference
// src/java/.../examples/MemoryGrowthTest.java).
package triton.client.examples;

import java.util.List;
import triton.client.DataType;
import triton.client.InferInput;
import triton.client.InferRequestedOutput;
import triton.client.InferenceServerClient;
import triton.client.Util;

/**
 * Hammers {@code simple} inferences and samples JVM heap usage; growth
 * between the early and late thirds beyond a tolerance fails the run
 * (exit 1), catching reference-count leaks in the client plumbing.
 *
 * <p>Usage: {@code MemoryGrowthTest [url] [iterations]}
 */
public final class MemoryGrowthTest {
  private MemoryGrowthTest() {}

  public static void main(String[] args) throws Exception {
    String url = args.length > 0 ? args[0] : "localhost:8000";
    int iterations = args.length > 1 ? Integer.parseInt(args[1]) : 2000;

    int[] a = new int[16];
    int[] b = new int[16];
    for (int i = 0; i < 16; i++) {
      a[i] = i;
      b[i] = i * i;
    }
    InferInput in0 = new InferInput("INPUT0", new long[] {1, 16},
        DataType.INT32);
    in0.setData(a);
    InferInput in1 = new InferInput("INPUT1", new long[] {1, 16},
        DataType.INT32);
    in1.setData(b);
    List<InferInput> inputs = List.of(in0, in1);
    List<InferRequestedOutput> outputs = List.of(
        new InferRequestedOutput("OUTPUT0", true));

    Runtime rt = Runtime.getRuntime();
    long earlySum = 0;
    int earlyCount = 0;
    long lateSum = 0;
    int lateCount = 0;
    try (InferenceServerClient client = new InferenceServerClient(url)) {
      for (int i = 0; i < iterations; i++) {
        client.infer("simple", inputs, outputs);
        if (i % 100 == 0) {
          System.gc();
          long used = rt.totalMemory() - rt.freeMemory();
          if (i < iterations / 3) {
            earlySum += used;
            earlyCount++;
          } else if (i >= 2 * iterations / 3) {
            lateSum += used;
            lateCount++;
          }
        }
      }
    }
    long early = earlySum / Math.max(earlyCount, 1);
    long late = lateSum / Math.max(lateCount, 1);
    System.out.printf(
        "heap early %s -> late %s%n", Util.formatBytes(early),
        Util.formatBytes(late));
    // tolerance: 20% + 8 MB slack for JIT/GC noise
    if (late > early * 1.2 + (8L << 20)) {
      System.err.println("MEMORY GROWTH DETECTED");
      System.exit(1);
    }
    System.out.println("memory growth OK");
  }
}
