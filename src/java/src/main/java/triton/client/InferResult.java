// Inference response: JSON header + optional binary tensor tail, split
// by Inference-Header-Content-Length (role of reference
// src/java/.../InferResult.java).
package triton.client;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.ArrayList;
import java.util.LinkedHashMap;
import java.util.List;
import java.util.Map;

public class InferResult {
  private final Map<String, Object> header;
  private final Map<String, byte[]> binaryOutputs = new LinkedHashMap<>();

  @SuppressWarnings("unchecked")
  InferResult(byte[] body, Integer headerLength) throws InferenceException {
    int jsonLength = headerLength != null ? headerLength : body.length;
    String json =
        new String(body, 0, jsonLength, StandardCharsets.UTF_8);
    try {
      header = Json.parseObject(json);
    } catch (RuntimeException e) {
      throw new InferenceException("malformed response JSON", e);
    }
    int cursor = jsonLength;
    for (Map<String, Object> output : outputs()) {
      Map<String, Object> params =
          (Map<String, Object>) output.get("parameters");
      if (params != null && params.get("binary_data_size") != null) {
        int size = ((Number) params.get("binary_data_size")).intValue();
        byte[] raw = new byte[size];
        System.arraycopy(body, cursor, raw, 0, size);
        cursor += size;
        binaryOutputs.put((String) output.get("name"), raw);
      }
    }
  }

  @SuppressWarnings("unchecked")
  private List<Map<String, Object>> outputs() {
    Object outputs = header.get("outputs");
    return outputs == null
        ? List.of()
        : (List<Map<String, Object>>) (List<?>) outputs;
  }

  public String getModelName() {
    return (String) header.get("model_name");
  }

  public String getId() {
    return (String) header.get("id");
  }

  @SuppressWarnings("unchecked")
  private Map<String, Object> findOutput(String name)
      throws InferenceException {
    for (Map<String, Object> output : outputs()) {
      if (name.equals(output.get("name"))) {
        return output;
      }
    }
    throw new InferenceException("no output named '" + name + "'");
  }

  public long[] getShape(String name) throws InferenceException {
    List<Object> shape =
        asList(findOutput(name).get("shape"));
    long[] out = new long[shape.size()];
    for (int i = 0; i < out.length; i++) {
      out[i] = ((Number) shape.get(i)).longValue();
    }
    return out;
  }

  public String getDatatype(String name) throws InferenceException {
    return (String) findOutput(name).get("datatype");
  }

  @SuppressWarnings("unchecked")
  private static List<Object> asList(Object value) {
    return (List<Object>) value;
  }

  /** Raw little-endian bytes of a binary output. */
  public byte[] getRawData(String name) throws InferenceException {
    byte[] raw = binaryOutputs.get(name);
    if (raw == null) {
      throw new InferenceException(
          "output '" + name + "' has no binary data");
    }
    return raw;
  }

  public int[] getOutputAsInt(String name) throws InferenceException {
    Object data = findOutput(name).get("data");
    if (data != null) { // JSON-delivered tensor
      List<Object> values = asList(data);
      int[] out = new int[values.size()];
      for (int i = 0; i < out.length; i++) {
        out[i] = ((Number) values.get(i)).intValue();
      }
      return out;
    }
    ByteBuffer buf =
        ByteBuffer.wrap(getRawData(name)).order(ByteOrder.LITTLE_ENDIAN);
    int[] out = new int[buf.remaining() / 4];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getInt();
    }
    return out;
  }

  public float[] getOutputAsFloat(String name) throws InferenceException {
    Object data = findOutput(name).get("data");
    if (data != null) {
      List<Object> values = asList(data);
      float[] out = new float[values.size()];
      for (int i = 0; i < out.length; i++) {
        out[i] = ((Number) values.get(i)).floatValue();
      }
      return out;
    }
    ByteBuffer buf =
        ByteBuffer.wrap(getRawData(name)).order(ByteOrder.LITTLE_ENDIAN);
    float[] out = new float[buf.remaining() / 4];
    for (int i = 0; i < out.length; i++) {
      out[i] = buf.getFloat();
    }
    return out;
  }

  /** BYTES tensor elements (4-byte little-endian length prefix each). */
  public List<byte[]> getOutputAsBytes(String name)
      throws InferenceException {
    ByteBuffer buf =
        ByteBuffer.wrap(getRawData(name)).order(ByteOrder.LITTLE_ENDIAN);
    List<byte[]> out = new ArrayList<>();
    while (buf.remaining() >= 4) {
      int length = buf.getInt();
      byte[] element = new byte[length];
      buf.get(element);
      out.add(element);
    }
    return out;
  }
}
