// Error type thrown by the Java client (role of reference
// src/java/.../InferenceException.java).
package triton.client;

public class InferenceException extends Exception {
  public InferenceException(String message) {
    super(message);
  }

  public InferenceException(String message, Throwable cause) {
    super(message, cause);
  }
}
