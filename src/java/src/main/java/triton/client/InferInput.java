// Input tensor descriptor + data for an inference request (role of
// reference src/java/.../InferInput.java).
package triton.client;

import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;
import java.util.List;

public class InferInput {
  private final String name;
  private final long[] shape;
  private final DataType datatype;
  private byte[] data;            // little-endian raw tensor bytes
  private String sharedMemoryRegion;
  private long sharedMemoryByteSize;
  private long sharedMemoryOffset;

  public InferInput(String name, long[] shape, DataType datatype) {
    this.name = name;
    this.shape = shape.clone();
    this.datatype = datatype;
  }

  public String getName() {
    return name;
  }

  public long[] getShape() {
    return shape.clone();
  }

  public DataType getDatatype() {
    return datatype;
  }

  byte[] getData() {
    return data;
  }

  String getSharedMemoryRegion() {
    return sharedMemoryRegion;
  }

  long getSharedMemoryByteSize() {
    return sharedMemoryByteSize;
  }

  long getSharedMemoryOffset() {
    return sharedMemoryOffset;
  }

  /** Raw little-endian tensor bytes (caller-controlled layout). */
  public void setData(byte[] raw) {
    this.data = raw;
    this.sharedMemoryRegion = null;
  }

  public void setData(int[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (int v : values) {
      buf.putInt(v);
    }
    setData(buf.array());
  }

  public void setData(long[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (long v : values) {
      buf.putLong(v);
    }
    setData(buf.array());
  }

  public void setData(float[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 4).order(ByteOrder.LITTLE_ENDIAN);
    for (float v : values) {
      buf.putFloat(v);
    }
    setData(buf.array());
  }

  public void setData(double[] values) {
    ByteBuffer buf =
        ByteBuffer.allocate(values.length * 8).order(ByteOrder.LITTLE_ENDIAN);
    for (double v : values) {
      buf.putDouble(v);
    }
    setData(buf.array());
  }

  /** BYTES tensor: 4-byte little-endian length prefix per element. */
  public void setData(List<byte[]> elements) {
    int total = 0;
    for (byte[] e : elements) {
      total += 4 + e.length;
    }
    ByteBuffer buf =
        ByteBuffer.allocate(total).order(ByteOrder.LITTLE_ENDIAN);
    for (byte[] e : elements) {
      buf.putInt(e.length);
      buf.put(e);
    }
    setData(buf.array());
  }

  public void setStringData(List<String> strings) {
    setData(
        strings.stream()
            .map(s -> s.getBytes(StandardCharsets.UTF_8))
            .toList());
  }

  /** Reference the tensor in a registered shared-memory region instead of
   * carrying bytes in the request body. */
  public void setSharedMemory(String regionName, long byteSize, long offset) {
    this.sharedMemoryRegion = regionName;
    this.sharedMemoryByteSize = byteSize;
    this.sharedMemoryOffset = offset;
    this.data = null;
  }
}
