// Single-address endpoint (role of reference
// src/java/.../endpoint/FixedEndpoint.java).
package triton.client.endpoint;

/** Always returns the one address it was constructed with. */
public class FixedEndpoint extends AbstractEndpoint {
  private final String url;

  public FixedEndpoint(String url) {
    this.url = url;
  }

  @Override
  public String getUrl() {
    return url;
  }
}
