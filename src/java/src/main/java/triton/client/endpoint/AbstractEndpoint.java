// Server-address provider abstraction (role of reference
// src/java/.../endpoint/AbstractEndpoint.java: clients resolve the
// target URL per request, so subclasses can rotate replicas or skip
// unhealthy hosts).
package triton.client.endpoint;

import triton.client.InferenceException;

/**
 * Supplies the base URL for each request. Implementations may load
 * balance or fail over; {@link #markFailure} lets the client report a
 * transport error so stateful endpoints can react.
 */
public abstract class AbstractEndpoint {
  /** Base URL (scheme optional, {@code host:port} accepted) to use for
   *  the next request. */
  public abstract String getUrl() throws InferenceException;

  /** Number of distinct underlying addresses (1 for a fixed endpoint). */
  public int size() {
    return 1;
  }

  /** Transport-failure feedback; default is stateless. */
  public void markFailure(String url, Exception cause) {}
}
