// Unit tests for the gRPC client tier that need no server: HPACK integer
// and header-block codecs (both the nghttp2-backed and fallback decode
// paths), grpc-message percent decoding, and ModelInferRequest protobuf
// assembly. The wire-level integration tests live in
// tests/test_cc_grpc.py against a real grpcio server.

#include <cmath>
#include <cstdio>
#include <cstring>

#include "grpc_channel.h"
#include "grpc_service.pb.h"
#include "hpack.h"

static int failures = 0;
static int checks = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    ++checks;                                                         \
    if (!(cond)) {                                                    \
      ++failures;                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                 \
  } while (0)

#define CHECK_OK(err)                                                  \
  do {                                                                 \
    ++checks;                                                          \
    tc::Error e_ = (err);                                              \
    if (!e_.IsOk()) {                                                  \
      ++failures;                                                      \
      fprintf(                                                         \
          stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,              \
          e_.Message().c_str());                                       \
    }                                                                  \
  } while (0)

using tc::h2::DecodeInteger;
using tc::h2::EncodeInteger;
using tc::h2::Header;
using tc::h2::HpackDecoder;
using tc::h2::HpackEncoder;

static void
TestIntegerCodec()
{
  // RFC 7541 C.1 examples + boundaries
  const uint64_t values[] = {0, 1, 9, 10, 30, 31, 32, 127, 128, 1337,
                             16383, 16384, 0xffffffffull};
  for (int prefix = 4; prefix <= 8; ++prefix) {
    for (uint64_t v : values) {
      std::vector<uint8_t> buf;
      EncodeInteger(v, prefix, 0, &buf);
      size_t pos = 0;
      uint64_t out = 0;
      CHECK(DecodeInteger(buf.data(), buf.size(), &pos, prefix, &out));
      CHECK(out == v);
      CHECK(pos == buf.size());
    }
  }
  // the RFC's worked example: 1337 with 5-bit prefix -> 1f 9a 0a
  std::vector<uint8_t> buf;
  EncodeInteger(1337, 5, 0, &buf);
  CHECK(buf.size() == 3);
  CHECK(buf[0] == 0x1f && buf[1] == 0x9a && buf[2] == 0x0a);
}

static void
RoundTrip(HpackDecoder* decoder)
{
  HpackEncoder encoder;
  std::vector<Header> in = {
      {":method", "POST"},        // exact static match
      {":scheme", "http"},        // exact static match
      {":path", "/inference.GRPCInferenceService/ModelInfer"},
      {":authority", "localhost:8001"},
      {"te", "trailers"},
      {"content-type", "application/grpc"},
      {"grpc-timeout", "1000000u"},
      {"x-empty", ""},
  };
  std::vector<uint8_t> block;
  encoder.EncodeBlock(in, &block);
  std::vector<Header> out;
  CHECK_OK(decoder->DecodeBlock(block.data(), block.size(), &out));
  CHECK(out.size() == in.size());
  for (size_t i = 0; i < in.size() && i < out.size(); ++i) {
    CHECK(out[i].name == in[i].name);
    CHECK(out[i].value == in[i].value);
  }
}

static void
TestHpackRoundTripNghttp2()
{
  HpackDecoder decoder;
  if (!decoder.UsingNghttp2()) {
    fprintf(stderr, "note: libnghttp2 unavailable, skipping\n");
    return;
  }
  RoundTrip(&decoder);
}

static void
TestHpackRoundTripFallback()
{
  HpackDecoder decoder(/*use_nghttp2=*/false);
  CHECK(!decoder.UsingNghttp2());
  RoundTrip(&decoder);
}

static void
TestHpackFallbackDynamicTable()
{
  // hand-encoded: literal WITH incremental indexing (new name), then an
  // indexed reference to the dynamic entry (index 62 = static size + 1)
  HpackDecoder decoder(/*use_nghttp2=*/false);
  std::vector<uint8_t> block;
  block.push_back(0x40);  // literal w/ incremental indexing, new name
  block.push_back(11);    // name len
  const char* name = "grpc-status";
  block.insert(block.end(), name, name + 11);
  block.push_back(1);
  block.push_back('0');
  block.push_back(0x80 | 62);  // indexed: first dynamic entry
  std::vector<Header> out;
  CHECK_OK(decoder.DecodeBlock(block.data(), block.size(), &out));
  CHECK(out.size() == 2);
  CHECK(out[0].name == out[1].name);
  CHECK(out[0].value == "0" && out[1].value == "0");
}

static void
TestHuffmanDecode()
{
  // RFC 7541 Appendix C worked examples
  struct Vec {
    std::vector<uint8_t> coded;
    const char* text;
  };
  const Vec vecs[] = {
      {{0xf1, 0xe3, 0xc2, 0xe5, 0xf2, 0x3a, 0x6b, 0xa0, 0xab, 0x90, 0xf4,
        0xff},
       "www.example.com"},
      {{0xa8, 0xeb, 0x10, 0x64, 0x9c, 0xbf}, "no-cache"},
      {{0x25, 0xa8, 0x49, 0xe9, 0x5b, 0xa9, 0x7d, 0x7f}, "custom-key"},
      {{0x25, 0xa8, 0x49, 0xe9, 0x5b, 0xb8, 0xe8, 0xb4, 0xbf},
       "custom-value"},
      {{0x64, 0x02}, "302"},
      {{0xae, 0xc3, 0x77, 0x1a, 0x4b}, "private"},
      {{0x9d, 0x29, 0xad, 0x17, 0x18, 0x63, 0xc7, 0x8f, 0x0b, 0x97, 0xc8,
        0xe9, 0xae, 0x82, 0xae, 0x43, 0xd3},
       "https://www.example.com"},
  };
  for (const auto& v : vecs) {
    std::string out;
    CHECK(tc::h2::HuffmanDecode(v.coded.data(), v.coded.size(), &out));
    CHECK(out == v.text);
  }
  // '0' is code 00000 (5 bits): 0x07 pads with ones (valid), 0x00 pads
  // with zeros (invalid), 0xff alone is 8 bits of padding (invalid)
  std::string out;
  out.clear();
  const uint8_t ok_pad[] = {0x07};
  CHECK(tc::h2::HuffmanDecode(ok_pad, 1, &out) && out == "0");
  out.clear();
  const uint8_t bad_pad[] = {0x00};
  CHECK(!tc::h2::HuffmanDecode(bad_pad, 1, &out));
  out.clear();
  const uint8_t long_pad[] = {0xff};
  CHECK(!tc::h2::HuffmanDecode(long_pad, 1, &out));
  out.clear();
  CHECK(tc::h2::HuffmanDecode(nullptr, 0, &out) && out.empty());
}

static void
TestHpackFallbackHuffmanBlock()
{
  // RFC 7541 C.6.1: full response header block, Huffman-coded literals
  // WITH incremental indexing — exercises Huffman + dynamic inserts in
  // the fallback decoder (the path a gRPC C-core peer produces).
  HpackDecoder decoder(/*use_nghttp2=*/false);
  const uint8_t block[] = {
      0x48, 0x82, 0x64, 0x02, 0x58, 0x85, 0xae, 0xc3, 0x77, 0x1a, 0x4b,
      0x61, 0x96, 0xd0, 0x7a, 0xbe, 0x94, 0x10, 0x54, 0xd4, 0x44, 0xa8,
      0x20, 0x05, 0x95, 0x04, 0x0b, 0x81, 0x66, 0xe0, 0x82, 0xa6, 0x2d,
      0x1b, 0xff, 0x6e, 0x91, 0x9d, 0x29, 0xad, 0x17, 0x18, 0x63, 0xc7,
      0x8f, 0x0b, 0x97, 0xc8, 0xe9, 0xae, 0x82, 0xae, 0x43, 0xd3};
  std::vector<Header> out;
  CHECK_OK(decoder.DecodeBlock(block, sizeof(block), &out));
  CHECK(out.size() == 4);
  if (out.size() == 4) {
    CHECK(out[0].name == ":status" && out[0].value == "302");
    CHECK(out[1].name == "cache-control" && out[1].value == "private");
    CHECK(
        out[2].name == "date" &&
        out[2].value == "Mon, 21 Oct 2013 20:13:21 GMT");
    CHECK(
        out[3].name == "location" &&
        out[3].value == "https://www.example.com");
  }
  // dynamic entries must now be referenceable (62 = newest = location)
  const uint8_t indexed[] = {0x80 | 62};
  std::vector<Header> out2;
  CHECK_OK(decoder.DecodeBlock(indexed, 1, &out2));
  CHECK(out2.size() == 1 && out2[0].name == "location");
}

static void
TestEncodeGrpcTimeout()
{
  using tc::h2::EncodeGrpcTimeout;
  CHECK(EncodeGrpcTimeout(1) == "1u");
  CHECK(EncodeGrpcTimeout(99999999) == "99999999u");
  // >= 100 seconds in us exceeds 8 digits -> scale to ms (rounded up)
  CHECK(EncodeGrpcTimeout(100000000) == "100000m");
  CHECK(EncodeGrpcTimeout(100000001) == "100001m");
  // and onward through S/M/H
  CHECK(EncodeGrpcTimeout(99999999ull * 1000) == "99999999m");
  CHECK(EncodeGrpcTimeout(100000000ull * 1000) == "100000S");
  const uint64_t us_per_hour = 3600ull * 1000000;
  CHECK(EncodeGrpcTimeout(24 * us_per_hour) == "86400000m");
  // 200000 h = 7.2e8 seconds (9 digits) -> scales to minutes
  CHECK(EncodeGrpcTimeout(200000ull * us_per_hour) == "12000000M");
  for (int i = 0; i < 9; ++i) {
    // every encoding stays within 8 digits + unit
    CHECK(EncodeGrpcTimeout(7ull * (uint64_t)std::pow(10, i)).size() <= 9);
  }
}

static void
TestPercentDecode()
{
  CHECK(tc::h2::PercentDecode("model%20not%20found") == "model not found");
  CHECK(tc::h2::PercentDecode("plain") == "plain");
  CHECK(tc::h2::PercentDecode("trailing%2") == "trailing%2");
  CHECK(tc::h2::PercentDecode("%41%42") == "AB");
}

static void
TestModelInferRequestProto()
{
  inference::ModelInferRequest request;
  request.set_model_name("simple");
  auto* input = request.add_inputs();
  input->set_name("INPUT0");
  input->set_datatype("INT32");
  input->add_shape(1);
  input->add_shape(16);
  std::string raw(64, '\x01');
  request.add_raw_input_contents(raw);
  (*request.mutable_parameters())["sequence_id"].set_uint64_param(42);

  std::string serialized;
  CHECK(request.SerializeToString(&serialized));
  inference::ModelInferRequest parsed;
  CHECK(parsed.ParseFromString(serialized));
  CHECK(parsed.model_name() == "simple");
  CHECK(parsed.inputs_size() == 1);
  CHECK(parsed.inputs(0).shape(1) == 16);
  CHECK(parsed.raw_input_contents(0).size() == 64);
  CHECK(parsed.parameters().at("sequence_id").uint64_param() == 42);
}

int
main()
{
  TestIntegerCodec();
  TestHpackRoundTripNghttp2();
  TestHpackRoundTripFallback();
  TestHpackFallbackDynamicTable();
  TestHuffmanDecode();
  TestHpackFallbackHuffmanBlock();
  TestEncodeGrpcTimeout();
  TestPercentDecode();
  TestModelInferRequestProto();
  printf("%d checks, %d failures\n", checks, failures);
  return failures == 0 ? 0 : 1;
}
