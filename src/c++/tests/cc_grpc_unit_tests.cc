// Unit tests for the gRPC client tier that need no server: HPACK integer
// and header-block codecs (both the nghttp2-backed and fallback decode
// paths), grpc-message percent decoding, and ModelInferRequest protobuf
// assembly. The wire-level integration tests live in
// tests/test_cc_grpc.py against a real grpcio server.

#include <cstdio>
#include <cstring>

#include "grpc_channel.h"
#include "grpc_service.pb.h"
#include "hpack.h"

static int failures = 0;
static int checks = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    ++checks;                                                         \
    if (!(cond)) {                                                    \
      ++failures;                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                 \
  } while (0)

#define CHECK_OK(err)                                                  \
  do {                                                                 \
    ++checks;                                                          \
    tc::Error e_ = (err);                                              \
    if (!e_.IsOk()) {                                                  \
      ++failures;                                                      \
      fprintf(                                                         \
          stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,              \
          e_.Message().c_str());                                       \
    }                                                                  \
  } while (0)

using tc::h2::DecodeInteger;
using tc::h2::EncodeInteger;
using tc::h2::Header;
using tc::h2::HpackDecoder;
using tc::h2::HpackEncoder;

static void
TestIntegerCodec()
{
  // RFC 7541 C.1 examples + boundaries
  const uint64_t values[] = {0, 1, 9, 10, 30, 31, 32, 127, 128, 1337,
                             16383, 16384, 0xffffffffull};
  for (int prefix = 4; prefix <= 8; ++prefix) {
    for (uint64_t v : values) {
      std::vector<uint8_t> buf;
      EncodeInteger(v, prefix, 0, &buf);
      size_t pos = 0;
      uint64_t out = 0;
      CHECK(DecodeInteger(buf.data(), buf.size(), &pos, prefix, &out));
      CHECK(out == v);
      CHECK(pos == buf.size());
    }
  }
  // the RFC's worked example: 1337 with 5-bit prefix -> 1f 9a 0a
  std::vector<uint8_t> buf;
  EncodeInteger(1337, 5, 0, &buf);
  CHECK(buf.size() == 3);
  CHECK(buf[0] == 0x1f && buf[1] == 0x9a && buf[2] == 0x0a);
}

static void
RoundTrip(HpackDecoder* decoder)
{
  HpackEncoder encoder;
  std::vector<Header> in = {
      {":method", "POST"},        // exact static match
      {":scheme", "http"},        // exact static match
      {":path", "/inference.GRPCInferenceService/ModelInfer"},
      {":authority", "localhost:8001"},
      {"te", "trailers"},
      {"content-type", "application/grpc"},
      {"grpc-timeout", "1000000u"},
      {"x-empty", ""},
  };
  std::vector<uint8_t> block;
  encoder.EncodeBlock(in, &block);
  std::vector<Header> out;
  CHECK_OK(decoder->DecodeBlock(block.data(), block.size(), &out));
  CHECK(out.size() == in.size());
  for (size_t i = 0; i < in.size() && i < out.size(); ++i) {
    CHECK(out[i].name == in[i].name);
    CHECK(out[i].value == in[i].value);
  }
}

static void
TestHpackRoundTripNghttp2()
{
  HpackDecoder decoder;
  if (!decoder.UsingNghttp2()) {
    fprintf(stderr, "note: libnghttp2 unavailable, skipping\n");
    return;
  }
  RoundTrip(&decoder);
}

static void
TestHpackRoundTripFallback()
{
  HpackDecoder decoder(/*use_nghttp2=*/false);
  CHECK(!decoder.UsingNghttp2());
  RoundTrip(&decoder);
}

static void
TestHpackFallbackDynamicTable()
{
  // hand-encoded: literal WITH incremental indexing (new name), then an
  // indexed reference to the dynamic entry (index 62 = static size + 1)
  HpackDecoder decoder(/*use_nghttp2=*/false);
  std::vector<uint8_t> block;
  block.push_back(0x40);  // literal w/ incremental indexing, new name
  block.push_back(11);    // name len
  const char* name = "grpc-status";
  block.insert(block.end(), name, name + 11);
  block.push_back(1);
  block.push_back('0');
  block.push_back(0x80 | 62);  // indexed: first dynamic entry
  std::vector<Header> out;
  CHECK_OK(decoder.DecodeBlock(block.data(), block.size(), &out));
  CHECK(out.size() == 2);
  CHECK(out[0].name == out[1].name);
  CHECK(out[0].value == "0" && out[1].value == "0");
}

static void
TestHpackFallbackRejectsHuffman()
{
  HpackDecoder decoder(/*use_nghttp2=*/false);
  // literal w/o indexing, new name, Huffman bit set on name
  std::vector<uint8_t> block = {0x00, 0x83, 0xaa, 0xbb, 0xcc};
  std::vector<Header> out;
  tc::Error err = decoder.DecodeBlock(block.data(), block.size(), &out);
  CHECK(!err.IsOk());
  CHECK(err.Message().find("Huffman") != std::string::npos);
}

static void
TestPercentDecode()
{
  CHECK(tc::h2::PercentDecode("model%20not%20found") == "model not found");
  CHECK(tc::h2::PercentDecode("plain") == "plain");
  CHECK(tc::h2::PercentDecode("trailing%2") == "trailing%2");
  CHECK(tc::h2::PercentDecode("%41%42") == "AB");
}

static void
TestModelInferRequestProto()
{
  inference::ModelInferRequest request;
  request.set_model_name("simple");
  auto* input = request.add_inputs();
  input->set_name("INPUT0");
  input->set_datatype("INT32");
  input->add_shape(1);
  input->add_shape(16);
  std::string raw(64, '\x01');
  request.add_raw_input_contents(raw);
  (*request.mutable_parameters())["sequence_id"].set_uint64_param(42);

  std::string serialized;
  CHECK(request.SerializeToString(&serialized));
  inference::ModelInferRequest parsed;
  CHECK(parsed.ParseFromString(serialized));
  CHECK(parsed.model_name() == "simple");
  CHECK(parsed.inputs_size() == 1);
  CHECK(parsed.inputs(0).shape(1) == 16);
  CHECK(parsed.raw_input_contents(0).size() == 64);
  CHECK(parsed.parameters().at("sequence_id").uint64_param() == 42);
}

int
main()
{
  TestIntegerCodec();
  TestHpackRoundTripNghttp2();
  TestHpackRoundTripFallback();
  TestHpackFallbackDynamicTable();
  TestHpackFallbackRejectsHuffman();
  TestPercentDecode();
  TestModelInferRequestProto();
  printf("%d checks, %d failures\n", checks, failures);
  return failures == 0 ? 0 : 1;
}
