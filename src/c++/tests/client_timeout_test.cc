// Client-timeout behavior against the delayed_identity fixture model
// (role of reference src/c++/tests/client_timeout_test.cc — exercises
// client_timeout_ deadlines on both protocols).

#include <getopt.h>
#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

// request against delayed_identity with the given server-side delay and
// client timeout; returns whether the request succeeded
template <typename ClientT>
bool
DelayedInfer(ClientT* client, uint32_t delay_us, uint64_t timeout_us)
{
  std::vector<int32_t> payload{7};
  tc::InferInput* input0;
  tc::InferInput* delay_in;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&delay_in, "DELAY_US", {1}, "UINT32"),
      "creating DELAY_US");
  std::shared_ptr<tc::InferInput> input0_ptr(input0),
      delay_ptr(delay_in);
  input0_ptr->AppendRaw(
      (const uint8_t*)payload.data(), sizeof(int32_t));
  delay_ptr->AppendRaw((const uint8_t*)&delay_us, sizeof(delay_us));
  tc::InferOptions options("delayed_identity");
  options.client_timeout_us_ = timeout_us;
  tc::InferResult* result = nullptr;
  tc::Error err = client->Infer(
      &result, options, {input0_ptr.get(), delay_ptr.get()});
  bool ok = err.IsOk() && result != nullptr &&
            result->RequestStatus().IsOk();
  delete result;
  return ok;
}

}  // namespace

int
main(int argc, char** argv)
{
  std::string http_url("localhost:8000");
  std::string grpc_url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "u:g:")) != -1) {
    switch (opt) {
      case 'u':
        http_url = optarg;
        break;
      case 'g':
        grpc_url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&http_client, http_url, false),
      "creating http client");
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&grpc_client, grpc_url, false),
      "creating grpc client");

  // generous timeout, no delay: must succeed
  if (!DelayedInfer(http_client.get(), 0, 10 * 1000 * 1000)) {
    std::cerr << "error: http infer failed with generous timeout"
              << std::endl;
    exit(1);
  }
  if (!DelayedInfer(grpc_client.get(), 0, 10 * 1000 * 1000)) {
    std::cerr << "error: grpc infer failed with generous timeout"
              << std::endl;
    exit(1);
  }

  // 500 ms server-side delay with a 50 ms client deadline: must fail
  if (DelayedInfer(http_client.get(), 500 * 1000, 50 * 1000)) {
    std::cerr << "error: http infer ignored the client timeout"
              << std::endl;
    exit(1);
  }
  if (DelayedInfer(grpc_client.get(), 500 * 1000, 50 * 1000)) {
    std::cerr << "error: grpc infer ignored the client timeout"
              << std::endl;
    exit(1);
  }

  // clients survive a timed-out request (fresh request succeeds)
  if (!DelayedInfer(http_client.get(), 0, 10 * 1000 * 1000)) {
    std::cerr << "error: http client broken after timeout" << std::endl;
    exit(1);
  }
  if (!DelayedInfer(grpc_client.get(), 0, 10 * 1000 * 1000)) {
    std::cerr << "error: grpc client broken after timeout" << std::endl;
    exit(1);
  }

  std::cout << "client timeout test OK" << std::endl;
  return 0;
}
