// Unit tests for the C++ client library pieces that need no server:
// tjson parse/serialize round trips, InferInput scatter-gather and BYTES
// serialization, request-body generation, response-body parsing
// (role of reference src/c++/tests + perf_analyzer doctest harness —
// no gtest/doctest in this image, so a minimal assert harness).

#include <cstdio>
#include <cstring>

#include "http_client.h"
#include "tjson.h"

static int failures = 0;
static int checks = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    ++checks;                                                         \
    if (!(cond)) {                                                    \
      ++failures;                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                 \
  } while (0)

#define CHECK_OK(err)                                                  \
  do {                                                                 \
    ++checks;                                                          \
    tc::Error e_ = (err);                                              \
    if (!e_.IsOk()) {                                                  \
      ++failures;                                                      \
      fprintf(                                                         \
          stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,              \
          e_.Message().c_str());                                       \
    }                                                                  \
  } while (0)

static void
TestJsonRoundTrip()
{
  std::string err;
  auto v = tc::json::Parse(
      "{\"a\": 1, \"b\": [true, null, 2.5, \"x\\ny\"], \"c\": {\"d\": "
      "-7}}",
      &err);
  CHECK(v != nullptr);
  CHECK(v->Get("a")->AsInt() == 1);
  CHECK(v->Get("b")->Size() == 4);
  CHECK(v->Get("b")->At(0)->AsBool());
  CHECK(v->Get("b")->At(1)->IsNull());
  CHECK(v->Get("b")->At(2)->AsDouble() == 2.5);
  CHECK(v->Get("b")->At(3)->AsString() == "x\ny");
  CHECK(v->Get("c")->Get("d")->AsInt() == -7);

  // serialize -> reparse
  auto v2 = tc::json::Parse(v->Serialize(), &err);
  CHECK(v2 != nullptr);
  CHECK(v2->Get("c")->Get("d")->AsInt() == -7);

  // errors
  CHECK(tc::json::Parse("{", &err) == nullptr);
  CHECK(!err.empty());
  CHECK(tc::json::Parse("[1, 2", &err) == nullptr);
  CHECK(tc::json::Parse("nope", &err) == nullptr);
  // unicode escape
  auto u = tc::json::Parse("\"\\u00e9\"", &err);
  CHECK(u != nullptr && u->AsString() == "\xc3\xa9");
}

static void
TestInferInputScatterGather()
{
  tc::InferInput* raw;
  CHECK_OK(tc::InferInput::Create(&raw, "IN", {2, 4}, "INT32"));
  std::unique_ptr<tc::InferInput> input(raw);
  int32_t a[4] = {1, 2, 3, 4};
  int32_t b[4] = {5, 6, 7, 8};
  CHECK_OK(input->AppendRaw((uint8_t*)a, sizeof(a)));
  CHECK_OK(input->AppendRaw((uint8_t*)b, sizeof(b)));
  CHECK(input->TotalByteSize() == 32);

  CHECK_OK(input->PrepareForRequest());
  const uint8_t* buf;
  size_t len;
  bool end = false;
  CHECK_OK(input->GetNext(&buf, &len, &end));
  CHECK(buf == (uint8_t*)a && len == 16 && !end);
  CHECK_OK(input->GetNext(&buf, &len, &end));
  CHECK(buf == (uint8_t*)b && len == 16 && end);

  // shm exclusivity
  CHECK(!input->SetSharedMemory("region", 32).IsOk());
  CHECK_OK(input->Reset());
  CHECK_OK(input->SetSharedMemory("region", 32));
  CHECK(input->IsSharedMemory());
  CHECK(!input->AppendRaw((uint8_t*)a, 4).IsOk());
}

static void
TestBytesSerialization()
{
  tc::InferInput* raw;
  CHECK_OK(tc::InferInput::Create(&raw, "S", {2}, "BYTES"));
  std::unique_ptr<tc::InferInput> input(raw);
  CHECK_OK(input->AppendFromString({"ab", "cdef"}));
  CHECK(input->TotalByteSize() == 4 + 2 + 4 + 4);
  CHECK_OK(input->PrepareForRequest());
  const uint8_t* buf;
  size_t len;
  bool end;
  CHECK_OK(input->GetNext(&buf, &len, &end));
  uint32_t l0;
  memcpy(&l0, buf, 4);
  CHECK(l0 == 2 && memcmp(buf + 4, "ab", 2) == 0);
}

static void
TestGenerateRequestBody()
{
  tc::InferInput* in_raw;
  CHECK_OK(tc::InferInput::Create(&in_raw, "INPUT0", {1, 4}, "INT32"));
  std::unique_ptr<tc::InferInput> input(in_raw);
  int32_t data[4] = {9, 8, 7, 6};
  CHECK_OK(input->AppendRaw((uint8_t*)data, sizeof(data)));

  tc::InferRequestedOutput* out_raw;
  CHECK_OK(tc::InferRequestedOutput::Create(&out_raw, "OUTPUT0"));
  std::unique_ptr<tc::InferRequestedOutput> output(out_raw);

  tc::InferOptions options("simple");
  options.request_id_ = "req-1";
  options.sequence_id_ = 42;
  options.sequence_start_ = true;

  std::vector<uint8_t> body;
  size_t header_length;
  CHECK_OK(tc::InferenceServerHttpClient::GenerateRequestBody(
      &body, &header_length, options, {input.get()}, {output.get()}));
  CHECK(body.size() == header_length + sizeof(data));
  CHECK(memcmp(body.data() + header_length, data, sizeof(data)) == 0);

  std::string err;
  auto doc = tc::json::Parse(
      std::string((const char*)body.data(), header_length), &err);
  CHECK(doc != nullptr);
  CHECK(doc->Get("id")->AsString() == "req-1");
  CHECK(doc->Get("parameters")->Get("sequence_id")->AsInt() == 42);
  auto in0 = doc->Get("inputs")->At(0);
  CHECK(in0->Get("name")->AsString() == "INPUT0");
  CHECK(
      in0->Get("parameters")->Get("binary_data_size")->AsInt() ==
      (int64_t)sizeof(data));
}

static void
TestParseResponseBody()
{
  // response: JSON header + one binary INT32[4] section
  int32_t payload[4] = {10, 20, 30, 40};
  std::string header =
      "{\"model_name\":\"simple\",\"model_version\":\"1\",\"id\":\"7\","
      "\"outputs\":[{\"name\":\"OUTPUT0\",\"datatype\":\"INT32\","
      "\"shape\":[1,4],\"parameters\":{\"binary_data_size\":16}}]}";
  std::vector<uint8_t> body(header.begin(), header.end());
  body.insert(
      body.end(), (uint8_t*)payload, (uint8_t*)payload + sizeof(payload));

  tc::InferResult* result;
  CHECK_OK(tc::InferenceServerHttpClient::ParseResponseBody(
      &result, body, header.size()));
  std::unique_ptr<tc::InferResult> result_ptr(result);
  std::string name, version, id, datatype;
  CHECK_OK(result->ModelName(&name));
  CHECK(name == "simple");
  CHECK_OK(result->Id(&id));
  CHECK(id == "7");
  std::vector<int64_t> shape;
  CHECK_OK(result->Shape("OUTPUT0", &shape));
  CHECK(shape.size() == 2 && shape[0] == 1 && shape[1] == 4);
  CHECK_OK(result->Datatype("OUTPUT0", &datatype));
  CHECK(datatype == "INT32");
  const uint8_t* buf;
  size_t byte_size;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK(byte_size == 16);
  CHECK(memcmp(buf, payload, 16) == 0);
  CHECK(!result->RawData("NOPE", &buf, &byte_size).IsOk());
  CHECK_OK(result->RequestStatus());
}

static void
TestErrorResponse()
{
  std::string header = "{\"error\":\"model not found\"}";
  std::vector<uint8_t> body(header.begin(), header.end());
  tc::InferResult* result;
  CHECK_OK(tc::InferenceServerHttpClient::ParseResponseBody(
      &result, body, header.size()));
  std::unique_ptr<tc::InferResult> result_ptr(result);
  CHECK(!result->RequestStatus().IsOk());
  CHECK(result->RequestStatus().Message() == "model not found");
}

int
main()
{
  TestJsonRoundTrip();
  TestInferInputScatterGather();
  TestBytesSerialization();
  TestGenerateRequestBody();
  TestParseResponseBody();
  TestErrorResponse();
  printf("%d checks, %d failures\n", checks, failures);
  return failures == 0 ? 0 : 1;
}
