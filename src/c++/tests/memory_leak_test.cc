// Loop inference and verify client-side memory stays bounded (role of
// reference src/c++/tests/memory_leak_test.cc, which loops infer against
// a live server watching for growth; RSS via getrusage here).

#include <getopt.h>
#include <sys/resource.h>
#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

long
RssKb()
{
  struct rusage usage;
  getrusage(RUSAGE_SELF, &usage);
  return usage.ru_maxrss;
}

}  // namespace

int
main(int argc, char** argv)
{
  std::string url("localhost:8000");
  std::string protocol = "http";
  int iterations = 2000;
  long max_growth_kb = 32 * 1024;
  int opt;
  while ((opt = getopt(argc, argv, "u:i:n:g:")) != -1) {
    switch (opt) {
      case 'u':
        url = optarg;
        break;
      case 'i':
        protocol = optarg;
        break;
      case 'n':
        iterations = atoi(optarg);
        break;
      case 'g':
        max_growth_kb = atol(optarg);
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-u url] [-i http|grpc] [-n iters] [-g max_kb]"
                  << std::endl;
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  if (protocol == "grpc") {
    FAIL_IF_ERR(
        tc::InferenceServerGrpcClient::Create(&grpc_client, url, false),
        "creating grpc client");
  } else {
    FAIL_IF_ERR(
        tc::InferenceServerHttpClient::Create(&http_client, url, false),
        "creating http client");
  }

  std::vector<int32_t> input0_data(16), input1_data(16, 1);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
  }
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  input0_ptr->AppendRaw(
      (const uint8_t*)input0_data.data(),
      input0_data.size() * sizeof(int32_t));
  input1_ptr->AppendRaw(
      (const uint8_t*)input1_data.data(),
      input1_data.size() * sizeof(int32_t));
  tc::InferOptions options("simple");
  std::vector<tc::InferInput*> inputs{input0_ptr.get(), input1_ptr.get()};

  auto infer_once = [&]() {
    tc::InferResult* result = nullptr;
    if (grpc_client != nullptr) {
      FAIL_IF_ERR(
          grpc_client->Infer(&result, options, inputs), "infer");
    } else {
      FAIL_IF_ERR(
          http_client->Infer(&result, options, inputs), "infer");
    }
    FAIL_IF_ERR(result->RequestStatus(), "request status");
    delete result;
  };

  // warmup establishes steady-state allocations (pools, buffers)
  for (int i = 0; i < 200; ++i) {
    infer_once();
  }
  long baseline_kb = RssKb();
  for (int i = 0; i < iterations; ++i) {
    infer_once();
  }
  long growth_kb = RssKb() - baseline_kb;
  std::cout << "rss baseline " << baseline_kb << " KB, growth after "
            << iterations << " iterations: " << growth_kb << " KB"
            << std::endl;
  if (growth_kb > max_growth_kb) {
    std::cerr << "error: memory growth " << growth_kb << " KB exceeds "
              << max_growth_kb << " KB" << std::endl;
    exit(1);
  }
  std::cout << "memory leak test OK" << std::endl;
  return 0;
}
