// TLS end-to-end smoke driver, exercised by tests/test_cc_tls.py.
//
// Usage:
//   tls_smoke_test http  https://HOST:PORT CA_FILE
//   tls_smoke_test grpc  HOST:PORT        CA_FILE
//   tls_smoke_test http-noverify https://HOST:PORT
//
// Connects with TLS (verifying against CA_FILE unless -noverify), checks
// server liveness, runs one `simple` add/sub inference, and prints
// "TLS_SMOKE_OK <alpn-protocol-or-http1>" on success.  Exit 0/1.
// Proves the capability the reference gets from libcurl/grpc++ TLS
// (reference http_client.h:46-87, grpc_client.h:43-82) works end-to-end
// on this stack's dlopen'd-OpenSSL transport (library/tls.h).

#include <cstdio>
#include <cstring>
#include <iostream>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace {

void
FillInputs(
    std::vector<int32_t>* in0, std::vector<int32_t>* in1,
    std::vector<tc::InferInput*>* inputs)
{
  for (int i = 0; i < 16; ++i) {
    (*in0)[i] = i;
    (*in1)[i] = 2 * i;
  }
  tc::InferInput* input0;
  tc::InferInput* input1;
  std::vector<int64_t> shape{1, 16};
  if (!tc::InferInput::Create(&input0, "INPUT0", shape, "INT32").IsOk() ||
      !tc::InferInput::Create(&input1, "INPUT1", shape, "INT32").IsOk()) {
    std::cerr << "input create failed" << std::endl;
    exit(1);
  }
  input0->AppendRaw(
      reinterpret_cast<uint8_t*>(in0->data()), in0->size() * sizeof(int32_t));
  input1->AppendRaw(
      reinterpret_cast<uint8_t*>(in1->data()), in1->size() * sizeof(int32_t));
  inputs->push_back(input0);
  inputs->push_back(input1);
}

int
CheckSum(tc::InferResult* result)
{
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  if (!result->RawData("OUTPUT0", &buf, &byte_size).IsOk() ||
      byte_size != 16 * sizeof(int32_t)) {
    std::cerr << "bad OUTPUT0" << std::endl;
    return 1;
  }
  const int32_t* vals = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (vals[i] != 3 * i) {
      std::cerr << "OUTPUT0[" << i << "] = " << vals[i] << " != " << 3 * i
                << std::endl;
      return 1;
    }
  }
  return 0;
}

}  // namespace

int
main(int argc, char** argv)
{
  if (argc < 3) {
    std::cerr << "usage: tls_smoke_test http|grpc|http-noverify URL [CA]"
              << std::endl;
    return 2;
  }
  const std::string mode = argv[1];
  const std::string url = argv[2];
  const std::string ca = argc > 3 ? argv[3] : "";

  std::vector<int32_t> in0(16), in1(16);
  std::vector<tc::InferInput*> inputs;
  FillInputs(&in0, &in1, &inputs);
  tc::InferOptions options("simple");
  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  if (!tc::InferRequestedOutput::Create(&output0, "OUTPUT0").IsOk() ||
      !tc::InferRequestedOutput::Create(&output1, "OUTPUT1").IsOk()) {
    std::cerr << "output create failed" << std::endl;
    return 1;
  }
  std::vector<const tc::InferRequestedOutput*> outputs{output0, output1};

  if (mode == "http" || mode == "http-noverify") {
    tc::HttpSslOptions ssl;
    ssl.ca_info = ca;
    if (mode == "http-noverify") {
      ssl.verify_peer = 0;
      ssl.verify_host = 0;
    }
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    tc::Error err =
        tc::InferenceServerHttpClient::Create(&client, url, false, 2, ssl);
    if (!err.IsOk()) {
      std::cerr << "create failed: " << err.Message() << std::endl;
      return 1;
    }
    bool live = false;
    err = client->IsServerLive(&live);
    if (!err.IsOk() || !live) {
      std::cerr << "liveness failed: " << err.Message() << std::endl;
      return 1;
    }
    tc::InferResult* result = nullptr;
    err = client->Infer(&result, options, inputs, outputs);
    if (!err.IsOk()) {
      std::cerr << "infer failed: " << err.Message() << std::endl;
      return 1;
    }
    int rc = CheckSum(result);
    delete result;
    if (rc == 0) {
      std::cout << "TLS_SMOKE_OK http1" << std::endl;
    }
    return rc;
  }

  if (mode == "grpc") {
    tc::SslOptions ssl;
    ssl.root_certificates = ca;
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    tc::Error err = tc::InferenceServerGrpcClient::Create(
        &client, url, false, true /* use_ssl */, ssl);
    if (!err.IsOk()) {
      std::cerr << "create failed: " << err.Message() << std::endl;
      return 1;
    }
    bool live = false;
    err = client->IsServerLive(&live);
    if (!err.IsOk() || !live) {
      std::cerr << "liveness failed: " << err.Message() << std::endl;
      return 1;
    }
    tc::InferResult* result = nullptr;
    err = client->Infer(&result, options, inputs, outputs);
    if (!err.IsOk()) {
      std::cerr << "infer failed: " << err.Message() << std::endl;
      return 1;
    }
    int rc = CheckSum(result);
    delete result;
    if (rc == 0) {
      std::cout << "TLS_SMOKE_OK h2" << std::endl;
    }
    return rc;
  }

  std::cerr << "unknown mode " << mode << std::endl;
  return 2;
}
