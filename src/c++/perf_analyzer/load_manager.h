// Load-manager base: owns the data loader, workers, and the timestamp
// plumbing the profiler swaps out each measurement window
// (reference load_manager.{h,cc}:63-167).

#pragma once

#include <atomic>
#include <memory>
#include <thread>
#include <vector>

#include "infer_context.h"

namespace pa {

struct LoadManagerConfig {
  int batch_size = 1;
  SharedMemoryType shared_memory = SharedMemoryType::NONE;
  bool zero_input = false;
  std::string input_data_json;  // empty -> synthetic
  bool async = false;
  // issue over the backend's bidi stream (gRPC only); decoupled models
  // get the empty-final-response marker so completion is detectable
  bool streaming = false;
  bool decoupled = false;
  bool use_sequences = false;
  size_t sequence_length = 20;
  double sequence_length_variation = 20.0;
  // concurrent sequence streams + id allocation (reference
  // --num-of-sequences / --start-sequence-id / --sequence-id-range)
  size_t num_of_sequences = 4;
  uint64_t start_sequence_id = 1;
  uint64_t sequence_id_range = 0;
  uint32_t seed = 17;
  // directory of per-input raw data files (reference --data-directory)
  std::string data_directory;
  // XLA-shm regions attach to this device on the server side
  int xla_device_ordinal = 0;
};

class LoadManager {
 public:
  LoadManager(
      std::shared_ptr<ClientBackend> backend,
      std::shared_ptr<ModelParser> parser, const LoadManagerConfig& config)
      : backend_(std::move(backend)), parser_(std::move(parser)),
        config_(config)
  {
  }

  virtual ~LoadManager()
  {
    StopWorkers();
    if (stream_tracker_ != nullptr) {
      backend_->StopStream();
    }
    TeardownSystemShm();
    TeardownXlaShm();
  }

  tc::Error InitManager()
  {
    data_loader_ = std::make_shared<DataLoader>();
    tc::Error err;
    if (!config_.input_data_json.empty()) {
      err = data_loader_->ReadDataFromJson(
          parser_->Inputs(), config_.input_data_json, config_.batch_size);
    } else if (!config_.data_directory.empty()) {
      err = data_loader_->ReadDataFromDir(
          parser_->Inputs(), config_.data_directory, config_.batch_size);
    } else {
      err = data_loader_->GenerateData(
          parser_->Inputs(), config_.zero_input, 1, 1, config_.batch_size,
          config_.seed);
    }
    if (!err.IsOk()) {
      return err;
    }
    if (config_.shared_memory == SharedMemoryType::SYSTEM) {
      err = SetupSystemShm();
    } else if (config_.shared_memory == SharedMemoryType::XLA) {
      err = SetupXlaShm();
    }
    if (!err.IsOk()) {
      return err;
    }
    if (config_.streaming) {
      stream_tracker_ = std::make_shared<StreamTracker>();
      auto tracker = stream_tracker_;
      err = backend_->StartStream(
          [tracker](BackendInferResult&& result) {
            tracker->OnResponse(std::move(result));
          });
    }
    return err;
  }


  // Swap out all accumulated request records (one measurement window).
  std::vector<RequestRecord> SwapRequestRecords()
  {
    std::vector<RequestRecord> out;
    {
      std::lock_guard<std::mutex> lk(retired_mu_);
      out.swap(retired_records_);
    }
    for (auto& stat : thread_stats_) {
      std::lock_guard<std::mutex> lk(stat->mu);
      out.insert(out.end(), stat->records.begin(), stat->records.end());
      stat->records.clear();
    }
    return out;
  }

  size_t GetAndResetNumSentRequests()
  {
    return sent_requests_.exchange(0);
  }

  // Active worker threads at the current load level (overhead-pct math).
  size_t WorkerCount() const { return threads_.size(); }

  tc::Error CheckHealth()
  {
    if (!retired_status_.IsOk()) {
      return retired_status_;
    }
    for (auto& stat : thread_stats_) {
      std::lock_guard<std::mutex> lk(stat->mu);
      if (!stat->status.IsOk()) {
        return stat->status;
      }
    }
    return tc::Error::Success;
  }

  virtual void StopWorkers()
  {
    stop_.store(true);
    for (auto& t : threads_) {
      if (t.joinable()) {
        t.join();
      }
    }
    threads_.clear();
    stop_.store(false);
    // retire the finished level's stats so window swaps and health checks
    // stay proportional to the current level; unswapped records are kept
    // for the next SwapRequestRecords (the profiler discards pre-window
    // leftovers itself at each level start)
    for (auto& stat : thread_stats_) {
      std::lock_guard<std::mutex> lk(stat->mu);
      if (!stat->status.IsOk()) {
        retired_status_ = stat->status;
      }
      std::lock_guard<std::mutex> lk2(retired_mu_);
      retired_records_.insert(
          retired_records_.end(), stat->records.begin(),
          stat->records.end());
      stat->records.clear();
    }
    thread_stats_.clear();
  }

 protected:
  tc::Error SetupSystemShm();
  void TeardownSystemShm();
  // XLA/TPU shared memory from a non-JAX process: create the region's
  // host staging window (POSIX shm, the cross-process half of an
  // XlaShmHandle) and register it with a handle the server's
  // xla_shared_memory.attach_from_raw_handle understands.
  tc::Error SetupXlaShm();
  void TeardownXlaShm();

  std::shared_ptr<InferContext> MakeContext(size_t seq_slot)
  {
    auto stat = std::make_shared<ThreadStat>();
    thread_stats_.push_back(stat);
    std::shared_ptr<SequenceManager> seq;
    if (config_.use_sequences) {
      if (sequence_manager_ == nullptr) {
        sequence_manager_ = std::make_shared<SequenceManager>(
            config_.num_of_sequences, config_.sequence_length,
            config_.sequence_length_variation, config_.seed,
            config_.start_sequence_id, config_.sequence_id_range);
      }
      seq = sequence_manager_;
    }
    return std::make_shared<InferContext>(
        backend_, parser_, data_loader_, seq, stat, config_.batch_size,
        seq_slot, shm_layout_);
  }

  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  LoadManagerConfig config_;
  std::shared_ptr<DataLoader> data_loader_;
  std::shared_ptr<SequenceManager> sequence_manager_;
  std::shared_ptr<StreamTracker> stream_tracker_;
  std::vector<std::shared_ptr<ThreadStat>> thread_stats_;
  std::vector<std::thread> threads_;
  std::atomic<bool> stop_{false};
  std::atomic<size_t> sent_requests_{0};
  std::shared_ptr<ShmLayout> shm_layout_;
  std::mutex retired_mu_;
  std::vector<RequestRecord> retired_records_;
  tc::Error retired_status_ = tc::Error::Success;
  void* shm_base_ = nullptr;
  int shm_fd_ = -1;
  size_t shm_total_ = 0;
  bool xla_shm_registered_ = false;
};

}  // namespace pa
