// In-process serving backend: embeds the tpuserver Python runtime in the
// perf_analyzer process so inference is measured without any network or
// IPC — the TPU-native role of the reference's "triton_c_api" mode,
// which dlopens libtritonserver.so and binds ~40 TRITONSERVER_* symbols
// (reference client_backend/triton_c_api/triton_loader.h:85-115).  Here
// the embedded runtime is CPython (libpython) hosting
// tpuserver.core.InferenceServer, and the binding surface is a small
// JSON+bytes bridge (see kBridgeSource in tpuserver_loader.cc).
//
// Like the reference's C-API mode, calls are serialized (the reference
// supports no async mode either — docs/benchmarking.md:92-98); here the
// GIL is the serializer.

#pragma once

#include <memory>
#include <string>
#include <vector>

#include "client_backend.h"

namespace pa {

class TpuServerLoader {
 public:
  struct Options {
    // directory holding the tpuserver/tritonclient packages (the repo's
    // src/python); role of the reference's --triton-server-directory
    std::string server_src;
    bool include_vision = false;
    bool verbose = false;
  };

  // Initialize the embedded interpreter + server core (idempotent; the
  // process can host only one interpreter, mirroring the reference's
  // single TritonLoader singleton, triton_loader.cc:230-235).
  static tc::Error Create(const Options& options);
  static TpuServerLoader* GetSingleton();

  bool Initialized() const { return initialized_; }

  tc::Error ServerReady(bool* ready);
  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version);
  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version);
  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name);

  // request/response carried as a JSON descriptor plus aligned raw
  // buffers (non-shm inputs), matching the backend-neutral types.
  tc::Error Infer(
      BackendInferResult* result, const BackendInferRequest& request);

  tc::Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size);
  tc::Error UnregisterSystemSharedMemory(const std::string& name);
  tc::Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal);
  tc::Error UnregisterXlaSharedMemory(const std::string& name);

 private:
  TpuServerLoader() = default;
  tc::Error InitPython(const Options& options);

  bool initialized_ = false;
};

}  // namespace pa
