#include "metrics_manager.h"

#include "rest_util.h"

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>

namespace pa {

namespace {

// url "host:port/path" -> host/port/path (path defaults to /metrics);
// socket work is shared with the REST backends (rest_util)
void
SplitUrl(const std::string& url, std::string* host, int* port,
         std::string* path)
{
  std::string u = url;
  auto scheme = u.find("://");
  if (scheme != std::string::npos) {
    u = u.substr(scheme + 3);
  }
  auto slash = u.find('/');
  *path = (slash == std::string::npos) ? "/metrics" : u.substr(slash);
  SplitHostPort(u, 8002, host, port);  // 8002: reference metrics port
}

}  // namespace

bool
IsRelevantMetric(const std::string& name)
{
  // the accelerator/host gauges the report cares about (reference parses
  // nv_gpu_utilization / nv_gpu_power_usage / nv_gpu_memory_*; the TPU
  // server exports tpu_* and process_* analogues)
  static const char* kPrefixes[] = {"nv_", "tpu_", "process_"};
  for (const char* p : kPrefixes) {
    if (name.rfind(p, 0) == 0) {
      return true;
    }
  }
  return name.find("utilization") != std::string::npos ||
         name.find("duty") != std::string::npos ||
         name.find("memory") != std::string::npos ||
         name.find("power") != std::string::npos;
}

MetricsSnapshot
ParsePrometheusText(const std::string& body)
{
  MetricsSnapshot snap;
  std::istringstream ss(body);
  std::string line;
  while (std::getline(ss, line)) {
    if (line.empty() || line[0] == '#') {
      continue;
    }
    // name{labels} value [timestamp]   |   name value [timestamp]
    size_t value_at = line.find_last_of(' ');
    if (value_at == std::string::npos) {
      continue;
    }
    std::string name = line.substr(0, value_at);
    std::string value_str = line.substr(value_at + 1);
    // a trailing timestamp makes the tail non-numeric-value; try the
    // previous token too
    char* end = nullptr;
    double value = strtod(value_str.c_str(), &end);
    if (end == value_str.c_str()) {
      continue;
    }
    // strip possible trailing timestamp: "name{l} 3.4 1700000000"
    size_t prev_space = name.find_last_of(' ');
    if (prev_space != std::string::npos &&
        name.find('}') != std::string::npos &&
        prev_space > name.find('}')) {
      value = strtod(name.c_str() + prev_space + 1, nullptr);
      name = name.substr(0, prev_space);
    } else if (
        prev_space != std::string::npos &&
        name.find('{') == std::string::npos) {
      value = strtod(name.c_str() + prev_space + 1, nullptr);
      name = name.substr(0, prev_space);
    }
    snap[name] = value;
  }
  return snap;
}

tc::Error
MetricsManager::ScrapeOnce(MetricsSnapshot* out)
{
  std::string host, path;
  int port = 0;
  SplitUrl(url_, &host, &port, &path);
  std::string body;
  long code = 0;
  tc::Error err =
      RestRequest(host, port, "GET", path, "", "", &code, &body);
  if (err.IsOk() && code != 200) {
    err = tc::Error(
        "metrics: non-200 response: HTTP " + std::to_string(code));
  }
  if (!err.IsOk()) {
    return err;
  }
  *out = ParsePrometheusText(body);
  return tc::Error::Success;
}

tc::Error
MetricsManager::Start()
{
  MetricsSnapshot snap;
  tc::Error err = ScrapeOnce(&snap);
  if (!err.IsOk()) {
    return err;  // fail fast when the endpoint is absent
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : snap) {
      if (IsRelevantMetric(kv.first)) {
        acc_[kv.first] = {kv.second, 1};
      }
    }
  }
  thread_ = std::thread(&MetricsManager::Loop, this);
  return tc::Error::Success;
}

void
MetricsManager::Stop()
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    exit_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) {
    thread_.join();
  }
}

void
MetricsManager::Loop()
{
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait_for(lk, std::chrono::milliseconds(interval_ms_), [&]() {
        return exit_;
      });
      if (exit_) {
        return;
      }
    }
    MetricsSnapshot snap;
    if (!ScrapeOnce(&snap).IsOk()) {
      continue;  // transient failure: keep polling
    }
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : snap) {
      if (!IsRelevantMetric(kv.first)) {
        continue;
      }
      auto& slot = acc_[kv.first];
      slot.first += kv.second;
      slot.second += 1;
    }
  }
}

void
MetricsManager::StartNewMeasurement()
{
  std::lock_guard<std::mutex> lk(mu_);
  acc_.clear();
}

MetricsSnapshot
MetricsManager::MeasurementAverages()
{
  std::lock_guard<std::mutex> lk(mu_);
  MetricsSnapshot out;
  for (const auto& kv : acc_) {
    if (kv.second.second > 0) {
      out[kv.first] = kv.second.first / (double)kv.second.second;
    }
  }
  return out;
}

}  // namespace pa
