#include "metrics_manager.h"

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>

namespace pa {

namespace {

// Minimal blocking HTTP/1.0 GET (Connection: close framing keeps the
// read loop trivial; a metrics scrape every second doesn't need a pool).
tc::Error
HttpGet(
    const std::string& host, int port, const std::string& path,
    std::string* body)
{
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc =
      getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res);
  if (rc != 0) {
    return tc::Error(
        "metrics: failed to resolve " + host + ": " + gai_strerror(rc));
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      continue;
    }
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    return tc::Error("metrics: unable to connect to " + host);
  }
  std::string request = "GET " + path +
                        " HTTP/1.0\r\nHost: " + host +
                        "\r\nConnection: close\r\n\r\n";
  if (::send(fd, request.data(), request.size(), MSG_NOSIGNAL) !=
      (ssize_t)request.size()) {
    close(fd);
    return tc::Error("metrics: send failed");
  }
  std::string response;
  char buf[8192];
  ssize_t n;
  while ((n = ::read(fd, buf, sizeof(buf))) > 0) {
    response.append(buf, n);
  }
  close(fd);
  size_t header_end = response.find("\r\n\r\n");
  if (header_end == std::string::npos) {
    return tc::Error("metrics: malformed HTTP response");
  }
  if (response.find("200") == std::string::npos ||
      response.find("200") > response.find("\r\n")) {
    return tc::Error(
        "metrics: non-200 response: " +
        response.substr(0, response.find("\r\n")));
  }
  *body = response.substr(header_end + 4);
  return tc::Error::Success;
}

void
SplitUrl(const std::string& url, std::string* host, int* port,
         std::string* path)
{
  std::string u = url;
  auto scheme = u.find("://");
  if (scheme != std::string::npos) {
    u = u.substr(scheme + 3);
  }
  auto slash = u.find('/');
  *path = (slash == std::string::npos) ? "/metrics" : u.substr(slash);
  if (slash != std::string::npos) {
    u = u.substr(0, slash);
  }
  auto colon = u.rfind(':');
  if (colon == std::string::npos) {
    *host = u;
    *port = 8002;  // reference Triton metrics port
  } else {
    *host = u.substr(0, colon);
    *port = atoi(u.c_str() + colon + 1);
  }
}

}  // namespace

bool
IsRelevantMetric(const std::string& name)
{
  // the accelerator/host gauges the report cares about (reference parses
  // nv_gpu_utilization / nv_gpu_power_usage / nv_gpu_memory_*; the TPU
  // server exports tpu_* and process_* analogues)
  static const char* kPrefixes[] = {"nv_", "tpu_", "process_"};
  for (const char* p : kPrefixes) {
    if (name.rfind(p, 0) == 0) {
      return true;
    }
  }
  return name.find("utilization") != std::string::npos ||
         name.find("duty") != std::string::npos ||
         name.find("memory") != std::string::npos ||
         name.find("power") != std::string::npos;
}

MetricsSnapshot
ParsePrometheusText(const std::string& body)
{
  MetricsSnapshot snap;
  std::istringstream ss(body);
  std::string line;
  while (std::getline(ss, line)) {
    if (line.empty() || line[0] == '#') {
      continue;
    }
    // name{labels} value [timestamp]   |   name value [timestamp]
    size_t value_at = line.find_last_of(' ');
    if (value_at == std::string::npos) {
      continue;
    }
    std::string name = line.substr(0, value_at);
    std::string value_str = line.substr(value_at + 1);
    // a trailing timestamp makes the tail non-numeric-value; try the
    // previous token too
    char* end = nullptr;
    double value = strtod(value_str.c_str(), &end);
    if (end == value_str.c_str()) {
      continue;
    }
    // strip possible trailing timestamp: "name{l} 3.4 1700000000"
    size_t prev_space = name.find_last_of(' ');
    if (prev_space != std::string::npos &&
        name.find('}') != std::string::npos &&
        prev_space > name.find('}')) {
      value = strtod(name.c_str() + prev_space + 1, nullptr);
      name = name.substr(0, prev_space);
    } else if (
        prev_space != std::string::npos &&
        name.find('{') == std::string::npos) {
      value = strtod(name.c_str() + prev_space + 1, nullptr);
      name = name.substr(0, prev_space);
    }
    snap[name] = value;
  }
  return snap;
}

tc::Error
MetricsManager::ScrapeOnce(MetricsSnapshot* out)
{
  std::string host, path;
  int port = 0;
  SplitUrl(url_, &host, &port, &path);
  std::string body;
  tc::Error err = HttpGet(host, port, path, &body);
  if (!err.IsOk()) {
    return err;
  }
  *out = ParsePrometheusText(body);
  return tc::Error::Success;
}

tc::Error
MetricsManager::Start()
{
  MetricsSnapshot snap;
  tc::Error err = ScrapeOnce(&snap);
  if (!err.IsOk()) {
    return err;  // fail fast when the endpoint is absent
  }
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : snap) {
      if (IsRelevantMetric(kv.first)) {
        acc_[kv.first] = {kv.second, 1};
      }
    }
  }
  thread_ = std::thread(&MetricsManager::Loop, this);
  return tc::Error::Success;
}

void
MetricsManager::Stop()
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    exit_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) {
    thread_.join();
  }
}

void
MetricsManager::Loop()
{
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait_for(lk, std::chrono::milliseconds(interval_ms_), [&]() {
        return exit_;
      });
      if (exit_) {
        return;
      }
    }
    MetricsSnapshot snap;
    if (!ScrapeOnce(&snap).IsOk()) {
      continue;  // transient failure: keep polling
    }
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& kv : snap) {
      if (!IsRelevantMetric(kv.first)) {
        continue;
      }
      auto& slot = acc_[kv.first];
      slot.first += kv.second;
      slot.second += 1;
    }
  }
}

void
MetricsManager::StartNewMeasurement()
{
  std::lock_guard<std::mutex> lk(mu_);
  acc_.clear();
}

MetricsSnapshot
MetricsManager::MeasurementAverages()
{
  std::lock_guard<std::mutex> lk(mu_);
  MetricsSnapshot out;
  for (const auto& kv : acc_) {
    if (kv.second.second > 0) {
      out[kv.first] = kv.second.first / (double)kv.second.second;
    }
  }
  return out;
}

}  // namespace pa
