// perf_analyzer unit tests, mock-backend-first: everything runs without a
// server (role of the reference's doctest suite,
// perf_analyzer_unit_tests.cc:37-39 + test_*.cc).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <set>

#include "command_line_parser.h"
#include "concurrency_manager.h"
#include "inference_profiler.h"
#include "metrics_manager.h"
#include "mock_client_backend.h"
#include "perf_analyzer.h"
#include "report_writer.h"
#include "request_rate_manager.h"

static int failures = 0;
static int checks = 0;

#define CHECK(cond)                                                   \
  do {                                                                \
    ++checks;                                                         \
    if (!(cond)) {                                                    \
      ++failures;                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                 \
  } while (0)

using namespace pa;

// -- CLI parsing (reference test_command_line_parser.cc) --------------------

static void
TestCliDefaults()
{
  const char* argv[] = {"perf_analyzer", "-m", "simple"};
  PerfAnalyzerParameters params;
  std::string error;
  CHECK(CLParser::Parse(3, (char**)argv, &params, &error));
  CHECK(params.model_name == "simple");
  CHECK(params.url == "localhost:8000");
  CHECK(params.batch_size == 1);
  CHECK(params.measurement_window_ms == 5000);
  CHECK(params.stability_threshold_pct == 10.0);
  CHECK(params.concurrency_start == 1 && params.concurrency_end == 1);
}

static void
TestCliMissingModel()
{
  const char* argv[] = {"perf_analyzer"};
  PerfAnalyzerParameters params;
  std::string error;
  CHECK(!CLParser::Parse(1, (char**)argv, &params, &error));
  CHECK(error.find("model-name") != std::string::npos);
}

static void
TestCliSslShapeAndDataOptions()
{
  const char* argv[] = {
      "perf_analyzer", "-m", "simple",
      "--ssl-grpc-use-ssl",
      "--ssl-grpc-root-certifications-file", "/tmp/ca.pem",
      "--ssl-https-verify-peer", "0",
      "--ssl-https-verify-host", "0",
      "--ssl-https-ca-certificates-file", "/tmp/https-ca.pem",
      "--shape", "INPUT0:3,224,224",
      "--shape", "INPUT1:8",
      "--num-of-sequences", "7",
      "--data-directory", "/tmp/data",
      "--grpc-compression-algorithm", "gzip",
      "--model-signature-name", "my_sig",
      "--bls-composing-models", "tok,enc",
      "--triton-server-directory", "/srv/tree",
      "--model-repository", "/models/vision/",
  };
  PerfAnalyzerParameters params;
  std::string error;
  CHECK(CLParser::Parse(
      sizeof(argv) / sizeof(argv[0]), (char**)argv, &params, &error));
  CHECK(params.ssl_grpc_use_ssl);
  CHECK(params.ssl_grpc_root_certifications_file == "/tmp/ca.pem");
  CHECK(params.ssl_https_verify_peer == 0);
  CHECK(params.ssl_https_verify_host == 0);
  CHECK(params.ssl_https_ca_certificates_file == "/tmp/https-ca.pem");
  CHECK(params.input_shapes.size() == 2);
  CHECK(params.input_shapes[0].first == "INPUT0");
  CHECK(
      params.input_shapes[0].second ==
      (std::vector<int64_t>{3, 224, 224}));
  CHECK(params.input_shapes[1].second == (std::vector<int64_t>{8}));
  CHECK(params.num_of_sequences == 7);
  CHECK(params.data_directory == "/tmp/data");
  CHECK(params.grpc_compression_algorithm == "gzip");
  CHECK(params.model_signature_name == "my_sig");
  CHECK(params.bls_composing_models.size() == 2);
  CHECK(params.bls_composing_models[1] == "enc");
  CHECK(params.server_src == "/srv/tree");
  CHECK(params.server_zoo == "vision");

  const char* bad_shape[] = {
      "perf_analyzer", "-m", "simple", "--shape", "noshape"};
  PerfAnalyzerParameters p2;
  CHECK(!CLParser::Parse(5, (char**)bad_shape, &p2, &error));
  const char* bad_comp[] = {
      "perf_analyzer", "-m", "simple", "--grpc-compression-algorithm",
      "br"};
  PerfAnalyzerParameters p3;
  CHECK(!CLParser::Parse(5, (char**)bad_comp, &p3, &error));
  const char* bad_repo[] = {
      "perf_analyzer", "-m", "simple", "--model-repository", "/nope"};
  PerfAnalyzerParameters p4;
  CHECK(!CLParser::Parse(5, (char**)bad_repo, &p4, &error));
}

static void
TestShapeOverrideAndDataDirectory()
{
  ModelParser parser;
  parser.InitDirect(
      "m", 0,
      {ModelTensor{"IN", "FP32", {-1, 4}}},
      {ModelTensor{"OUT", "FP32", {4}}});
  CHECK(parser.Inputs()[0].is_shape_dynamic());
  CHECK(parser.OverrideShapes({{"IN", {2, 4}}}).IsOk());
  CHECK(!parser.Inputs()[0].is_shape_dynamic());
  CHECK(parser.Inputs()[0].shape == (std::vector<int64_t>{2, 4}));
  CHECK(!parser.OverrideShapes({{"NOPE", {1}}}).IsOk());

  // data-directory: raw file feeding an input, size-checked
  char dir[] = "/tmp/pa_dataXXXXXX";
  CHECK(mkdtemp(dir) != nullptr);
  std::string path = std::string(dir) + "/IN";
  {
    FILE* f = fopen(path.c_str(), "wb");
    float vals[8] = {1, 2, 3, 4, 5, 6, 7, 8};
    fwrite(vals, sizeof(float), 8, f);
    fclose(f);
  }
  DataLoader loader;
  CHECK(loader.ReadDataFromDir(parser.Inputs(), dir, 1).IsOk());
  const std::vector<uint8_t>* data = nullptr;
  CHECK(loader.GetInputData("IN", 0, 0, &data).IsOk());
  CHECK(data->size() == 8 * sizeof(float));
  // wrong size -> loud error
  ModelParser parser2;
  parser2.InitDirect(
      "m", 0, {ModelTensor{"IN", "FP32", {16, 4}}}, {});
  DataLoader loader2;
  CHECK(!loader2.ReadDataFromDir(parser2.Inputs(), dir, 1).IsOk());
  remove(path.c_str());
  remove(dir);
}

static void
TestSequenceIdAllocation()
{
  // start id + bounded range wrap (reference --start-sequence-id /
  // --sequence-id-range)
  SequenceManager mgr(2, 1, 0.0, 33, 100, 3);
  std::vector<uint64_t> seen;
  for (int i = 0; i < 6; ++i) {
    auto flags = mgr.Next(i % 2);
    CHECK(flags.start && flags.end);  // length-1 sequences
    seen.push_back(flags.sequence_id);
  }
  for (uint64_t id : seen) {
    CHECK(id >= 100 && id < 103);
  }
}

static void
TestCliRanges()
{
  const char* argv[] = {
      "perf_analyzer", "-m", "m", "--concurrency-range", "2:8:2",
      "--measurement-mode", "count_windows", "--shared-memory", "xla",
      "--request-distribution", "poisson"};
  PerfAnalyzerParameters params;
  std::string error;
  CHECK(CLParser::Parse(11, (char**)argv, &params, &error));
  CHECK(params.concurrency_start == 2);
  CHECK(params.concurrency_end == 8);
  CHECK(params.concurrency_step == 2);
  CHECK(params.count_windows);
  CHECK(params.shared_memory == SharedMemoryType::XLA);
  CHECK(params.request_distribution == Distribution::POISSON);

  const char* bad[] = {
      "perf_analyzer", "-m", "m", "--concurrency-range", "2:8:0"};
  PerfAnalyzerParameters p2;
  CHECK(!CLParser::Parse(5, (char**)bad, &p2, &error));
}

static void
TestCliBackHalf()
{
  // the reference's remaining option surface: search, stability metric,
  // streaming, trace forwarding, metrics collection
  const char* argv[] = {
      "perf_analyzer", "-m", "m", "-i", "grpc",
      "--concurrency-range", "1:32:1", "-l", "50", "--binary-search",
      "--percentile", "99", "--warmup-request-count", "10",
      "--streaming", "--trace-file", "/tmp/t.json", "--trace-level",
      "TIMESTAMPS", "--trace-rate", "100", "--collect-metrics",
      "--metrics-interval", "250", "--verbose-csv", "--enable-mpi",
      "--string-length", "64", "--start-sequence-id", "7",
      "--sequence-id-range", "100"};
  PerfAnalyzerParameters params;
  std::string error;
  CHECK(CLParser::Parse(
      sizeof(argv) / sizeof(argv[0]), (char**)argv, &params, &error));
  CHECK(params.latency_threshold_ms == 50);
  CHECK(params.binary_search);
  CHECK(params.percentile == 99);
  CHECK(params.warmup_request_count == 10);
  CHECK(params.streaming);
  CHECK(params.trace_file == "/tmp/t.json");
  CHECK(params.trace_level == "TIMESTAMPS");
  CHECK(params.trace_rate == 100);
  CHECK(params.collect_metrics);
  CHECK(params.metrics_interval_ms == 250);
  CHECK(params.verbose_csv);
  CHECK(params.enable_mpi);
  CHECK(params.string_length == 64);
  CHECK(params.start_sequence_id == 7);
  CHECK(params.sequence_id_range == 100);

  // --binary-search without -l is invalid
  const char* bad1[] = {
      "perf_analyzer", "-m", "m", "--concurrency-range", "1:8",
      "--binary-search"};
  PerfAnalyzerParameters p1;
  CHECK(!CLParser::Parse(6, (char**)bad1, &p1, &error));
  CHECK(error.find("latency-threshold") != std::string::npos);

  // --binary-search without a range is invalid
  const char* bad2[] = {
      "perf_analyzer", "-m", "m", "-l", "10", "--binary-search"};
  PerfAnalyzerParameters p2;
  CHECK(!CLParser::Parse(6, (char**)bad2, &p2, &error));
  CHECK(error.find("range") != std::string::npos);

  // --streaming requires grpc
  const char* bad3[] = {"perf_analyzer", "-m", "m", "--streaming"};
  PerfAnalyzerParameters p3;
  CHECK(!CLParser::Parse(4, (char**)bad3, &p3, &error));
  CHECK(error.find("grpc") != std::string::npos);

  // --percentile bounds
  const char* bad4[] = {"perf_analyzer", "-m", "m", "--percentile", "101"};
  PerfAnalyzerParameters p4;
  CHECK(!CLParser::Parse(5, (char**)bad4, &p4, &error));

  // legacy -t concurrency alias
  const char* legacy[] = {"perf_analyzer", "-m", "m", "-t", "6"};
  PerfAnalyzerParameters p5;
  CHECK(CLParser::Parse(5, (char**)legacy, &p5, &error));
  CHECK(p5.concurrency_start == 6 && p5.concurrency_end == 6);
}

// -- schedule distribution (reference test_request_rate_manager.cc) --------

static void
TestScheduleDistribution()
{
  ScheduleDistribution constant(Distribution::CONSTANT, 100.0, 1);
  CHECK(constant.NextGapNs() == 10000000ull);
  CHECK(constant.NextGapNs() == 10000000ull);

  ScheduleDistribution poisson(Distribution::POISSON, 1000.0, 1);
  double total = 0;
  const int kSamples = 20000;
  for (int i = 0; i < kSamples; ++i) {
    total += (double)poisson.NextGapNs();
  }
  double mean_us = total / kSamples / 1000.0;
  CHECK(std::fabs(mean_us - 1000.0) < 50.0);  // ~1ms mean gap
}

// -- profiler math (reference test_inference_profiler.cc) -------------------

static void
TestSummarizeRecords()
{
  std::vector<RequestRecord> records;
  // 100 successes with latencies 1..100 ms
  for (uint64_t i = 1; i <= 100; ++i) {
    records.push_back({0, i * 1000000, true, false});
  }
  records.push_back({0, 1, false, false});  // one failure
  auto stats =
      InferenceProfiler::SummarizeRecords(records, 1000000000ull);
  CHECK(stats.request_count == 100);
  CHECK(stats.failed_request_count == 1);
  CHECK(stats.infer_per_sec == 100.0);
  CHECK(stats.avg_latency_ns == 50500000ull);
  CHECK(stats.p50_ns == 50000000ull);
  CHECK(stats.p90_ns == 90000000ull);
  CHECK(stats.p95_ns == 95000000ull);
  CHECK(stats.p99_ns == 99000000ull);
}

// -- model parser -----------------------------------------------------------

static void
TestModelParser()
{
  MockClientBackend backend;
  ModelParser parser;
  CHECK(parser.Init(&backend, "mock", "").IsOk());
  CHECK(parser.ModelName() == "mock");
  CHECK(parser.MaxBatchSize() == 8);
  CHECK(parser.Inputs().size() == 1);
  CHECK(parser.Inputs()[0].name == "INPUT0");
  CHECK(parser.Inputs()[0].datatype == "INT32");
  CHECK(parser.Outputs().size() == 1);
  CHECK(parser.Scheduler() == SchedulerType::NONE);
}

// -- data loader ------------------------------------------------------------

static void
TestDataLoader()
{
  std::vector<ModelTensor> inputs = {
      {"INPUT0", "INT32", {16}}, {"STR", "BYTES", {2}}};
  DataLoader loader;
  CHECK(loader.GenerateData(inputs, false, 1, 2, 1).IsOk());
  const std::vector<uint8_t>* data;
  CHECK(loader.GetInputData("INPUT0", 0, 0, &data).IsOk());
  CHECK(data->size() == 64);
  CHECK(loader.GetInputData("STR", 0, 1, &data).IsOk());
  CHECK(data->size() == 2 * (4 + 7));  // 2x len-prefixed "pa_data"
  CHECK(!loader.GetInputData("NOPE", 0, 0, &data).IsOk());

  DataLoader json_loader;
  CHECK(json_loader
            .ReadDataFromJson(
                {{"INPUT0", "INT32", {4}}},
                "{\"data\": [{\"INPUT0\": [1, 2, 3, 4]}]}")
            .IsOk());
  CHECK(json_loader.GetInputData("INPUT0", 0, 0, &data).IsOk());
  CHECK(data->size() == 16);
  int32_t vals[4];
  memcpy(vals, data->data(), 16);
  CHECK(vals[0] == 1 && vals[3] == 4);
}

// -- sequence manager -------------------------------------------------------

static void
TestSequenceManager()
{
  SequenceManager mgr(2, 3, 0.0);
  // slot 0: 3-long sequence then a new id
  auto f1 = mgr.Next(0);
  CHECK(f1.start && !f1.end);
  auto f2 = mgr.Next(0);
  CHECK(!f2.start && !f2.end);
  CHECK(f2.sequence_id == f1.sequence_id);
  auto f3 = mgr.Next(0);
  CHECK(f3.end);
  auto f4 = mgr.Next(0);
  CHECK(f4.start);
  CHECK(f4.sequence_id != f1.sequence_id);
  // slot 1 is independent
  auto g1 = mgr.Next(1);
  CHECK(g1.start);
  CHECK(g1.sequence_id != f4.sequence_id);
  // CompleteOngoing closes the open ones
  auto open = mgr.CompleteOngoing();
  CHECK(open.size() == 2);  // f4 started slot 0; g1 started slot 1
  for (const auto& f : open) {
    CHECK(f.end);
  }
}

// -- concurrency manager against the mock (reference
//    test_concurrency_manager.cc) ------------------------------------------

static void
TestConcurrencyManagerAgainstMock()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 1000});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  ConcurrencyManager manager(backend, parser, config);
  CHECK(manager.InitManager().IsOk());
  CHECK(manager.ChangeConcurrencyLevel(4).IsOk());
  std::this_thread::sleep_for(std::chrono::milliseconds(200));
  manager.StopWorkers();
  auto records = manager.SwapRequestRecords();
  // 4 workers x ~1ms per request x 200ms window: expect roughly 800,
  // definitely in (100, 1600)
  CHECK(records.size() > 100);
  CHECK(records.size() < 1600);
  for (const auto& r : records) {
    CHECK(r.success);
    CHECK(r.end_ns > r.start_ns);
  }
  CHECK(backend->Stats().infer_calls >= records.size());
}

static void
TestConcurrencyManagerFailuresSurface()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{
          .response_delay_us = 100,
          .return_statuses = {true, false}});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  ConcurrencyManager manager(backend, parser, config);
  CHECK(manager.InitManager().IsOk());
  CHECK(manager.ChangeConcurrencyLevel(2).IsOk());
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  manager.StopWorkers();
  auto records = manager.SwapRequestRecords();
  size_t failed = 0;
  for (const auto& r : records) {
    failed += r.success ? 0 : 1;
  }
  CHECK(failed > 0);
}

// -- request rate manager ---------------------------------------------------

static void
TestRequestRateManagerAgainstMock()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 100});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  RequestRateManager manager(
      backend, parser, config, Distribution::CONSTANT, 2);
  CHECK(manager.InitManager().IsOk());
  CHECK(manager.ChangeRequestRate(500.0).IsOk());
  std::this_thread::sleep_for(std::chrono::milliseconds(400));
  manager.StopWorkers();
  // wait for async completions to land
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  auto records = manager.SwapRequestRecords();
  // 500/sec over 0.4s -> ~200; allow wide margin for scheduling jitter
  CHECK(records.size() > 100);
  CHECK(records.size() < 350);
}

// -- sequences flow through the load manager --------------------------------

static void
TestSequencesThroughManager()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 100});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  config.use_sequences = true;
  config.sequence_length = 4;
  config.sequence_length_variation = 0.0;
  ConcurrencyManager manager(backend, parser, config);
  CHECK(manager.InitManager().IsOk());
  CHECK(manager.ChangeConcurrencyLevel(2).IsOk());
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  manager.StopWorkers();
  auto seq_records = backend->SequenceRecords();
  CHECK(!seq_records.empty());
  // per sequence id: exactly one start, one end, in order
  std::map<uint64_t, std::vector<MockClientBackend::SeqRecord>> by_id;
  for (const auto& r : seq_records) {
    by_id[r.id].push_back(r);
  }
  size_t complete = 0;
  for (const auto& kv : by_id) {
    const auto& seq = kv.second;
    CHECK(seq.front().start);
    for (size_t i = 1; i < seq.size(); ++i) {
      CHECK(!seq[i].start);
    }
    if (seq.back().end) {
      ++complete;
      CHECK(seq.size() == 4);
    }
  }
  CHECK(complete > 0);
}

// -- end-to-end profile against the mock ------------------------------------

static void
TestProfilerEndToEndWithMock()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 500});
  PerfAnalyzerParameters params;
  params.model_name = "mock";
  params.measurement_window_ms = 100;
  params.max_trials = 5;
  params.stability_threshold_pct = 50.0;  // fast convergence for the test
  PerfAnalyzer analyzer(params);
  CHECK(analyzer.CreateAnalyzerObjects(backend).IsOk());
  CHECK(analyzer.Profile().IsOk());
  CHECK(analyzer.Results().size() == 1);
  const auto& status = analyzer.Results()[0];
  CHECK(status.concurrency == 1);
  CHECK(status.client_stats.request_count > 50);
  CHECK(status.client_stats.infer_per_sec > 100);
  CHECK(status.client_stats.avg_latency_ns > 400000);
  CHECK(status.server_stats.inference_count > 0);
}

// -- stability determination (reference test_inference_profiler.cc:160-738)

static ClientSideStats
MakeWindow(double infer_per_sec, uint64_t stab_lat_ns)
{
  ClientSideStats w;
  w.request_count = 100;
  w.infer_per_sec = infer_per_sec;
  w.avg_latency_ns = stab_lat_ns;
  w.stability_latency_ns = stab_lat_ns;
  return w;
}

static void
TestDetermineStability()
{
  using IP = InferenceProfiler;
  // fewer than 3 windows can never be stable
  CHECK(!IP::DetermineStability({MakeWindow(100, 1000)}, 10.0));
  CHECK(!IP::DetermineStability(
      {MakeWindow(100, 1000), MakeWindow(100, 1000)}, 10.0));
  // three identical windows are stable
  CHECK(IP::DetermineStability(
      {MakeWindow(100, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000)},
      10.0));
  // oscillating throughput beyond +-10% is unstable even though the
  // latency is rock solid (rate-unstable / latency-stable)
  CHECK(!IP::DetermineStability(
      {MakeWindow(100, 1000), MakeWindow(130, 1000),
       MakeWindow(100, 1000)},
      10.0));
  // latency oscillation with stable rate is equally unstable
  // (latency-unstable / rate-stable)
  CHECK(!IP::DetermineStability(
      {MakeWindow(100, 1000), MakeWindow(100, 1300),
       MakeWindow(100, 1000)},
      10.0));
  // deviation is measured against the LAST window: drift that ends
  // within threshold of the final value is stable
  CHECK(IP::DetermineStability(
      {MakeWindow(95, 1000), MakeWindow(98, 1020),
       MakeWindow(100, 1000)},
      10.0));
  // boundary: exactly at the threshold passes (> rejects, not >=)
  CHECK(IP::DetermineStability(
      {MakeWindow(90, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000)},
      10.0));
  CHECK(!IP::DetermineStability(
      {MakeWindow(89, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000)},
      10.0));
  // only the last `window_count` windows matter: early chaos is fine
  CHECK(IP::DetermineStability(
      {MakeWindow(500, 9000), MakeWindow(5, 50), MakeWindow(100, 1000),
       MakeWindow(100, 1000), MakeWindow(100, 1000)},
      10.0));
  // a tighter threshold rejects what a looser one accepts
  CHECK(IP::DetermineStability(
      {MakeWindow(95, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000)},
      10.0));
  CHECK(!IP::DetermineStability(
      {MakeWindow(95, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000)},
      1.0));
  // custom window_count: 4 windows must all agree
  CHECK(!IP::DetermineStability(
      {MakeWindow(130, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000), MakeWindow(100, 1000)},
      10.0, 4));
  CHECK(IP::DetermineStability(
      {MakeWindow(100, 1000), MakeWindow(100, 1000),
       MakeWindow(100, 1000), MakeWindow(100, 1000)},
      10.0, 4));
}

// -- custom-interval manager (reference test_custom_load_manager.cc:108) ----

static void
TestCustomIntervalParsing()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 50});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  {
    CustomLoadManager manager(backend, parser, config);
    CHECK(manager.InitManager().IsOk());
    // microsecond lines -> nanosecond schedule; blank lines skipped
    CHECK(manager.InitCustomIntervals("1000\n2000\n\n1500\n").IsOk());
    manager.StopWorkers();
    const auto& sched = manager.Schedule();
    CHECK(sched.size() == 3);
    CHECK(sched[0] == 1000000ull);
    CHECK(sched[1] == 2000000ull);
    CHECK(sched[2] == 1500000ull);
  }
  {
    CustomLoadManager manager(backend, parser, config);
    CHECK(manager.InitManager().IsOk());
    tc::Error err = manager.InitCustomIntervals("");
    CHECK(!err.IsOk());
    CHECK(err.Message().find("no intervals") != std::string::npos);
  }
}

static void
TestCustomIntervalsDriveSchedule()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 50});
  auto parser = std::make_shared<ModelParser>();
  CHECK(parser->Init(backend.get(), "mock", "").IsOk());
  LoadManagerConfig config;
  CustomLoadManager manager(backend, parser, config);
  CHECK(manager.InitManager().IsOk());
  // 2ms intervals -> ~500/sec; measure for 300ms -> ~150 requests
  CHECK(manager.InitCustomIntervals("2000\n").IsOk());
  std::this_thread::sleep_for(std::chrono::milliseconds(300));
  manager.StopWorkers();
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  auto records = manager.SwapRequestRecords();
  CHECK(records.size() > 75);
  CHECK(records.size() < 300);
  // inter-request gaps should cluster near the 2ms interval: check the
  // median gap lands in [1ms, 4ms] (scheduling jitter tolerated)
  std::vector<uint64_t> starts;
  for (const auto& r : records) {
    starts.push_back(r.start_ns);
  }
  std::sort(starts.begin(), starts.end());
  std::vector<uint64_t> gaps;
  for (size_t i = 1; i < starts.size(); ++i) {
    gaps.push_back(starts[i] - starts[i - 1]);
  }
  std::sort(gaps.begin(), gaps.end());
  uint64_t median_gap = gaps[gaps.size() / 2];
  CHECK(median_gap > 1000000ull);
  CHECK(median_gap < 4000000ull);
}

// -- metrics manager (reference test_metrics_manager.cc:52,96) --------------

static void
TestMetricsManagerParse()
{
  const char* body =
      "# HELP tpu_duty_cycle duty\n"
      "# TYPE tpu_duty_cycle gauge\n"
      "tpu_duty_cycle{chip=\"0\"} 87.5\n"
      "nv_gpu_utilization 0.4\n"
      "process_resident_memory_bytes 123456 1700000000000\n"
      "garbage line without value\n"
      "requests_total 42\n";
  auto snap = ParsePrometheusText(body);
  CHECK(snap.count("tpu_duty_cycle{chip=\"0\"}") == 1);
  CHECK(std::fabs(snap["tpu_duty_cycle{chip=\"0\"}"] - 87.5) < 1e-9);
  CHECK(std::fabs(snap["nv_gpu_utilization"] - 0.4) < 1e-9);
  // trailing timestamp is stripped, value kept
  CHECK(std::fabs(snap["process_resident_memory_bytes"] - 123456.0) < 1e-6);
  CHECK(snap.count("requests_total") == 1);
  // relevance filter: nv_/tpu_/process_ prefixes + utilization/memory/
  // power/duty names are kept, plain counters are not
  CHECK(IsRelevantMetric("nv_gpu_utilization"));
  CHECK(IsRelevantMetric("tpu_duty_cycle{chip=\"0\"}"));
  CHECK(IsRelevantMetric("process_resident_memory_bytes"));
  CHECK(IsRelevantMetric("hbm_memory_used"));
  CHECK(!IsRelevantMetric("requests_total"));
}

static void
TestMetricsManagerScrapesRealEndpoint()
{
  // a minimal /metrics HTTP server on a loopback socket: two scrapes
  // see different gauge values, the measurement average must combine
  // them (reference test_metrics_manager.cc polling behavior)
  int listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  CHECK(listen_fd >= 0);
  sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  CHECK(bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) == 0);
  CHECK(listen(listen_fd, 8) == 0);
  socklen_t alen = sizeof(addr);
  CHECK(getsockname(listen_fd, (sockaddr*)&addr, &alen) == 0);
  int port = ntohs(addr.sin_port);
  std::atomic<bool> server_exit{false};
  std::atomic<int> served{0};
  std::thread server([&]() {
    while (!server_exit.load()) {
      int fd = accept(listen_fd, nullptr, nullptr);
      if (fd < 0) {
        break;
      }
      char buf[2048];
      (void)!read(fd, buf, sizeof(buf));
      double util = (served.load() == 0) ? 10.0 : 30.0;
      char body[256];
      snprintf(
          body, sizeof(body),
          "tpu_duty_cycle %.1f\nrequests_total 7\n", util);
      char resp[512];
      int n = snprintf(
          resp, sizeof(resp),
          "HTTP/1.1 200 OK\r\nContent-Type: text/plain\r\n"
          "Content-Length: %zu\r\nConnection: close\r\n\r\n%s",
          strlen(body), body);
      (void)!write(fd, resp, n);
      close(fd);
      served++;
    }
  });
  {
    MetricsManager metrics(
        "127.0.0.1:" + std::to_string(port) + "/metrics", 50);
    CHECK(metrics.Start().IsOk());
    // wait until a background scrape has actually been FOLDED into the
    // accumulator (the served counter alone races the scraper thread's
    // parse+merge)
    auto avg = metrics.MeasurementAverages();
    for (int i = 0; i < 120; ++i) {
      avg = metrics.MeasurementAverages();
      if (avg.count("tpu_duty_cycle") && avg["tpu_duty_cycle"] > 10.0) {
        break;
      }
      std::this_thread::sleep_for(std::chrono::milliseconds(25));
    }
    CHECK(avg.count("tpu_duty_cycle") == 1);
    // average of 10 (startup scrape) and >=1 folded poll at 30
    CHECK(avg["tpu_duty_cycle"] > 10.0);
    CHECK(avg["tpu_duty_cycle"] <= 30.0);
    CHECK(served.load() >= 2);
    // irrelevant counters are filtered out of the accumulator
    CHECK(avg.count("requests_total") == 0);
    // a new measurement discards history
    metrics.StartNewMeasurement();
    for (int i = 0; i < 40; ++i) {
      std::this_thread::sleep_for(std::chrono::milliseconds(25));
      if (metrics.MeasurementAverages().count("tpu_duty_cycle")) {
        break;
      }
    }
    auto avg2 = metrics.MeasurementAverages();
    if (avg2.count("tpu_duty_cycle")) {
      CHECK(std::fabs(avg2["tpu_duty_cycle"] - 30.0) < 1e-9);
    }
    metrics.Stop();
  }
  server_exit = true;
  // unblock accept()
  int poke = socket(AF_INET, SOCK_STREAM, 0);
  connect(poke, (sockaddr*)&addr, sizeof(addr));
  close(poke);
  server.join();
  close(listen_fd);
  // failure path: nothing listening -> Start fails fast
  MetricsManager dead("127.0.0.1:1/metrics", 50);
  CHECK(!dead.Start().IsOk());
}

// -- count-window measurement mode + overhead accounting --------------------

static void
TestProfilerCountWindowsWithMock()
{
  auto backend = std::make_shared<MockClientBackend>(
      MockClientBackend::Config{.response_delay_us = 500});
  PerfAnalyzerParameters params;
  params.model_name = "mock";
  params.count_windows = true;  // reference --measurement-mode count
  params.measurement_request_count = 30;
  params.measurement_window_ms = 2000;  // backstop only
  params.max_trials = 6;
  params.stability_threshold_pct = 80.0;
  PerfAnalyzer analyzer(params);
  CHECK(analyzer.CreateAnalyzerObjects(backend).IsOk());
  CHECK(analyzer.Profile().IsOk());
  CHECK(analyzer.Results().size() == 1);
  const auto& status = analyzer.Results()[0];
  // each merged window waited for >=30 completions
  CHECK(status.client_stats.request_count >= 30);
  CHECK(status.client_stats.infer_per_sec > 0);
  // concurrency-1 sync workers over a 500us mock: most wall-time is
  // inside requests, so client overhead must be small
  CHECK(status.client_stats.overhead_pct >= 0.0);
  CHECK(status.client_stats.overhead_pct <= 100.0);
}

// -- report writer (reference test_report_writer.cc) ------------------------

static void
TestReportWriterCsv()
{
  PerfStatus status;
  status.concurrency = 2;
  status.client_stats.infer_per_sec = 1234.5;
  status.client_stats.avg_latency_ns = 800000;
  status.client_stats.p50_ns = 700000;
  status.client_stats.p90_ns = 880000;
  status.client_stats.p95_ns = 920000;
  status.client_stats.p99_ns = 1000000;
  status.server_stats.success_count = 10;
  status.server_stats.queue_ns = 410000;
  status.server_stats.compute_infer_ns = 2570000;
  std::string csv = ReportWriter::GenerateCsv({status}, true);
  CHECK(csv.find("Concurrency,Inferences/Second") == 0);
  CHECK(csv.find("2,1234.5,0,") != std::string::npos);
  CHECK(csv.find(",41,") != std::string::npos);   // queue usec
  CHECK(csv.find(",257,") != std::string::npos);  // compute infer usec
  CHECK(csv.find(",700,880,920,1000") != std::string::npos);
}

int
main()
{
  TestCliDefaults();
  TestCliMissingModel();
  TestCliRanges();
  TestCliSslShapeAndDataOptions();
  TestShapeOverrideAndDataDirectory();
  TestSequenceIdAllocation();
  TestCliBackHalf();
  TestScheduleDistribution();
  TestSummarizeRecords();
  TestModelParser();
  TestDataLoader();
  TestSequenceManager();
  TestConcurrencyManagerAgainstMock();
  TestConcurrencyManagerFailuresSurface();
  TestRequestRateManagerAgainstMock();
  TestSequencesThroughManager();
  TestProfilerEndToEndWithMock();
  TestDetermineStability();
  TestCustomIntervalParsing();
  TestCustomIntervalsDriveSchedule();
  TestMetricsManagerParse();
  TestMetricsManagerScrapesRealEndpoint();
  TestProfilerCountWindowsWithMock();
  TestReportWriterCsv();
  printf("%d checks, %d failures\n", checks, failures);
  return failures == 0 ? 0 : 1;
}
