// Request tensor data: synthetic (random/zero) or user-supplied JSON
// (reference data_loader.{h,cc}:71-97).

#pragma once

#include <map>
#include <random>
#include <string>
#include <vector>

#include "model_parser.h"

namespace pa {

class DataLoader {
 public:
  // Generate synthetic data for every model input: `streams` independent
  // data streams of `steps` request payloads each (sequence models walk a
  // stream across requests).
  tc::Error GenerateData(
      const std::vector<ModelTensor>& inputs, bool zero_data,
      size_t streams = 1, size_t steps = 1, int batch_size = 1,
      uint32_t seed = 17);

  // Load user data from a JSON document of the reference's input-data
  // format: {"data": [{"INPUT0": [..], ...}, ...]} — one entry per step.
  tc::Error ReadDataFromJson(
      const std::vector<ModelTensor>& inputs, const std::string& json_text,
      int batch_size = 1);

  // Load raw little-endian tensor bytes from <dir>/<INPUT_NAME> for
  // every model input, one data stream of one step (reference
  // --data-directory file layout).  File size must match the input's
  // byte size (batch dim included).
  tc::Error ReadDataFromDir(
      const std::vector<ModelTensor>& inputs, const std::string& dir,
      int batch_size = 1);

  size_t StreamCount() const { return streams_; }
  size_t StepCount() const { return steps_; }

  // raw payload for (stream, step, input)
  tc::Error GetInputData(
      const std::string& input_name, size_t stream, size_t step,
      const std::vector<uint8_t>** data) const;

 private:
  size_t streams_ = 0;
  size_t steps_ = 0;
  // key: input name + ":" + stream + ":" + step
  std::map<std::string, std::vector<uint8_t>> data_;
};

}  // namespace pa
