#include "inference_profiler.h"

#include <algorithm>
#include <cmath>
#include <thread>

#include "metrics_manager.h"
#include "tjson.h"

namespace pa {

namespace {

uint64_t
Percentile(std::vector<uint64_t>& sorted, double pct)
{
  if (sorted.empty()) {
    return 0;
  }
  size_t idx = (size_t)std::ceil(pct / 100.0 * sorted.size());
  if (idx > 0) {
    --idx;
  }
  if (idx >= sorted.size()) {
    idx = sorted.size() - 1;
  }
  return sorted[idx];
}

}  // namespace

ClientSideStats
InferenceProfiler::SummarizeRecords(
    const std::vector<RequestRecord>& records, uint64_t window_ns,
    size_t percentile)
{
  ClientSideStats stats;
  std::vector<uint64_t> latencies;
  uint64_t total = 0;
  for (const auto& r : records) {
    if (!r.success) {
      stats.failed_request_count++;
      continue;
    }
    if (r.delayed) {
      stats.delayed_request_count++;
    }
    uint64_t lat = r.end_ns - r.start_ns;
    latencies.push_back(lat);
    total += lat;
    stats.request_count++;
    stats.response_count += (r.response_count > 0) ? r.response_count : 1;
  }
  if (stats.request_count == 0) {
    return stats;
  }
  std::sort(latencies.begin(), latencies.end());
  stats.avg_latency_ns = total / stats.request_count;
  stats.p50_ns = Percentile(latencies, 50);
  stats.p90_ns = Percentile(latencies, 90);
  stats.p95_ns = Percentile(latencies, 95);
  stats.p99_ns = Percentile(latencies, 99);
  stats.stability_latency_ns =
      (percentile > 0) ? Percentile(latencies, (double)percentile)
                       : stats.avg_latency_ns;
  double mean = (double)stats.avg_latency_ns;
  double var = 0;
  for (uint64_t lat : latencies) {
    var += ((double)lat - mean) * ((double)lat - mean);
  }
  stats.std_ns = (uint64_t)std::sqrt(var / (double)latencies.size());
  if (window_ns > 0) {
    stats.infer_per_sec =
        (double)stats.request_count / ((double)window_ns / 1e9);
  }
  return stats;
}

bool
InferenceProfiler::DetermineStability(
    const std::vector<ClientSideStats>& windows, double threshold_pct,
    size_t window_count)
{
  if (windows.size() < window_count || window_count == 0) {
    return false;
  }
  const auto& last = windows[windows.size() - 1];
  for (size_t i = windows.size() - window_count; i < windows.size(); ++i) {
    const auto& w = windows[i];
    double tput_dev = std::fabs(w.infer_per_sec - last.infer_per_sec) /
                      (last.infer_per_sec > 0 ? last.infer_per_sec : 1.0);
    double lat_dev =
        std::fabs(
            (double)w.stability_latency_ns -
            (double)last.stability_latency_ns) /
        (last.stability_latency_ns > 0 ? (double)last.stability_latency_ns
                                       : 1.0);
    if (tput_dev > threshold_pct / 100.0 ||
        lat_dev > threshold_pct / 100.0) {
      return false;
    }
  }
  return true;
}

tc::Error
InferenceProfiler::QueryServerStats(
    ServerSideStats* stats, const std::string& model_name)
{
  *stats = ServerSideStats();
  std::string stats_json;
  tc::Error err = backend_->ModelStatistics(&stats_json, model_name);
  if (!err.IsOk()) {
    return err;
  }
  std::string parse_err;
  auto doc = tc::json::Parse(stats_json, &parse_err);
  if (doc == nullptr) {
    return tc::Error("failed to parse server statistics: " + parse_err);
  }
  auto model_stats = doc->Get("model_stats");
  if (model_stats == nullptr || model_stats->Size() == 0) {
    return tc::Error("no model_stats in server statistics");
  }
  auto entry = model_stats->At(0);
  auto get_u64 = [](const tc::json::ValuePtr& v, const char* key) {
    auto f = v ? v->Get(key) : nullptr;
    return f ? (uint64_t)f->AsInt() : 0ull;
  };
  stats->inference_count = get_u64(entry, "inference_count");
  stats->execution_count = get_u64(entry, "execution_count");
  auto infer_stats = entry->Get("inference_stats");
  if (infer_stats != nullptr) {
    auto dur = [&](const char* key) {
      auto d = infer_stats->Get(key);
      return d ? get_u64(d, "ns") : 0ull;
    };
    stats->queue_ns = dur("queue");
    stats->compute_input_ns = dur("compute_input");
    stats->compute_infer_ns = dur("compute_infer");
    stats->compute_output_ns = dur("compute_output");
    auto success = infer_stats->Get("success");
    stats->success_count = success ? get_u64(success, "count") : 0;
  }
  return tc::Error::Success;
}

tc::Error
InferenceProfiler::ProfileCurrentLevel(PerfStatus* status)
{
  std::vector<ClientSideStats> windows;

  // warmup: let the level issue-and-discard requests before measuring
  // (reference --warmup-request-count)
  if (config_.warmup_request_count > 0) {
    size_t warmed = 0;
    // stall-based deadline, reset on progress: the first request may sit
    // in a long server-side compile (XLA warms per shape), which must
    // not push measurement windows into the compile
    uint64_t last_progress = NowNs();
    manager_->GetAndResetNumSentRequests();
    while (warmed < config_.warmup_request_count && !early_exit.load() &&
           (NowNs() - last_progress) < 300ull * 1000000000ull) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      size_t progressed = manager_->GetAndResetNumSentRequests();
      if (progressed > 0) {
        warmed += progressed;
        last_progress = NowNs();
      }
      tc::Error err = manager_->CheckHealth();
      if (!err.IsOk()) {
        return err;
      }
    }
  }

  ServerSideStats server_begin;
  bool have_server_stats =
      QueryServerStats(&server_begin, parser_->ModelName()).IsOk();
  std::map<std::string, ServerSideStats> composing_begin;
  auto composing_models = parser_->ComposingModels();
  for (const auto& extra : config_.extra_composing_models) {
    composing_models.push_back(extra);
  }
  for (const auto& composing : composing_models) {
    ServerSideStats s;
    if (QueryServerStats(&s, composing).IsOk()) {
      composing_begin[composing] = s;
    }
  }
  if (metrics_ != nullptr) {
    metrics_->StartNewMeasurement();
  }
  sent_in_window_ = 0;
  manager_->GetAndResetNumSentRequests();
  // discard completions from before this level's windows (previous
  // level's tail, worker spin-up, warmup)
  manager_->SwapRequestRecords();

  for (size_t trial = 0;
       trial < config_.max_trials && !early_exit.load(); ++trial) {
    uint64_t window_start = NowNs();
    if (config_.count_windows) {
      // wait until the target request count completes (reference
      // count-window measurement mode)
      while (true) {
        std::this_thread::sleep_for(std::chrono::milliseconds(50));
        size_t n = 0;
        {
          auto err = manager_->CheckHealth();
          if (!err.IsOk()) {
            return err;
          }
        }
        // peek without swap: approximate by time accumulation; swap below
        if ((NowNs() - window_start) / 1000000 >=
            config_.measurement_window_ms) {
          break;
        }
        n = manager_->GetAndResetNumSentRequests();
        sent_in_window_ += n;
        if (sent_in_window_ >= config_.measurement_request_count) {
          break;
        }
        if (early_exit.load()) {
          break;
        }
      }
      sent_in_window_ = 0;
    } else {
      std::this_thread::sleep_for(
          std::chrono::milliseconds(config_.measurement_window_ms));
    }
    uint64_t window_ns = NowNs() - window_start;
    auto records = manager_->SwapRequestRecords();
    tc::Error err = manager_->CheckHealth();
    if (!err.IsOk()) {
      return err;
    }
    auto window_stats =
        SummarizeRecords(records, window_ns, config_.percentile);
    if (window_stats.request_count == 0) {
      continue;
    }
    // client overhead: share of worker wall-time spent outside requests
    // (reference overhead pct; meaningful in concurrency mode where
    // workers issue back-to-back)
    size_t workers = manager_->WorkerCount();
    if (workers > 0 && window_ns > 0) {
      uint64_t busy = 0;
      for (const auto& r : records) {
        busy += (r.end_ns > r.start_ns) ? r.end_ns - r.start_ns : 0;
      }
      double util = (double)busy / ((double)window_ns * (double)workers);
      window_stats.overhead_pct =
          100.0 * std::max(0.0, 1.0 - std::min(util, 1.0));
    }
    windows.push_back(window_stats);
    if (config_.verbose) {
      printf(
          "  window %zu: %.1f infer/sec, avg %.0f usec\n", windows.size(),
          window_stats.infer_per_sec,
          window_stats.avg_latency_ns / 1e3);
    }
    // stability: last 3 windows within threshold on throughput + the
    // stability latency metric (avg, or p<N> with --percentile)
    if (DetermineStability(windows, config_.stability_threshold_pct)) {
      status->stabilized = true;
      break;
    }
  }
  if (windows.empty()) {
    return tc::Error(
        "no requests completed within the measurement windows");
  }
  // merge the last up-to-3 windows (reference MergePerfStatusReports)
  size_t first = windows.size() >= 3 ? windows.size() - 3 : 0;
  ClientSideStats merged;
  double tput_sum = 0;
  uint64_t lat_sum = 0;
  uint64_t stab_sum = 0;
  double overhead_sum = 0;
  for (size_t i = first; i < windows.size(); ++i) {
    const auto& w = windows[i];
    merged.request_count += w.request_count;
    merged.delayed_request_count += w.delayed_request_count;
    merged.failed_request_count += w.failed_request_count;
    merged.response_count += w.response_count;
    tput_sum += w.infer_per_sec;
    lat_sum += w.avg_latency_ns;
    stab_sum += w.stability_latency_ns;
    overhead_sum += w.overhead_pct;
    merged.p50_ns = w.p50_ns;  // representative: last window percentiles
    merged.p90_ns = w.p90_ns;
    merged.p95_ns = w.p95_ns;
    merged.p99_ns = w.p99_ns;
    merged.std_ns = w.std_ns;
  }
  size_t n = windows.size() - first;
  merged.infer_per_sec = tput_sum / (double)n;
  merged.avg_latency_ns = lat_sum / n;
  merged.stability_latency_ns = stab_sum / n;
  merged.overhead_pct = overhead_sum / (double)n;
  status->client_stats = merged;

  auto delta_stats = [](const ServerSideStats& a, const ServerSideStats& b) {
    auto delta = [](uint64_t x, uint64_t y) { return y >= x ? y - x : 0; };
    ServerSideStats d;
    d.inference_count = delta(a.inference_count, b.inference_count);
    d.execution_count = delta(a.execution_count, b.execution_count);
    d.queue_ns = delta(a.queue_ns, b.queue_ns);
    d.compute_input_ns = delta(a.compute_input_ns, b.compute_input_ns);
    d.compute_infer_ns = delta(a.compute_infer_ns, b.compute_infer_ns);
    d.compute_output_ns = delta(a.compute_output_ns, b.compute_output_ns);
    d.success_count = delta(a.success_count, b.success_count);
    return d;
  };
  if (have_server_stats) {
    ServerSideStats server_end;
    if (QueryServerStats(&server_end, parser_->ModelName()).IsOk()) {
      status->server_stats = delta_stats(server_begin, server_end);
    }
  }
  // ensemble: per-composing-model deltas (reference ensemble stat merge)
  for (const auto& kv : composing_begin) {
    ServerSideStats end;
    if (QueryServerStats(&end, kv.first).IsOk()) {
      status->composing_server_stats[kv.first] =
          delta_stats(kv.second, end);
    }
  }
  if (metrics_ != nullptr) {
    status->metrics = metrics_->MeasurementAverages();
  }
  return tc::Error::Success;
}

}  // namespace pa
