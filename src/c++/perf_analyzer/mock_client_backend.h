// In-process fake server for unit tests: configurable delays/failures,
// async responses on detached threads, call accounting
// (reference client_backend/mock_client_backend.h:126-589 — the pattern
// that lets the whole load-generation stack be tested with no server).

#pragma once

#include <atomic>
#include <chrono>
#include <mutex>
#include <thread>
#include <vector>

#include "client_backend.h"

namespace pa {

class MockClientBackend : public ClientBackend {
 public:
  struct Config {
    uint64_t response_delay_us = 0;
    // per-call statuses consumed round-robin; empty = always success
    std::vector<bool> return_statuses;
    // stream responses per StreamInfer (last one is final) — models a
    // decoupled server when > 1
    size_t stream_responses_per_request = 1;
    // serialize sync Infer calls: latency then grows with offered
    // concurrency (a capacity-1 server), which latency-threshold /
    // binary-search tests need
    bool serialize_requests = false;
    std::string metadata_json =
        "{\"name\":\"mock\",\"inputs\":[{\"name\":\"INPUT0\","
        "\"datatype\":\"INT32\",\"shape\":[16]}],"
        "\"outputs\":[{\"name\":\"OUTPUT0\",\"datatype\":\"INT32\","
        "\"shape\":[16]}]}";
    std::string config_json =
        "{\"name\":\"mock\",\"max_batch_size\":8}";
  };

  MockClientBackend();
  explicit MockClientBackend(Config config);

  ~MockClientBackend() override
  {
    // drain detached async responders
    while (async_inflight_.load() > 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
  }

  tc::Error ServerReady(bool* ready) override
  {
    *ready = true;
    return tc::Error::Success;
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string&,
      const std::string&) override
  {
    *metadata_json = config_.metadata_json;
    return tc::Error::Success;
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string&,
      const std::string&) override
  {
    *config_json = config_.config_json;
    return tc::Error::Success;
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string&) override
  {
    size_t count = stats_.infer_calls + stats_.async_infer_calls;
    *stats_json =
        "{\"model_stats\":[{\"name\":\"mock\",\"inference_count\":" +
        std::to_string(count) +
        ",\"execution_count\":" + std::to_string(count) +
        ",\"inference_stats\":{\"success\":{\"count\":" +
        std::to_string(count) +
        ",\"ns\":1000},\"queue\":{\"count\":1,\"ns\":100},"
        "\"compute_input\":{\"count\":1,\"ns\":100},"
        "\"compute_infer\":{\"count\":1,\"ns\":700},"
        "\"compute_output\":{\"count\":1,\"ns\":100}}}]}";
    return tc::Error::Success;
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stats_.infer_calls++;
      RecordSequence(request);
    }
    if (config_.response_delay_us > 0) {
      if (config_.serialize_requests) {
        std::lock_guard<std::mutex> lk(serial_mu_);
        std::this_thread::sleep_for(
            std::chrono::microseconds(config_.response_delay_us));
      } else {
        std::this_thread::sleep_for(
            std::chrono::microseconds(config_.response_delay_us));
      }
    }
    result->status = NextStatus();
    result->request_id = request.request_id;
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback, const BackendInferRequest& request) override
  {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stats_.async_infer_calls++;
      RecordSequence(request);
    }
    async_inflight_++;
    uint64_t delay_us = config_.response_delay_us;
    auto status = NextStatus();
    std::string request_id = request.request_id;
    std::thread([this, callback, delay_us, status, request_id] {
      if (delay_us > 0) {
        std::this_thread::sleep_for(std::chrono::microseconds(delay_us));
      }
      BackendInferResult result;
      result.status = status;
      result.request_id = request_id;
      callback(std::move(result));
      async_inflight_--;
    }).detach();
    return tc::Error::Success;
  }

  tc::Error RegisterSystemSharedMemory(
      const std::string&, const std::string&, size_t) override
  {
    std::lock_guard<std::mutex> lk(mu_);
    stats_.shm_register_calls++;
    return tc::Error::Success;
  }

  tc::Error RegisterXlaSharedMemory(
      const std::string&, const std::string& raw_handle, size_t,
      int) override
  {
    std::lock_guard<std::mutex> lk(mu_);
    stats_.shm_register_calls++;
    last_xla_raw_handle_ = raw_handle;
    return tc::Error::Success;
  }
  tc::Error UnregisterXlaSharedMemory(const std::string&) override
  {
    return tc::Error::Success;
  }

  tc::Error StartStream(BackendCallback stream_callback) override
  {
    std::lock_guard<std::mutex> lk(mu_);
    stream_callback_ = std::move(stream_callback);
    return tc::Error::Success;
  }

  tc::Error StopStream() override
  {
    while (async_inflight_.load() > 0) {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
    }
    std::lock_guard<std::mutex> lk(mu_);
    stream_callback_ = nullptr;
    return tc::Error::Success;
  }

  tc::Error StreamInfer(const BackendInferRequest& request) override
  {
    BackendCallback cb;
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (stream_callback_ == nullptr) {
        return tc::Error("stream not started");
      }
      cb = stream_callback_;
      stats_.stream_infer_calls++;
      RecordSequence(request);
    }
    async_inflight_++;
    uint64_t delay_us = config_.response_delay_us;
    size_t responses = config_.stream_responses_per_request;
    auto status = NextStatus();
    std::string request_id = request.request_id;
    std::thread([this, cb, delay_us, responses, status, request_id] {
      for (size_t i = 0; i < responses; ++i) {
        if (delay_us > 0) {
          std::this_thread::sleep_for(std::chrono::microseconds(delay_us));
        }
        BackendInferResult result;
        result.status = status;
        result.request_id = request_id;
        result.final_response = (i + 1 == responses);
        cb(std::move(result));
      }
      async_inflight_--;
    }).detach();
    return tc::Error::Success;
  }

  tc::Error UpdateTraceSettings(
      const std::map<std::string, std::vector<std::string>>& settings)
      override
  {
    std::lock_guard<std::mutex> lk(mu_);
    last_trace_settings_ = settings;
    return tc::Error::Success;
  }

  std::map<std::string, std::vector<std::string>> LastTraceSettings()
  {
    std::lock_guard<std::mutex> lk(mu_);
    return last_trace_settings_;
  }

  std::string LastXlaRawHandle()
  {
    std::lock_guard<std::mutex> lk(mu_);
    return last_xla_raw_handle_;
  }

  BackendStats Stats() override
  {
    std::lock_guard<std::mutex> lk(mu_);
    return stats_;
  }

  // sequence correctness accounting: (id, start, end) per request
  struct SeqRecord {
    uint64_t id;
    bool start;
    bool end;
  };
  std::vector<SeqRecord> SequenceRecords()
  {
    std::lock_guard<std::mutex> lk(mu_);
    return seq_records_;
  }

 private:
  void RecordSequence(const BackendInferRequest& request)
  {
    if (request.sequence_id != 0) {
      seq_records_.push_back(
          {request.sequence_id, request.sequence_start,
           request.sequence_end});
    }
  }

  tc::Error NextStatus()
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (config_.return_statuses.empty()) {
      return tc::Error::Success;
    }
    bool ok = config_.return_statuses[status_cursor_ %
                                      config_.return_statuses.size()];
    status_cursor_++;
    return ok ? tc::Error::Success : tc::Error("mock failure");
  }

  Config config_;
  std::mutex mu_;
  std::mutex serial_mu_;
  BackendStats stats_;
  std::vector<SeqRecord> seq_records_;
  size_t status_cursor_ = 0;
  std::atomic<int> async_inflight_{0};
  BackendCallback stream_callback_;
  std::map<std::string, std::vector<std::string>> last_trace_settings_;
  std::string last_xla_raw_handle_;
};

inline MockClientBackend::MockClientBackend() : config_(Config()) {}
inline MockClientBackend::MockClientBackend(Config config)
    : config_(std::move(config))
{
}

}  // namespace pa
