#include "data_loader.h"

#include <cstring>

namespace pa {

namespace {

std::string
Key(const std::string& name, size_t stream, size_t step)
{
  return name + ":" + std::to_string(stream) + ":" + std::to_string(step);
}

void
FillRandom(std::vector<uint8_t>* data, std::mt19937* rng)
{
  std::uniform_int_distribution<int> dist(0, 255);
  for (auto& b : *data) {
    b = (uint8_t)dist(*rng);
  }
}

}  // namespace

tc::Error
DataLoader::GenerateData(
    const std::vector<ModelTensor>& inputs, bool zero_data, size_t streams,
    size_t steps, int batch_size, uint32_t seed)
{
  std::mt19937 rng(seed);
  streams_ = streams;
  steps_ = steps;
  for (const auto& input : inputs) {
    int64_t elem_size = ByteSize(input.datatype);
    int64_t count = ElementCount(input.shape);
    for (size_t stream = 0; stream < streams; ++stream) {
      for (size_t step = 0; step < steps; ++step) {
        std::vector<uint8_t> payload;
        if (elem_size < 0) {
          // BYTES: batch_size * count entries of 4-byte len + "pa_data"
          static const char kStr[] = "pa_data";
          uint32_t len = sizeof(kStr) - 1;
          for (int64_t i = 0; i < count * batch_size; ++i) {
            payload.insert(
                payload.end(), (uint8_t*)&len, (uint8_t*)&len + 4);
            payload.insert(
                payload.end(), (const uint8_t*)kStr,
                (const uint8_t*)kStr + len);
          }
        } else {
          payload.resize((size_t)(count * elem_size * batch_size));
          if (!zero_data) {
            FillRandom(&payload, &rng);
          }
        }
        data_[Key(input.name, stream, step)] = std::move(payload);
      }
    }
  }
  return tc::Error::Success;
}

tc::Error
DataLoader::ReadDataFromJson(
    const std::vector<ModelTensor>& inputs, const std::string& json_text,
    int batch_size)
{
  std::string parse_err;
  auto doc = tc::json::Parse(json_text, &parse_err);
  if (doc == nullptr) {
    return tc::Error("failed to parse input data JSON: " + parse_err);
  }
  auto data = doc->Get("data");
  if (data == nullptr) {
    return tc::Error("input data JSON missing 'data' array");
  }
  streams_ = 1;
  steps_ = data->Size();
  for (size_t step = 0; step < data->Size(); ++step) {
    auto entry = data->At(step);
    for (const auto& input : inputs) {
      auto values = entry->Get(input.name);
      if (values == nullptr) {
        return tc::Error(
            "missing data for input '" + input.name + "' at step " +
            std::to_string(step));
      }
      int64_t elem_size = ByteSize(input.datatype);
      std::vector<uint8_t> payload;
      // flatten nested arrays of numbers (or strings for BYTES)
      std::vector<tc::json::ValuePtr> stack{values};
      std::vector<tc::json::ValuePtr> flat;
      // breadth-preserving DFS flatten
      std::function<void(const tc::json::ValuePtr&)> walk =
          [&](const tc::json::ValuePtr& v) {
            if (v->type() == tc::json::Type::Array) {
              for (const auto& e : v->Elements()) {
                walk(e);
              }
            } else {
              flat.push_back(v);
            }
          };
      walk(values);
      for (const auto& v : flat) {
        if (elem_size < 0) {
          const std::string& s = v->AsString();
          uint32_t len = (uint32_t)s.size();
          payload.insert(
              payload.end(), (uint8_t*)&len, (uint8_t*)&len + 4);
          payload.insert(payload.end(), s.begin(), s.end());
        } else if (
            input.datatype == "FP32") {
          float f = (float)v->AsDouble();
          payload.insert(
              payload.end(), (uint8_t*)&f, (uint8_t*)&f + 4);
        } else if (input.datatype == "FP64") {
          double d = v->AsDouble();
          payload.insert(
              payload.end(), (uint8_t*)&d, (uint8_t*)&d + 8);
        } else if (
            input.datatype == "INT64" || input.datatype == "UINT64") {
          int64_t i = v->AsInt();
          payload.insert(
              payload.end(), (uint8_t*)&i, (uint8_t*)&i + 8);
        } else if (
            input.datatype == "INT32" || input.datatype == "UINT32") {
          int32_t i = (int32_t)v->AsInt();
          payload.insert(
              payload.end(), (uint8_t*)&i, (uint8_t*)&i + 4);
        } else if (
            input.datatype == "INT16" || input.datatype == "UINT16") {
          int16_t i = (int16_t)v->AsInt();
          payload.insert(
              payload.end(), (uint8_t*)&i, (uint8_t*)&i + 2);
        } else if (
            input.datatype == "INT8" || input.datatype == "UINT8" ||
            input.datatype == "BOOL") {
          int8_t i = (int8_t)v->AsInt();
          payload.push_back((uint8_t)i);
        } else {
          return tc::Error(
              "unsupported datatype in JSON data: " + input.datatype);
        }
      }
      data_[Key(input.name, 0, step)] = std::move(payload);
    }
  }
  return tc::Error::Success;
}

tc::Error
DataLoader::ReadDataFromDir(
    const std::vector<ModelTensor>& inputs, const std::string& dir,
    int batch_size)
{
  streams_ = 1;
  steps_ = 1;
  for (const auto& input : inputs) {
    const std::string path = dir + "/" + input.name;
    FILE* f = fopen(path.c_str(), "rb");
    if (f == nullptr) {
      return tc::Error(
          "--data-directory: cannot open '" + path + "' for input '" +
          input.name + "'");
    }
    fseek(f, 0, SEEK_END);
    long fsize = ftell(f);
    fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> payload((size_t)fsize);
    size_t got = fsize > 0 ? fread(payload.data(), 1, (size_t)fsize, f) : 0;
    fclose(f);
    if ((long)got != fsize) {
      return tc::Error("--data-directory: short read on '" + path + "'");
    }
    int64_t elem_size = ByteSize(input.datatype);
    if (elem_size > 0) {
      int64_t elems = batch_size;
      for (int64_t d : input.shape) {
        if (d < 0) {
          return tc::Error(
              "--data-directory: input '" + input.name +
              "' has a dynamic shape; fix it with --shape " + input.name +
              ":d1,d2,...");
        }
        elems *= d;
      }
      if ((int64_t)payload.size() != elems * elem_size) {
        return tc::Error(
            "--data-directory: '" + path + "' holds " +
            std::to_string(payload.size()) + " bytes but input '" +
            input.name + "' needs " + std::to_string(elems * elem_size));
      }
    }
    data_[Key(input.name, 0, 0)] = std::move(payload);
  }
  return tc::Error::Success;
}

tc::Error
DataLoader::GetInputData(
    const std::string& input_name, size_t stream, size_t step,
    const std::vector<uint8_t>** data) const
{
  auto it = data_.find(Key(input_name, stream, step));
  if (it == data_.end()) {
    return tc::Error(
        "no data for input '" + input_name + "' stream " +
        std::to_string(stream) + " step " + std::to_string(step));
  }
  *data = &it->second;
  return tc::Error::Success;
}

}  // namespace pa
