// Orchestrator: factory -> parser -> load manager -> profiler -> report
// (reference perf_analyzer.{h,cc}:70-425).

#pragma once

#include <memory>

#include "command_line_parser.h"
#include "concurrency_manager.h"
#include "inference_profiler.h"
#include "report_writer.h"
#include "request_rate_manager.h"

namespace pa {

class PerfAnalyzer {
 public:
  explicit PerfAnalyzer(const PerfAnalyzerParameters& params)
      : params_(params)
  {
  }

  // Build backend/parser/manager/profiler (reference
  // CreateAnalyzerObjects); a pre-built backend may be injected (tests).
  tc::Error CreateAnalyzerObjects(
      std::shared_ptr<ClientBackend> backend = nullptr);

  // Sweep the load range, profiling each level (reference Profile).
  tc::Error Profile();

  // Summaries to stdout (+ CSV when requested).
  tc::Error WriteReport();

  const std::vector<PerfStatus>& Results() const { return results_; }

 private:
  bool ConcurrencyMode() const
  {
    return params_.request_rate_start <= 0 &&
           params_.request_intervals_path.empty();
  }

  PerfAnalyzerParameters params_;
  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  std::unique_ptr<LoadManager> manager_;
  std::unique_ptr<InferenceProfiler> profiler_;
  std::vector<PerfStatus> results_;
};

}  // namespace pa
