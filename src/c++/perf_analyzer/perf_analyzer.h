// Orchestrator: factory -> parser -> load manager -> profiler -> report
// (reference perf_analyzer.{h,cc}:70-425).

#pragma once

#include <memory>

#include "command_line_parser.h"
#include "concurrency_manager.h"
#include "inference_profiler.h"
#include "metrics_manager.h"
#include "mpi_utils.h"
#include "report_writer.h"
#include "request_rate_manager.h"

namespace pa {

class PerfAnalyzer {
 public:
  explicit PerfAnalyzer(const PerfAnalyzerParameters& params)
      : params_(params)
  {
  }

  // Build backend/parser/manager/profiler (reference
  // CreateAnalyzerObjects); a pre-built backend may be injected (tests).
  tc::Error CreateAnalyzerObjects(
      std::shared_ptr<ClientBackend> backend = nullptr);

  // Sweep the load range, profiling each level (reference Profile).
  tc::Error Profile();

  // Summaries to stdout (+ CSV when requested).
  tc::Error WriteReport();

  const std::vector<PerfStatus>& Results() const { return results_; }

 private:
  bool ConcurrencyMode() const
  {
    return params_.request_rate_start <= 0 &&
           params_.request_intervals_path.empty();
  }

  tc::Error ProfileSweep();
  bool ExceedsLatencyThreshold(const PerfStatus& status) const;

  // Binary search for the highest load level whose latency stays under
  // --latency-threshold (reference inference_profiler.h:243-297): probe
  // both ends, then bisect until the bracket narrows to `step`.
  template <typename T>
  tc::Error BinarySearch(
      T start, T end, T step,
      const std::function<tc::Error(T, PerfStatus*)>& profile)
  {
    PerfStatus status;
    tc::Error err = profile(start, &status);
    if (!err.IsOk()) {
      return err;
    }
    if (ExceedsLatencyThreshold(status)) {
      return tc::Error::Success;  // minimum load already over threshold
    }
    err = profile(end, &status);
    if (!err.IsOk()) {
      return err;
    }
    if (!ExceedsLatencyThreshold(status)) {
      return tc::Error::Success;  // maximum load fits
    }
    T lo = start;
    T hi = end;
    while (hi - lo > step && !early_exit.load()) {
      T mid = lo + (hi - lo) / 2;
      err = profile(mid, &status);
      if (!err.IsOk()) {
        return err;
      }
      if (ExceedsLatencyThreshold(status)) {
        hi = mid;
      } else {
        lo = mid;
      }
    }
    return tc::Error::Success;
  }

  PerfAnalyzerParameters params_;
  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  std::unique_ptr<LoadManager> manager_;
  std::unique_ptr<InferenceProfiler> profiler_;
  std::shared_ptr<MetricsManager> metrics_;
  std::shared_ptr<MPIDriver> mpi_;
  std::vector<PerfStatus> results_;
};

}  // namespace pa
