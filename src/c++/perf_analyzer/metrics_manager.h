// Background Prometheus scraper: polls the server's /metrics endpoint on
// an interval thread and averages gauges over each measurement
// (reference metrics_manager.h:44-91 + the parse in
// triton_client_backend.cc:386-445; GPU gauges map to the TPU/process
// gauges tpuserver exports).

#pragma once

#include <atomic>
#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"

namespace pa {

// One scrape: metric name (labels folded in as name{label}) -> value.
using MetricsSnapshot = std::map<std::string, double>;

// Parse Prometheus text exposition format into a snapshot (exposed for
// unit tests).  Only gauge/counter sample lines are read; HELP/TYPE
// comments are skipped.
MetricsSnapshot ParsePrometheusText(const std::string& body);

// Accelerator/host gauges worth reporting (nv_*/tpu_*/process_* and
// utilization/duty/memory/power names).
bool IsRelevantMetric(const std::string& name);

class MetricsManager {
 public:
  // url: "host:port/path" or "http://host:port/path"
  MetricsManager(const std::string& url, uint64_t interval_ms)
      : url_(url), interval_ms_(interval_ms)
  {
  }

  ~MetricsManager() { Stop(); }

  // Spawn the scrape thread; first scrape happens immediately so short
  // measurements still see at least one sample.
  tc::Error Start();
  void Stop();

  // Begin a measurement: discard accumulated samples.
  void StartNewMeasurement();

  // Average of each metric over the samples since StartNewMeasurement.
  MetricsSnapshot MeasurementAverages();

  // Scrape once, synchronously (also used by the thread; public for
  // tests and for --collect-metrics validation at startup).
  tc::Error ScrapeOnce(MetricsSnapshot* out);

 private:
  void Loop();

  std::string url_;
  uint64_t interval_ms_;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool exit_ = false;
  // accumulated sums + counts since the last StartNewMeasurement
  std::map<std::string, std::pair<double, size_t>> acc_;
};

}  // namespace pa
