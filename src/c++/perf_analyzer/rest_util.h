// Minimal HTTP helpers shared by the non-Triton REST backends
// (TF-Serving, TorchServe) and the metrics scraper.

#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"

namespace pa {

// One keep-alive HTTP/1.1 connection: reconnects on demand, frames
// responses by Content-Length (or connection close as a fallback).
// Not thread-safe; pool instances per concurrent caller.
class RestClient {
 public:
  RestClient(const std::string& host, int port);
  ~RestClient();

  tc::Error Request(
      const std::string& method, const std::string& path,
      const std::string& body, const std::string& content_type,
      long* http_code, std::string* response_body);

 private:
  tc::Error Connect();
  void Close();

  std::string host_;
  int port_;
  int fd_ = -1;
};

// Mutex-guarded pool of RestClients for concurrent perf workers.
class RestClientPool {
 public:
  RestClientPool(const std::string& host, int port)
      : host_(host), port_(port)
  {
  }

  tc::Error Request(
      const std::string& method, const std::string& path,
      const std::string& body, const std::string& content_type,
      long* http_code, std::string* response_body);

 private:
  std::string host_;
  int port_;
  std::mutex mu_;
  std::vector<std::unique_ptr<RestClient>> idle_;
};

// Fixed-size dispatch pool so backend AsyncInfer stays non-blocking
// (request-rate schedules depend on issue not stalling).
class RestDispatchPool {
 public:
  explicit RestDispatchPool(int workers = 4);
  ~RestDispatchPool();

  void Enqueue(std::function<void()> job);

 private:
  void Worker();

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::function<void()>> queue_;
  std::vector<std::thread> workers_;
  bool exiting_ = false;
};

// One-shot request (Connection: close framing); used by the metrics
// scraper where a request a second doesn't warrant a pool.
tc::Error RestRequest(
    const std::string& host, int port, const std::string& method,
    const std::string& path, const std::string& body,
    const std::string& content_type, long* http_code,
    std::string* response_body);

// "host:port" (optional scheme/path) -> host, port (default_port when
// absent).
void SplitHostPort(
    const std::string& url, int default_port, std::string* host,
    int* port);

}  // namespace pa
