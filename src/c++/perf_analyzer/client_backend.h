// Backend abstraction: load managers issue requests through this neutral
// interface so the harness runs identically against a live server, an
// in-process one, or a mock (reference client_backend/client_backend.h:
// 250-620).

#pragma once

#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "common.h"  // tc::Error et al. from the client library
#include "grpc_client.h"  // tc::SslOptions
#include "http_client.h"  // tc::HttpSslOptions
#include "perf_utils.h"

namespace pa {

// Neutral request/response record used by the harness.
struct BackendInferRequest {
  std::string model_name;
  std::string model_version;
  std::string request_id;
  uint64_t sequence_id = 0;
  bool sequence_start = false;
  bool sequence_end = false;
  // name -> (datatype, shape, bytes) — bytes empty when shm-resident
  struct Input {
    std::string name;
    std::string datatype;
    std::vector<int64_t> shape;
    std::vector<uint8_t> data;
    std::string shm_region;
    size_t shm_byte_size = 0;
    size_t shm_offset = 0;
  };
  std::vector<Input> inputs;
  std::vector<std::string> requested_outputs;
  // streaming to a decoupled model: ask for the trailing empty response
  // marked triton_final_response so the stream end is detectable
  bool enable_empty_final_response = false;
};

struct BackendInferResult {
  tc::Error status;
  std::string request_id;
  // output name -> raw bytes (empty when delivered via shm)
  std::map<std::string, std::vector<uint8_t>> outputs;
  // streaming: false for intermediate decoupled responses
  bool final_response = true;
};

using BackendCallback = std::function<void(BackendInferResult&&)>;

// Statistics a backend can report about itself (mock uses this to expose
// call accounting to tests; reference mock_client_backend.h:126-589).
struct BackendStats {
  size_t infer_calls = 0;
  size_t async_infer_calls = 0;
  size_t stream_infer_calls = 0;
  size_t shm_register_calls = 0;
};

class ClientBackend {
 public:
  virtual ~ClientBackend() = default;

  virtual tc::Error ServerReady(bool* ready) = 0;
  virtual tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) = 0;
  virtual tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) = 0;
  virtual tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) = 0;

  virtual tc::Error Infer(
      BackendInferResult* result, const BackendInferRequest& request) = 0;
  virtual tc::Error AsyncInfer(
      BackendCallback callback, const BackendInferRequest& request) = 0;

  // Bidirectional-stream issuance (decoupled models; reference
  // client_backend.h:335-466 StartStream/AsyncStreamInfer).  The stream
  // callback fires once per response, with final_response marking
  // request completion.
  virtual tc::Error StartStream(BackendCallback stream_callback)
  {
    return tc::Error("streaming is not supported by this backend");
  }
  virtual tc::Error StopStream() { return tc::Error::Success; }
  virtual tc::Error StreamInfer(const BackendInferRequest& request)
  {
    return tc::Error("streaming is not supported by this backend");
  }

  // Forward trace settings to the server (reference
  // triton_client_backend.cc:447-509 trace push).
  virtual tc::Error UpdateTraceSettings(
      const std::map<std::string, std::vector<std::string>>& settings)
  {
    return tc::Error("trace settings are not supported by this backend");
  }

  virtual tc::Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size)
  {
    return tc::Error("shared memory not supported by this backend");
  }
  virtual tc::Error UnregisterSystemSharedMemory(const std::string& name)
  {
    return tc::Error::Success;
  }
  virtual tc::Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal)
  {
    return tc::Error("xla shared memory not supported by this backend");
  }
  virtual tc::Error UnregisterXlaSharedMemory(const std::string& name)
  {
    return tc::Error::Success;
  }

  virtual BackendStats Stats() { return BackendStats(); }
};

struct BackendFactoryConfig {
  BackendKind kind = BackendKind::TRITON_HTTP;
  std::string url = "localhost:8000";
  bool verbose = false;
  int concurrency = 16;  // async worker threads for the http backend
  // IN_PROCESS mode (tpuserver embedded via CPython; role of reference
  // --triton-server-directory for the C-API backend)
  std::string server_src;
  bool inproc_vision = false;
  // TLS (reference --ssl-grpc-*/--ssl-https-* option families)
  bool grpc_use_ssl = false;
  tc::SslOptions grpc_ssl;
  tc::HttpSslOptions http_ssl;
  // per-message gRPC compression: "" | gzip | deflate
  std::string grpc_compression;
  // TF-Serving signature (reference --model-signature-name)
  std::string model_signature_name = "serving_default";
  // TFSERVING kind + "-i grpc": speak gRPC PredictService (the wire the
  // reference backend measures) instead of the REST predict API
  bool tfserve_grpc = false;
};

class ClientBackendFactory {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config);
};

}  // namespace pa
