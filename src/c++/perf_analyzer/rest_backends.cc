// Non-Triton serving backends over REST: TensorFlow Serving and
// TorchServe (roles of reference client_backend/tensorflow_serving/ —
// gRPC PredictService there — and client_backend/torchserve/; both are
// "beta" backends in the reference with documented caveats,
// docs/benchmarking.md:136-218).  The native metadata of each server is
// adapted into the KServe-style JSON the ModelParser consumes, playing
// the role of the reference's ModelParser::InitTFServe/InitTorchServe.

#include <cmath>
#include <cstring>
#include <sstream>

#include "client_backend.h"
#include "grpc_channel.h"
#include "rest_util.h"
#include "tfserve_predict.pb.h"
#include "tjson.h"

namespace pa {

namespace {

// Append `count` elements of width `elem` from `src` as little-endian
// wire bytes (the v2 binary-tensor convention): a plain memcpy on LE
// hosts, a per-element byte swap on BE ones.
void
AppendLE(std::vector<uint8_t>& raw, const void* src, size_t elem,
         size_t count)
{
  static const uint16_t probe = 1;
  static const bool little =
      *reinterpret_cast<const uint8_t*>(&probe) == 1;
  const uint8_t* p = static_cast<const uint8_t*>(src);
  size_t off = raw.size();
  raw.resize(off + elem * count);
  if (little) {
    memcpy(raw.data() + off, p, elem * count);
    return;
  }
  for (size_t i = 0; i < count; ++i) {
    for (size_t b = 0; b < elem; ++b) {
      raw[off + i * elem + b] = p[i * elem + (elem - 1 - b)];
    }
  }
}

// -- JSON <-> raw tensor conversion -----------------------------------------

size_t
DtypeSize(const std::string& datatype)
{
  if (datatype == "FP64" || datatype == "INT64" || datatype == "UINT64") {
    return 8;
  }
  if (datatype == "FP32" || datatype == "INT32" || datatype == "UINT32") {
    return 4;
  }
  if (datatype == "FP16" || datatype == "BF16" || datatype == "INT16" ||
      datatype == "UINT16") {
    return 2;
  }
  return 1;  // BOOL/INT8/UINT8
}

// append one element at `index` of the raw little-endian buffer as JSON
void
AppendElement(
    const std::string& datatype, const uint8_t* data, size_t index,
    std::ostringstream& out)
{
  if (datatype == "FP32") {
    float v;
    memcpy(&v, data + index * 4, 4);
    out << (std::isfinite(v) ? v : 0.0f);
  } else if (datatype == "FP64") {
    double v;
    memcpy(&v, data + index * 8, 8);
    out << (std::isfinite(v) ? v : 0.0);
  } else if (datatype == "INT64") {
    int64_t v;
    memcpy(&v, data + index * 8, 8);
    out << v;
  } else if (datatype == "INT32") {
    int32_t v;
    memcpy(&v, data + index * 4, 4);
    out << v;
  } else if (datatype == "INT16") {
    int16_t v;
    memcpy(&v, data + index * 2, 2);
    out << v;
  } else if (datatype == "UINT8") {
    out << (unsigned)data[index];
  } else if (datatype == "INT8") {
    out << (int)(int8_t)data[index];
  } else if (datatype == "BOOL") {
    out << (data[index] ? "true" : "false");
  } else {
    out << 0;  // unsupported dtypes send zeros
  }
}

// nested JSON array for shape[dim:] over the raw buffer
void
BuildNested(
    const std::string& datatype, const uint8_t* data,
    const std::vector<int64_t>& shape, size_t dim, size_t* cursor,
    std::ostringstream& out)
{
  if (dim == shape.size()) {
    AppendElement(datatype, data, (*cursor)++, out);
    return;
  }
  out << "[";
  for (int64_t i = 0; i < shape[dim]; ++i) {
    if (i) {
      out << ", ";
    }
    BuildNested(datatype, data, shape, dim + 1, cursor, out);
  }
  out << "]";
}

// flatten a parsed JSON value (nested arrays of numbers) into raw bytes
void
FlattenTo(
    const tc::json::ValuePtr& value, const std::string& datatype,
    std::vector<uint8_t>* out)
{
  if (value == nullptr) {
    return;
  }
  if (value->type() == tc::json::Type::Array) {
    for (const auto& e : value->Elements()) {
      FlattenTo(e, datatype, out);
    }
    return;
  }
  double d = value->type() == tc::json::Type::Bool
                 ? (value->AsBool() ? 1.0 : 0.0)
                 : value->AsDouble();
  size_t pos = out->size();
  if (datatype == "FP64") {
    out->resize(pos + 8);
    memcpy(out->data() + pos, &d, 8);
  } else if (datatype == "INT64") {
    int64_t v = (int64_t)d;
    out->resize(pos + 8);
    memcpy(out->data() + pos, &v, 8);
  } else if (datatype == "INT32") {
    int32_t v = (int32_t)d;
    out->resize(pos + 4);
    memcpy(out->data() + pos, &v, 4);
  } else if (datatype == "UINT8" || datatype == "INT8" ||
             datatype == "BOOL") {
    out->push_back((uint8_t)(int64_t)d);
  } else {  // FP32 default
    float v = (float)d;
    out->resize(pos + 4);
    memcpy(out->data() + pos, &v, 4);
  }
}

std::string
TfDtypeToKserve(const std::string& dt)
{
  if (dt == "DT_FLOAT") {
    return "FP32";
  }
  if (dt == "DT_DOUBLE") {
    return "FP64";
  }
  if (dt == "DT_INT32") {
    return "INT32";
  }
  if (dt == "DT_INT64") {
    return "INT64";
  }
  if (dt == "DT_INT8") {
    return "INT8";
  }
  if (dt == "DT_UINT8") {
    return "UINT8";
  }
  if (dt == "DT_BOOL") {
    return "BOOL";
  }
  if (dt == "DT_HALF") {
    return "FP16";
  }
  if (dt == "DT_STRING") {
    return "BYTES";
  }
  return "FP32";
}

int64_t
JsonNum(const tc::json::ValuePtr& v)
{
  if (v == nullptr) {
    return 0;
  }
  if (v->type() == tc::json::Type::String) {
    return strtoll(v->AsString().c_str(), nullptr, 10);
  }
  return v->AsInt();
}

}  // namespace

// ============================================================================
// TensorFlow Serving (REST predict API; the reference backend speaks the
// gRPC PredictService — client_backend/tensorflow_serving/)
// ============================================================================

class TFServeBackend : public ClientBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    auto* b = new TFServeBackend();
    SplitHostPort(config.url, 8501, &b->host_, &b->port_);
    b->pool_.reset(new RestClientPool(b->host_, b->port_));
    b->dispatch_.reset(new RestDispatchPool(config.concurrency));
    b->signature_name_ = config.model_signature_name;
    backend->reset(b);
    return tc::Error::Success;
  }

  tc::Error ServerReady(bool* ready) override
  {
    // TF-Serving has no global health endpoint; model state is checked
    // in ModelMetadata (reference notes the same caveat)
    *ready = true;
    return tc::Error::Success;
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) override
  {
    long code;
    std::string body;
    std::string path = "/v1/models/" + model_name +
                       (model_version.empty()
                            ? ""
                            : "/versions/" + model_version) +
                       "/metadata";
    tc::Error err = pool_->Request(
        "GET", path, "", "", &code, &body);
    if (!err.IsOk()) {
      return err;
    }
    if (code != 200) {
      return tc::Error(
          "tfserving metadata failed: HTTP " + std::to_string(code) +
          ": " + body);
    }
    // {"metadata": {"signature_def": {"signature_def": {"serving_default":
    //   {"inputs": {name: {"dtype": "DT_FLOAT", "tensor_shape":
    //     {"dim": [{"size": "-1"}, ...]}}}, "outputs": {...}}}}}
    std::string parse_err;
    auto doc = tc::json::Parse(body, &parse_err);
    if (doc == nullptr) {
      return tc::Error("tfserving metadata parse: " + parse_err);
    }
    auto sig = Walk(
        doc, {"metadata", "signature_def", "signature_def",
              signature_name_});
    if (sig == nullptr) {
      return tc::Error(
          "tfserving metadata has no " + signature_name_ +
          " signature (--model-signature-name)");
    }
    std::ostringstream out;
    out << "{\"name\": \"" << model_name << "\", \"inputs\": [";
    AppendTensors(sig->Get("inputs"), out);
    out << "], \"outputs\": [";
    AppendTensors(sig->Get("outputs"), out);
    out << "]}";
    *metadata_json = out.str();
    // remember input dtypes for predict conversion
    return tc::Error::Success;
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) override
  {
    // TF REST carries the batch dim inside tensor shapes; expose a
    // non-batching config and let shapes speak for themselves
    *config_json = "{\"name\": \"" + model_name +
                   "\", \"platform\": \"tensorflow_serving\", "
                   "\"max_batch_size\": 0}";
    return tc::Error::Success;
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) override
  {
    return tc::Error("tfserving reports no per-model statistics");
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    std::ostringstream body;
    body << "{";
    if (signature_name_ != "serving_default") {
      body << "\"signature_name\": \"" << signature_name_ << "\", ";
    }
    body << "\"inputs\": {";
    bool first = true;
    for (const auto& input : request.inputs) {
      if (!input.shm_region.empty()) {
        return tc::Error(
            "tfserving backend does not support shared memory");
      }
      if (!first) {
        body << ", ";
      }
      first = false;
      body << "\"" << input.name << "\": ";
      size_t cursor = 0;
      std::ostringstream nested;
      BuildNested(
          input.datatype, input.data.data(), input.shape, 0, &cursor,
          nested);
      body << nested.str();
    }
    body << "}}";
    long code;
    std::string response;
    tc::Error err = pool_->Request(
        "POST", "/v1/models/" + request.model_name + ":predict",
        body.str(), "application/json", &code, &response);
    if (!err.IsOk()) {
      result->status = err;
      return err;
    }
    if (code != 200) {
      result->status = tc::Error(
          "tfserving predict failed: HTTP " + std::to_string(code) +
          ": " + response);
      return result->status;
    }
    std::string parse_err;
    auto doc = tc::json::Parse(response, &parse_err);
    if (doc == nullptr) {
      result->status =
          tc::Error("tfserving response parse: " + parse_err);
      return result->status;
    }
    auto outputs = doc->Get("outputs");
    result->outputs.clear();
    result->request_id = request.request_id;
    result->status = tc::Error::Success;
    if (outputs != nullptr &&
        outputs->type() == tc::json::Type::Object) {
      for (const auto& kv : outputs->Members()) {
        std::vector<uint8_t> raw;
        FlattenTo(kv.second, "FP32", &raw);
        result->outputs[kv.first] = std::move(raw);
      }
    } else if (outputs != nullptr) {  // single unnamed output
      std::vector<uint8_t> raw;
      FlattenTo(outputs, "FP32", &raw);
      result->outputs["output"] = std::move(raw);
    }
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback,
      const BackendInferRequest& request) override
  {
    // non-blocking issue: rate schedules must not stall on slow servers
    BackendInferRequest copy = request;
    dispatch_->Enqueue([this, callback, copy = std::move(copy)]() {
      BackendInferResult result;
      Infer(&result, copy);
      callback(std::move(result));
    });
    return tc::Error::Success;
  }

 private:
  static tc::json::ValuePtr Walk(
      const tc::json::ValuePtr& root,
      const std::vector<std::string>& path)
  {
    tc::json::ValuePtr cur = root;
    for (const auto& key : path) {
      if (cur == nullptr) {
        return nullptr;
      }
      cur = cur->Get(key);
    }
    return cur;
  }

  static void AppendTensors(
      const tc::json::ValuePtr& tensors, std::ostringstream& out)
  {
    if (tensors == nullptr) {
      return;
    }
    bool first = true;
    for (const auto& kv : tensors->Members()) {
      if (!first) {
        out << ", ";
      }
      first = false;
      const auto& info = kv.second;
      std::string dtype = "FP32";
      if (info->Has("dtype")) {
        dtype = TfDtypeToKserve(info->Get("dtype")->AsString());
      }
      out << "{\"name\": \"" << kv.first << "\", \"datatype\": \""
          << dtype << "\", \"shape\": [";
      auto ts = info->Get("tensor_shape");
      auto dims = ts != nullptr ? ts->Get("dim") : nullptr;
      bool fd = true;
      if (dims != nullptr) {
        for (const auto& d : dims->Elements()) {
          if (!fd) {
            out << ", ";
          }
          fd = false;
          int64_t size = JsonNum(d->Get("size"));
          // TF uses -1 for the batch dim; the harness needs concrete
          // shapes, so unknown dims default to 1
          out << (size < 0 ? 1 : size);
        }
      }
      out << "]}";
    }
  }

 protected:
  std::string host_;
  int port_ = 8501;
  std::string signature_name_ = "serving_default";
  std::unique_ptr<RestClientPool> pool_;
  std::unique_ptr<RestDispatchPool> dispatch_;
};

// ============================================================================
// TorchServe (HTTP inference API; reference client_backend/torchserve/ —
// file-upload style input, JSON user data required)
// ============================================================================

class TorchServeBackend : public ClientBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    auto* b = new TorchServeBackend();
    SplitHostPort(config.url, 8080, &b->host_, &b->port_);
    b->pool_.reset(new RestClientPool(b->host_, b->port_));
    b->dispatch_.reset(new RestDispatchPool(config.concurrency));
    backend->reset(b);
    return tc::Error::Success;
  }

  tc::Error ServerReady(bool* ready) override
  {
    long code;
    std::string body;
    tc::Error err = pool_->Request(
        "GET", "/ping", "", "", &code, &body);
    *ready = err.IsOk() && code == 200;
    return tc::Error::Success;
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) override
  {
    // TorchServe exposes no tensor metadata; fabricate the single
    // BYTES input the reference uses (TORCHSERVE_INPUT, fed from
    // --input-data; reference model_parser.h:89-115 InitTorchServe)
    *metadata_json =
        "{\"name\": \"" + model_name +
        "\", \"inputs\": [{\"name\": \"TORCHSERVE_INPUT\", "
        "\"datatype\": \"BYTES\", \"shape\": [1]}], "
        "\"outputs\": [{\"name\": \"OUTPUT\", \"datatype\": \"BYTES\", "
        "\"shape\": [1]}]}";
    return tc::Error::Success;
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) override
  {
    *config_json = "{\"name\": \"" + model_name +
                   "\", \"platform\": \"torchserve\", "
                   "\"max_batch_size\": 0}";
    return tc::Error::Success;
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) override
  {
    return tc::Error("torchserve reports no per-model statistics");
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    if (request.inputs.empty()) {
      result->status = tc::Error("torchserve requires input data");
      return result->status;
    }
    const auto& input = request.inputs[0];
    // BYTES tensors carry a 4-byte length prefix per element; the
    // upload body is the first element's raw content
    std::string body;
    if (input.datatype == "BYTES" && input.data.size() >= 4) {
      uint32_t len;
      memcpy(&len, input.data.data(), 4);
      size_t n = std::min((size_t)len, input.data.size() - 4);
      body.assign((const char*)input.data.data() + 4, n);
    } else {
      body.assign(
          (const char*)input.data.data(), input.data.size());
    }
    long code;
    std::string response;
    tc::Error err = pool_->Request(
        "POST", "/predictions/" + request.model_name, body,
        "application/octet-stream", &code, &response);
    if (!err.IsOk()) {
      result->status = err;
      return err;
    }
    if (code != 200) {
      result->status = tc::Error(
          "torchserve predict failed: HTTP " + std::to_string(code) +
          ": " + response);
      return result->status;
    }
    result->request_id = request.request_id;
    result->status = tc::Error::Success;
    result->outputs.clear();
    result->outputs["OUTPUT"].assign(
        response.begin(), response.end());
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback,
      const BackendInferRequest& request) override
  {
    BackendInferRequest copy = request;
    dispatch_->Enqueue([this, callback, copy = std::move(copy)]() {
      BackendInferResult result;
      Infer(&result, copy);
      callback(std::move(result));
    });
    return tc::Error::Success;
  }

 private:
  std::string host_;
  int port_ = 8080;
  std::unique_ptr<RestClientPool> pool_;
  std::unique_ptr<RestDispatchPool> dispatch_;
};

// ============================================================================
// TensorFlow Serving over gRPC PredictService — the wire the reference
// backend measures (client_backend/tensorflow_serving/
// tfserve_grpc_client.cc).  Predict rides this framework's h2 gRPC
// channel with a wire-compatible proto subset (proto/
// tfserve_predict.proto); model METADATA still comes from the REST API
// (tensorflow_model_server serves both; the gRPC GetModelMetadata reply
// needs the full meta_graph proto tree for no measurement benefit).
// Port convention: the url names the gRPC port (default 8500), REST
// metadata is fetched from port+1 (the server's customary 8500/8501
// pairing).
// ============================================================================

namespace {

// KServe datatype -> tensorflow.DataType enum value
int
TfDtypeEnum(const std::string& datatype)
{
  if (datatype == "FP32") {
    return 1;  // DT_FLOAT
  }
  if (datatype == "FP64") {
    return 2;  // DT_DOUBLE
  }
  if (datatype == "INT32") {
    return 3;
  }
  if (datatype == "UINT8") {
    return 4;
  }
  if (datatype == "INT16") {
    return 5;
  }
  if (datatype == "INT8") {
    return 6;
  }
  if (datatype == "BYTES") {
    return 7;  // DT_STRING
  }
  if (datatype == "INT64") {
    return 9;
  }
  if (datatype == "BOOL") {
    return 10;
  }
  if (datatype == "FP16") {
    return 19;  // DT_HALF
  }
  if (datatype == "UINT32") {
    return 22;
  }
  if (datatype == "UINT64") {
    return 23;
  }
  return -1;  // unknown: callers error loudly (a silent DT_FLOAT label
              // on differently-sized elements would corrupt the wire)
}

}  // namespace

class TFServeGrpcBackend : public TFServeBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    auto* b = new TFServeGrpcBackend();
    SplitHostPort(config.url, 8500, &b->host_, &b->port_);
    tc::TlsOptions tls;
    if (config.grpc_use_ssl) {
      tls.enabled = true;
      tls.ca_file = config.grpc_ssl.root_certificates;
      tls.cert_file = config.grpc_ssl.certificate_chain;
      tls.key_file = config.grpc_ssl.private_key;
      tls.alpn = {"h2"};
    }
    tc::Error err = tc::h2::GrpcChannel::Create(
        &b->channel_, b->host_ + ":" + std::to_string(b->port_),
        config.verbose, tls);
    if (!err.IsOk()) {
      delete b;
      return err;
    }
    // REST metadata rides the customary adjacent port
    b->pool_.reset(new RestClientPool(b->host_, b->port_ + 1));
    b->dispatch_.reset(new RestDispatchPool(config.concurrency));
    b->signature_name_ = config.model_signature_name;
    backend->reset(b);
    return tc::Error::Success;
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    pa::tfserve::PredictRequest predict;
    predict.mutable_model_spec()->set_name(request.model_name);
    if (!request.model_version.empty()) {
      predict.mutable_model_spec()->mutable_version()->set_value(
          strtoll(request.model_version.c_str(), nullptr, 10));
    }
    if (signature_name_ != "serving_default") {
      predict.mutable_model_spec()->set_signature_name(signature_name_);
    }
    for (const auto& input : request.inputs) {
      if (!input.shm_region.empty()) {
        return tc::Error(
            "tfserving backend does not support shared memory");
      }
      int dtype_enum = TfDtypeEnum(input.datatype);
      if (dtype_enum < 0) {
        return tc::Error(
            "datatype " + input.datatype +
            " has no TensorFlow TensorProto mapping");
      }
      auto& tensor = (*predict.mutable_inputs())[input.name];
      tensor.set_dtype(dtype_enum);
      for (int64_t d : input.shape) {
        tensor.mutable_tensor_shape()->add_dim()->set_size(d);
      }
      if (input.datatype == "BYTES") {
        // triton length-prefix framing -> repeated string_val
        const uint8_t* p = input.data.data();
        size_t left = input.data.size();
        while (left >= 4) {
          uint32_t n;
          memcpy(&n, p, 4);
          p += 4;
          left -= 4;
          if (n > left) {
            return tc::Error("malformed BYTES input element");
          }
          tensor.add_string_val(reinterpret_cast<const char*>(p), n);
          p += n;
          left -= n;
        }
      } else {
        tensor.set_tensor_content(
            input.data.data(), input.data.size());
      }
    }
    for (const auto& name : request.requested_outputs) {
      predict.add_output_filter(name);
    }

    std::string serialized;
    if (!predict.SerializeToString(&serialized)) {
      return tc::Error("failed to serialize PredictRequest");
    }
    std::string out;
    tc::Error err = channel_->Unary(
        "tensorflow.serving.PredictionService", "Predict", serialized,
        &out);
    if (!err.IsOk()) {
      result->status = err;
      return err;
    }
    pa::tfserve::PredictResponse response;
    if (!response.ParseFromString(out)) {
      return tc::Error("failed to parse PredictResponse");
    }
    result->status = tc::Error::Success;
    result->request_id = request.request_id;
    for (const auto& kv : response.outputs()) {
      std::vector<uint8_t>& raw = result->outputs[kv.first];
      const auto& tensor = kv.second;
      if (!tensor.tensor_content().empty()) {
        raw.assign(
            tensor.tensor_content().begin(), tensor.tensor_content().end());
      } else if (tensor.string_val_size() > 0) {
        for (const auto& element : tensor.string_val()) {
          // 4-byte length prefix, explicitly little-endian (the v2
          // BYTES wire format; a native-endian write would corrupt on
          // big-endian hosts)
          uint32_t n = (uint32_t)element.size();
          uint8_t np[4] = {
              (uint8_t)(n & 0xff), (uint8_t)((n >> 8) & 0xff),
              (uint8_t)((n >> 16) & 0xff), (uint8_t)((n >> 24) & 0xff)};
          raw.insert(raw.end(), np, np + 4);
          raw.insert(raw.end(), element.begin(), element.end());
        }
      } else if (tensor.float_val_size() > 0) {
        AppendLE(raw, tensor.float_val().data(), 4,
                 tensor.float_val_size());
      } else if (tensor.double_val_size() > 0) {
        AppendLE(raw, tensor.double_val().data(), 8,
                 tensor.double_val_size());
      } else if (tensor.int_val_size() > 0) {
        // TensorProto packs every integer type <= 32 bits into
        // int_val; emit elements at the DECLARED dtype's width
        // (DT_INT8=6 / DT_UINT8=4 / DT_QINT8=11 / DT_QUINT8=12 -> 1
        // byte, DT_INT16=5 / DT_UINT16=17 / DT_QINT16=15 /
        // DT_QUINT16=16 -> 2, DT_INT32=3 / DT_UINT32 via its own
        // field; anything else packed here is 4 bytes)
        const int dt = tensor.dtype();
        const size_t width =
            (dt == 4 || dt == 6 || dt == 11 || dt == 12) ? 1
            : (dt == 5 || dt == 15 || dt == 16 || dt == 17)
                ? 2
                : 4;
        raw.resize(tensor.int_val_size() * width);
        for (int i = 0; i < tensor.int_val_size(); ++i) {
          // explicit little-endian narrowing (memcpy of the native
          // int32 would take the high-order bytes on big-endian hosts)
          uint32_t v = (uint32_t)tensor.int_val(i);
          for (size_t b = 0; b < width; ++b) {
            raw[i * width + b] = (uint8_t)((v >> (8 * b)) & 0xff);
          }
        }
      } else if (tensor.int64_val_size() > 0) {
        AppendLE(raw, tensor.int64_val().data(), 8,
                 tensor.int64_val_size());
      } else if (tensor.bool_val_size() > 0) {
        raw.resize(tensor.bool_val_size());
        for (int i = 0; i < tensor.bool_val_size(); ++i) {
          raw[i] = tensor.bool_val(i) ? 1 : 0;
        }
      } else if (tensor.half_val_size() > 0) {
        // half_val carries fp16 bit patterns in int32 slots
        raw.reserve(raw.size() + tensor.half_val_size() * 2);
        for (int i = 0; i < tensor.half_val_size(); ++i) {
          uint16_t bits = (uint16_t)tensor.half_val(i);
          AppendLE(raw, &bits, 2, 1);
        }
      } else if (tensor.uint32_val_size() > 0) {
        AppendLE(raw, tensor.uint32_val().data(), 4,
                 tensor.uint32_val_size());
      } else if (tensor.uint64_val_size() > 0) {
        AppendLE(raw, tensor.uint64_val().data(), 8,
                 tensor.uint64_val_size());
      }
    }
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback, const BackendInferRequest& request) override
  {
    auto copy = std::make_shared<BackendInferRequest>(request);
    dispatch_->Enqueue([this, callback, copy]() {
      BackendInferResult result;
      tc::Error err = Infer(&result, *copy);
      if (!err.IsOk()) {
        result.status = err;
      }
      callback(std::move(result));
    });
    return tc::Error::Success;
  }

 private:
  std::shared_ptr<tc::h2::GrpcChannel> channel_;
};

tc::Error
CreateTFServeBackend(
    std::shared_ptr<ClientBackend>* backend,
    const BackendFactoryConfig& config)
{
  if (config.tfserve_grpc) {
    return TFServeGrpcBackend::Create(backend, config);
  }
  return TFServeBackend::Create(backend, config);
}

tc::Error
CreateTorchServeBackend(
    std::shared_ptr<ClientBackend>* backend,
    const BackendFactoryConfig& config)
{
  return TorchServeBackend::Create(backend, config);
}

}  // namespace pa
