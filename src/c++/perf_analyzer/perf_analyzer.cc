#include "perf_analyzer.h"

#include <fstream>
#include <sstream>

namespace pa {

namespace {

tc::Error
ReadFile(const std::string& path, std::string* contents)
{
  std::ifstream f(path);
  if (!f) {
    return tc::Error("unable to read file " + path);
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  *contents = ss.str();
  return tc::Error::Success;
}

}  // namespace

tc::Error
PerfAnalyzer::CreateAnalyzerObjects(std::shared_ptr<ClientBackend> backend)
{
  if (backend != nullptr) {
    backend_ = backend;
  } else {
    BackendFactoryConfig config;
    config.kind = params_.kind;
    config.url = params_.url;
    config.verbose = params_.verbose;
    config.server_src = params_.server_src;
    config.inproc_vision = (params_.server_zoo == "vision");
    config.grpc_use_ssl = params_.ssl_grpc_use_ssl;
    config.grpc_ssl.root_certificates =
        params_.ssl_grpc_root_certifications_file;
    config.grpc_ssl.private_key = params_.ssl_grpc_private_key_file;
    config.grpc_ssl.certificate_chain =
        params_.ssl_grpc_certificate_chain_file;
    config.http_ssl.verify_peer = params_.ssl_https_verify_peer;
    config.http_ssl.verify_host = params_.ssl_https_verify_host;
    config.http_ssl.ca_info = params_.ssl_https_ca_certificates_file;
    config.http_ssl.cert = params_.ssl_https_client_certificate_file;
    config.http_ssl.key = params_.ssl_https_private_key_file;
    config.grpc_compression = params_.grpc_compression_algorithm == "none"
                                  ? ""
                                  : params_.grpc_compression_algorithm;
    config.model_signature_name = params_.model_signature_name;
    config.tfserve_grpc = params_.protocol_grpc;
    tc::Error err = ClientBackendFactory::Create(&backend_, config);
    if (!err.IsOk()) {
      return err;
    }
  }

  parser_ = std::make_shared<ModelParser>();
  tc::Error err = parser_->Init(
      backend_.get(), params_.model_name, params_.model_version);
  if (!err.IsOk()) {
    return err;
  }
  if (!params_.input_shapes.empty()) {
    err = parser_->OverrideShapes(params_.input_shapes);
    if (!err.IsOk()) {
      return err;
    }
  }
  if (parser_->Scheduler() == SchedulerType::SEQUENCE &&
      !params_.use_sequences) {
    params_.use_sequences = true;
  }

  // decoupled models can only be driven over the stream
  if (parser_->IsDecoupled() && !params_.streaming) {
    return tc::Error(
        "model '" + params_.model_name +
        "' is decoupled: use --streaming with -i grpc");
  }

  // forward trace settings before load starts (reference
  // command_line_parser.cc:750-754 trace forwarding)
  if (!params_.trace_file.empty() || !params_.trace_level.empty() ||
      params_.trace_rate > 0 || params_.trace_count > 0 ||
      params_.log_frequency > 0) {
    std::map<std::string, std::vector<std::string>> settings;
    if (!params_.trace_file.empty()) {
      settings["trace_file"] = {params_.trace_file};
    }
    if (!params_.trace_level.empty()) {
      settings["trace_level"] = {params_.trace_level};
    }
    if (params_.trace_rate > 0) {
      settings["trace_rate"] = {std::to_string(params_.trace_rate)};
    }
    if (params_.trace_count > 0) {
      settings["trace_count"] = {std::to_string(params_.trace_count)};
    }
    if (params_.log_frequency > 0) {
      settings["log_frequency"] = {std::to_string(params_.log_frequency)};
    }
    err = backend_->UpdateTraceSettings(settings);
    if (!err.IsOk()) {
      return err;
    }
  }

  LoadManagerConfig lm_config;
  lm_config.batch_size = params_.batch_size;
  lm_config.shared_memory = params_.shared_memory;
  lm_config.zero_input = params_.zero_input;
  lm_config.async = params_.async;
  lm_config.streaming = params_.streaming;
  lm_config.decoupled = parser_->IsDecoupled();
  lm_config.use_sequences = params_.use_sequences;
  lm_config.sequence_length = params_.sequence_length;
  lm_config.sequence_length_variation =
      params_.sequence_length_variation;
  // default slot pool covers every concurrency worker (the parser
  // rejects an explicit --num-of-sequences below the concurrency)
  lm_config.num_of_sequences =
      params_.num_of_sequences_given
          ? params_.num_of_sequences
          : std::max<size_t>(
                {params_.num_of_sequences, params_.concurrency_end,
                 params_.num_threads});
  lm_config.start_sequence_id = params_.start_sequence_id;
  lm_config.sequence_id_range = params_.sequence_id_range;
  lm_config.data_directory = params_.data_directory;
  lm_config.seed = params_.seed;
  if (!params_.input_data_path.empty()) {
    err = ReadFile(params_.input_data_path, &lm_config.input_data_json);
    if (!err.IsOk()) {
      return err;
    }
  }

  if (!params_.request_intervals_path.empty()) {
    auto* mgr = new CustomLoadManager(
        backend_, parser_, lm_config, params_.request_distribution,
        params_.num_threads);
    manager_.reset(mgr);
  } else if (params_.request_rate_start > 0) {
    manager_.reset(new RequestRateManager(
        backend_, parser_, lm_config, params_.request_distribution,
        params_.num_threads));
  } else {
    manager_.reset(new ConcurrencyManager(backend_, parser_, lm_config));
  }
  err = manager_->InitManager();
  if (!err.IsOk()) {
    return err;
  }

  ProfilerConfig prof_config;
  prof_config.measurement_window_ms = params_.measurement_window_ms;
  prof_config.count_windows = params_.count_windows;
  prof_config.measurement_request_count =
      params_.measurement_request_count;
  prof_config.max_trials = params_.max_trials;
  prof_config.stability_threshold_pct = params_.stability_threshold_pct;
  prof_config.percentile = params_.percentile;
  prof_config.warmup_request_count = params_.warmup_request_count;
  prof_config.extra_composing_models = params_.bls_composing_models;
  prof_config.verbose = params_.verbose;
  profiler_.reset(new InferenceProfiler(
      backend_, parser_, manager_.get(), prof_config));

  if (params_.collect_metrics) {
    std::string metrics_url = params_.metrics_url;
    if (metrics_url.empty()) {
      metrics_url = params_.url + "/metrics";
    }
    metrics_ = std::make_shared<MetricsManager>(
        metrics_url, params_.metrics_interval_ms);
    err = metrics_->Start();
    if (!err.IsOk()) {
      return err;
    }
    profiler_->SetMetricsManager(metrics_);
  }

  mpi_ = std::make_shared<MPIDriver>(params_.enable_mpi);
  return mpi_->Init();
}

bool
PerfAnalyzer::ExceedsLatencyThreshold(const PerfStatus& status) const
{
  if (params_.latency_threshold_ms == 0) {
    return false;
  }
  return status.client_stats.stability_latency_ns / 1000000.0 >
         (double)params_.latency_threshold_ms;
}

tc::Error
PerfAnalyzer::Profile()
{
  // multi-process runs measure the same interval (reference
  // perf_analyzer.cc:353-368 MPIBarrierWorld around Profile)
  tc::Error barrier_err = mpi_ ? mpi_->Barrier() : tc::Error::Success;
  if (!barrier_err.IsOk()) {
    return barrier_err;
  }
  tc::Error err = ProfileSweep();
  if (mpi_) {
    mpi_->Barrier();
  }
  return err;
}

tc::Error
PerfAnalyzer::ProfileSweep()
{
  if (!params_.request_intervals_path.empty()) {
    auto* mgr = static_cast<CustomLoadManager*>(manager_.get());
    std::string intervals;
    tc::Error err = ReadFile(params_.request_intervals_path, &intervals);
    if (!err.IsOk()) {
      return err;
    }
    err = mgr->InitCustomIntervals(intervals);
    if (!err.IsOk()) {
      return err;
    }
    PerfStatus status;
    err = profiler_->ProfileCurrentLevel(&status);
    mgr->StopWorkers();
    if (!err.IsOk()) {
      return err;
    }
    results_.push_back(status);
    return tc::Error::Success;
  }
  if (params_.request_rate_start > 0) {
    auto* mgr = static_cast<RequestRateManager*>(manager_.get());
    auto profile_rate = [&](double rate, PerfStatus* status) {
      tc::Error err = mgr->ChangeRequestRate(rate);
      if (!err.IsOk()) {
        return err;
      }
      status->request_rate = rate;
      err = profiler_->ProfileCurrentLevel(status);
      if (err.IsOk()) {
        results_.push_back(*status);
      }
      return err;
    };
    tc::Error err = tc::Error::Success;
    if (params_.binary_search) {
      err = BinarySearch<double>(
          params_.request_rate_start, params_.request_rate_end,
          params_.request_rate_step, profile_rate);
    } else {
      for (double rate = params_.request_rate_start;
           rate <= params_.request_rate_end + 1e-9 && !early_exit.load();
           rate += params_.request_rate_step) {
        PerfStatus status;
        err = profile_rate(rate, &status);
        if (!err.IsOk() || ExceedsLatencyThreshold(status)) {
          break;
        }
      }
    }
    mgr->StopWorkers();
    return err;
  }
  auto* mgr = static_cast<ConcurrencyManager*>(manager_.get());
  auto profile_conc = [&](size_t conc, PerfStatus* status) {
    tc::Error err = mgr->ChangeConcurrencyLevel(conc);
    if (!err.IsOk()) {
      return err;
    }
    status->concurrency = conc;
    err = profiler_->ProfileCurrentLevel(status);
    if (err.IsOk()) {
      results_.push_back(*status);
    }
    return err;
  };
  tc::Error err = tc::Error::Success;
  if (params_.binary_search) {
    err = BinarySearch<size_t>(
        params_.concurrency_start, params_.concurrency_end,
        params_.concurrency_step, profile_conc);
  } else {
    for (size_t conc = params_.concurrency_start;
         conc <= params_.concurrency_end && !early_exit.load();
         conc += params_.concurrency_step) {
      PerfStatus status;
      err = profile_conc(conc, &status);
      if (!err.IsOk() || ExceedsLatencyThreshold(status)) {
        break;
      }
    }
  }
  mgr->StopWorkers();
  return err;
}

tc::Error
PerfAnalyzer::WriteReport()
{
  ReportWriter::WriteSummary(results_, ConcurrencyMode());
  if (!params_.latency_report_file.empty()) {
    return ReportWriter::WriteCsvFile(
        params_.latency_report_file, results_, ConcurrencyMode(),
        params_.verbose_csv);
  }
  return tc::Error::Success;
}

}  // namespace pa
