#include "perf_analyzer.h"

#include <fstream>
#include <sstream>

namespace pa {

namespace {

tc::Error
ReadFile(const std::string& path, std::string* contents)
{
  std::ifstream f(path);
  if (!f) {
    return tc::Error("unable to read file " + path);
  }
  std::ostringstream ss;
  ss << f.rdbuf();
  *contents = ss.str();
  return tc::Error::Success;
}

}  // namespace

tc::Error
PerfAnalyzer::CreateAnalyzerObjects(std::shared_ptr<ClientBackend> backend)
{
  if (backend != nullptr) {
    backend_ = backend;
  } else {
    BackendFactoryConfig config;
    config.kind = params_.kind;
    config.url = params_.url;
    config.verbose = params_.verbose;
    tc::Error err = ClientBackendFactory::Create(&backend_, config);
    if (!err.IsOk()) {
      return err;
    }
  }

  parser_ = std::make_shared<ModelParser>();
  tc::Error err = parser_->Init(
      backend_.get(), params_.model_name, params_.model_version);
  if (!err.IsOk()) {
    return err;
  }
  if (parser_->Scheduler() == SchedulerType::SEQUENCE &&
      !params_.use_sequences) {
    params_.use_sequences = true;
  }

  LoadManagerConfig lm_config;
  lm_config.batch_size = params_.batch_size;
  lm_config.shared_memory = params_.shared_memory;
  lm_config.zero_input = params_.zero_input;
  lm_config.async = params_.async;
  lm_config.use_sequences = params_.use_sequences;
  lm_config.sequence_length = params_.sequence_length;
  lm_config.sequence_length_variation =
      params_.sequence_length_variation;
  lm_config.seed = params_.seed;
  if (!params_.input_data_path.empty()) {
    err = ReadFile(params_.input_data_path, &lm_config.input_data_json);
    if (!err.IsOk()) {
      return err;
    }
  }

  if (!params_.request_intervals_path.empty()) {
    auto* mgr = new CustomLoadManager(
        backend_, parser_, lm_config, params_.request_distribution,
        params_.num_threads);
    manager_.reset(mgr);
  } else if (params_.request_rate_start > 0) {
    manager_.reset(new RequestRateManager(
        backend_, parser_, lm_config, params_.request_distribution,
        params_.num_threads));
  } else {
    manager_.reset(new ConcurrencyManager(backend_, parser_, lm_config));
  }
  err = manager_->InitManager();
  if (!err.IsOk()) {
    return err;
  }

  ProfilerConfig prof_config;
  prof_config.measurement_window_ms = params_.measurement_window_ms;
  prof_config.count_windows = params_.count_windows;
  prof_config.measurement_request_count =
      params_.measurement_request_count;
  prof_config.max_trials = params_.max_trials;
  prof_config.stability_threshold_pct = params_.stability_threshold_pct;
  prof_config.verbose = params_.verbose;
  profiler_.reset(new InferenceProfiler(
      backend_, parser_, manager_.get(), prof_config));
  return tc::Error::Success;
}

tc::Error
PerfAnalyzer::Profile()
{
  if (!params_.request_intervals_path.empty()) {
    auto* mgr = static_cast<CustomLoadManager*>(manager_.get());
    std::string intervals;
    tc::Error err = ReadFile(params_.request_intervals_path, &intervals);
    if (!err.IsOk()) {
      return err;
    }
    err = mgr->InitCustomIntervals(intervals);
    if (!err.IsOk()) {
      return err;
    }
    PerfStatus status;
    err = profiler_->ProfileCurrentLevel(&status);
    mgr->StopWorkers();
    if (!err.IsOk()) {
      return err;
    }
    results_.push_back(status);
    return tc::Error::Success;
  }
  if (params_.request_rate_start > 0) {
    auto* mgr = static_cast<RequestRateManager*>(manager_.get());
    for (double rate = params_.request_rate_start;
         rate <= params_.request_rate_end + 1e-9 && !early_exit.load();
         rate += params_.request_rate_step) {
      tc::Error err = mgr->ChangeRequestRate(rate);
      if (!err.IsOk()) {
        return err;
      }
      PerfStatus status;
      status.request_rate = rate;
      err = profiler_->ProfileCurrentLevel(&status);
      if (!err.IsOk()) {
        mgr->StopWorkers();
        return err;
      }
      results_.push_back(status);
    }
    mgr->StopWorkers();
    return tc::Error::Success;
  }
  auto* mgr = static_cast<ConcurrencyManager*>(manager_.get());
  for (size_t conc = params_.concurrency_start;
       conc <= params_.concurrency_end && !early_exit.load();
       conc += params_.concurrency_step) {
    tc::Error err = mgr->ChangeConcurrencyLevel(conc);
    if (!err.IsOk()) {
      return err;
    }
    PerfStatus status;
    status.concurrency = conc;
    err = profiler_->ProfileCurrentLevel(&status);
    if (!err.IsOk()) {
      mgr->StopWorkers();
      return err;
    }
    results_.push_back(status);
  }
  mgr->StopWorkers();
  return tc::Error::Success;
}

tc::Error
PerfAnalyzer::WriteReport()
{
  ReportWriter::WriteSummary(results_, ConcurrencyMode());
  if (!params_.latency_report_file.empty()) {
    return ReportWriter::WriteCsvFile(
        params_.latency_report_file, results_, ConcurrencyMode());
  }
  return tc::Error::Success;
}

}  // namespace pa
