#include "infer_context.h"

namespace pa {

namespace {
// Request ids are correlation keys on shared streams (StreamTracker), so
// they must be unique across every context in the process.
std::atomic<uint64_t> g_request_id{0};
}  // namespace

BackendInferRequest
InferContext::BuildRequest()
{
  BackendInferRequest request;
  request.model_name = parser_->ModelName();
  request.model_version = parser_->ModelVersion();
  request.request_id = std::to_string(g_request_id.fetch_add(1) + 1);

  size_t step = step_;
  step_ = (step_ + 1) % (data_loader_->StepCount() > 0
                             ? data_loader_->StepCount()
                             : 1);
  for (const auto& input : parser_->Inputs()) {
    BackendInferRequest::Input in;
    in.name = input.name;
    in.datatype = input.datatype;
    if (parser_->MaxBatchSize() > 0) {
      in.shape.push_back(batch_size_);
    }
    for (int64_t d : input.shape) {
      in.shape.push_back(d < 0 ? 1 : d);
    }
    if (shm_layout_ != nullptr) {
      auto it = shm_layout_->inputs.find(input.name);
      if (it != shm_layout_->inputs.end()) {
        in.shm_region = shm_layout_->region_name;
        in.shm_offset = it->second.first;
        in.shm_byte_size = it->second.second;
      }
    }
    if (in.shm_region.empty()) {
      const std::vector<uint8_t>* data = nullptr;
      if (data_loader_->GetInputData(input.name, 0, step, &data).IsOk()) {
        in.data = *data;
      }
    }
    request.inputs.push_back(std::move(in));
  }
  for (const auto& output : parser_->Outputs()) {
    request.requested_outputs.push_back(output.name);
  }
  if (sequence_manager_ != nullptr) {
    auto flags = sequence_manager_->Next(seq_slot_);
    request.sequence_id = flags.sequence_id;
    request.sequence_start = flags.start;
    request.sequence_end = flags.end;
  }
  return request;
}

void
InferContext::Record(
    uint64_t start_ns, uint64_t end_ns, bool ok, bool delayed)
{
  std::lock_guard<std::mutex> lk(thread_stat_->mu);
  thread_stat_->records.push_back({start_ns, end_ns, ok, delayed});
}

void
InferContext::SendSyncRequest()
{
  BackendInferRequest request = BuildRequest();
  BackendInferResult result;
  uint64_t start = NowNs();
  tc::Error err = backend_->Infer(&result, request);
  uint64_t end = NowNs();
  bool ok = err.IsOk() && result.status.IsOk();
  if (!err.IsOk()) {
    std::lock_guard<std::mutex> lk(thread_stat_->mu);
    thread_stat_->status = err;
  }
  Record(start, end, ok, false);
}

void
InferContext::SendStreamRequest(
    const std::shared_ptr<StreamTracker>& tracker, bool decoupled,
    bool delayed)
{
  BackendInferRequest request = BuildRequest();
  request.enable_empty_final_response = decoupled;
  uint64_t start = NowNs();
  thread_stat_->inflight++;
  tracker->Register(
      request.request_id,
      StreamTracker::Pending{start, delayed, 0, thread_stat_});
  tc::Error err = backend_->StreamInfer(request);
  if (!err.IsOk()) {
    tracker->Remove(request.request_id);
    thread_stat_->inflight--;
    std::lock_guard<std::mutex> lk(thread_stat_->mu);
    thread_stat_->status = err;
    thread_stat_->records.push_back({start, NowNs(), false, delayed, 0});
  }
}

void
InferContext::SendAsyncRequest(bool delayed)
{
  // the request owns the input payload buffers that zero-copy backends
  // reference until the wire write completes — keep it alive until the
  // completion callback has fired (its copy of the shared_ptr drops
  // last)
  auto request = std::make_shared<BackendInferRequest>(BuildRequest());
  uint64_t start = NowNs();
  thread_stat_->inflight++;
  auto thread_stat = thread_stat_;
  tc::Error err = backend_->AsyncInfer(
      [thread_stat, start, delayed, request](BackendInferResult&& result) {
        uint64_t end = NowNs();
        {
          std::lock_guard<std::mutex> lk(thread_stat->mu);
          thread_stat->records.push_back(
              {start, end, result.status.IsOk(), delayed});
        }
        thread_stat->inflight--;
      },
      *request);
  if (!err.IsOk()) {
    thread_stat_->inflight--;
    std::lock_guard<std::mutex> lk(thread_stat_->mu);
    thread_stat_->status = err;
    thread_stat_->records.push_back({start, NowNs(), false, delayed});
  }
}

}  // namespace pa
