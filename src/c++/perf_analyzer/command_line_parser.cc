#include "command_line_parser.h"

#include <getopt.h>

#include <cstring>
#include <sstream>

namespace pa {

namespace {

enum LongOptIds {
  OPT_MEASUREMENT_MODE = 1000,
  OPT_MEASUREMENT_REQUEST_COUNT,
  OPT_REQUEST_DISTRIBUTION,
  OPT_REQUEST_INTERVALS,
  OPT_REQUEST_RATE_RANGE,
  OPT_CONCURRENCY_RANGE,
  OPT_SHARED_MEMORY,
  OPT_OUTPUT_SHM_SIZE,
  OPT_SEQUENCE_LENGTH,
  OPT_SEQUENCE_LENGTH_VARIATION,
  OPT_STABILITY_PCT,
  OPT_MAX_TRIALS,
  OPT_INPUT_DATA,
  OPT_SEED,
  OPT_NUM_THREADS,
  OPT_SERVICE_KIND,
  OPT_BINARY_SEARCH,
  OPT_PERCENTILE,
  OPT_WARMUP_REQUEST_COUNT,
  OPT_STREAMING,
  OPT_START_SEQUENCE_ID,
  OPT_SEQUENCE_ID_RANGE,
  OPT_STRING_LENGTH,
  OPT_STRING_DATA,
  OPT_TRACE_FILE,
  OPT_TRACE_LEVEL,
  OPT_TRACE_RATE,
  OPT_TRACE_COUNT,
  OPT_LOG_FREQUENCY,
  OPT_COLLECT_METRICS,
  OPT_METRICS_URL,
  OPT_METRICS_INTERVAL,
  OPT_VERBOSE_CSV,
  OPT_ENABLE_MPI,
  OPT_SERVER_SRC,
  OPT_SERVER_ZOO,
  OPT_SSL_GRPC_USE_SSL,
  OPT_SSL_GRPC_ROOT_CERTS,
  OPT_SSL_GRPC_PRIVATE_KEY,
  OPT_SSL_GRPC_CERT_CHAIN,
  OPT_SSL_HTTPS_VERIFY_PEER,
  OPT_SSL_HTTPS_VERIFY_HOST,
  OPT_SSL_HTTPS_CA_CERTS,
  OPT_SSL_HTTPS_CLIENT_CERT,
  OPT_SSL_HTTPS_CLIENT_CERT_TYPE,
  OPT_SSL_HTTPS_PRIVATE_KEY,
  OPT_SSL_HTTPS_PRIVATE_KEY_TYPE,
  OPT_SHAPE,
  OPT_NUM_OF_SEQUENCES,
  OPT_DATA_DIRECTORY,
  OPT_GRPC_COMPRESSION,
  OPT_MODEL_SIGNATURE_NAME,
  OPT_BLS_COMPOSING_MODELS,
  OPT_TRITON_SERVER_DIRECTORY,
  OPT_MODEL_REPOSITORY,
};

const struct option kLongOptions[] = {
    {"help", no_argument, nullptr, 'h'},
    {"verbose", no_argument, nullptr, 'v'},
    {"model-name", required_argument, nullptr, 'm'},
    {"model-version", required_argument, nullptr, 'x'},
    {"url", required_argument, nullptr, 'u'},
    {"batch-size", required_argument, nullptr, 'b'},
    {"concurrency-range", required_argument, nullptr,
     OPT_CONCURRENCY_RANGE},
    {"request-rate-range", required_argument, nullptr,
     OPT_REQUEST_RATE_RANGE},
    {"request-distribution", required_argument, nullptr,
     OPT_REQUEST_DISTRIBUTION},
    {"request-intervals", required_argument, nullptr,
     OPT_REQUEST_INTERVALS},
    {"measurement-interval", required_argument, nullptr, 'p'},
    {"measurement-mode", required_argument, nullptr,
     OPT_MEASUREMENT_MODE},
    {"measurement-request-count", required_argument, nullptr,
     OPT_MEASUREMENT_REQUEST_COUNT},
    {"stability-percentage", required_argument, nullptr,
     OPT_STABILITY_PCT},
    {"max-trials", required_argument, nullptr, OPT_MAX_TRIALS},
    {"async", no_argument, nullptr, 'a'},
    {"sync", no_argument, nullptr, 1999},
    {"zero-input", no_argument, nullptr, 'z'},
    {"input-data", required_argument, nullptr, OPT_INPUT_DATA},
    {"sequence-length", required_argument, nullptr, OPT_SEQUENCE_LENGTH},
    {"sequence-length-variation", required_argument, nullptr,
     OPT_SEQUENCE_LENGTH_VARIATION},
    {"shared-memory", required_argument, nullptr, OPT_SHARED_MEMORY},
    {"output-shared-memory-size", required_argument, nullptr,
     OPT_OUTPUT_SHM_SIZE},
    {"latency-report-file", required_argument, nullptr, 'f'},
    {"random-seed", required_argument, nullptr, OPT_SEED},
    {"num-threads", required_argument, nullptr, OPT_NUM_THREADS},
    {"service-kind", required_argument, nullptr, OPT_SERVICE_KIND},
    {"server-src", required_argument, nullptr, OPT_SERVER_SRC},
    {"server-zoo", required_argument, nullptr, OPT_SERVER_ZOO},
    {"protocol", required_argument, nullptr, 'i'},
    {"concurrency", required_argument, nullptr, 'c'},
    {"request-rate", required_argument, nullptr, 2000},
    {"latency-threshold", required_argument, nullptr, 'l'},
    {"binary-search", no_argument, nullptr, OPT_BINARY_SEARCH},
    {"percentile", required_argument, nullptr, OPT_PERCENTILE},
    {"warmup-request-count", required_argument, nullptr,
     OPT_WARMUP_REQUEST_COUNT},
    {"streaming", no_argument, nullptr, OPT_STREAMING},
    {"start-sequence-id", required_argument, nullptr,
     OPT_START_SEQUENCE_ID},
    {"sequence-id-range", required_argument, nullptr,
     OPT_SEQUENCE_ID_RANGE},
    {"string-length", required_argument, nullptr, OPT_STRING_LENGTH},
    {"string-data", required_argument, nullptr, OPT_STRING_DATA},
    {"trace-file", required_argument, nullptr, OPT_TRACE_FILE},
    {"trace-level", required_argument, nullptr, OPT_TRACE_LEVEL},
    {"trace-rate", required_argument, nullptr, OPT_TRACE_RATE},
    {"trace-count", required_argument, nullptr, OPT_TRACE_COUNT},
    {"log-frequency", required_argument, nullptr, OPT_LOG_FREQUENCY},
    {"collect-metrics", no_argument, nullptr, OPT_COLLECT_METRICS},
    {"metrics-url", required_argument, nullptr, OPT_METRICS_URL},
    {"metrics-interval", required_argument, nullptr,
     OPT_METRICS_INTERVAL},
    {"verbose-csv", no_argument, nullptr, OPT_VERBOSE_CSV},
    {"enable-mpi", no_argument, nullptr, OPT_ENABLE_MPI},
    {"max-threads", required_argument, nullptr, 2001},
    {"ssl-grpc-use-ssl", no_argument, nullptr, OPT_SSL_GRPC_USE_SSL},
    {"ssl-grpc-root-certifications-file", required_argument, nullptr,
     OPT_SSL_GRPC_ROOT_CERTS},
    {"ssl-grpc-private-key-file", required_argument, nullptr,
     OPT_SSL_GRPC_PRIVATE_KEY},
    {"ssl-grpc-certificate-chain-file", required_argument, nullptr,
     OPT_SSL_GRPC_CERT_CHAIN},
    {"ssl-https-verify-peer", required_argument, nullptr,
     OPT_SSL_HTTPS_VERIFY_PEER},
    {"ssl-https-verify-host", required_argument, nullptr,
     OPT_SSL_HTTPS_VERIFY_HOST},
    {"ssl-https-ca-certificates-file", required_argument, nullptr,
     OPT_SSL_HTTPS_CA_CERTS},
    {"ssl-https-client-certificate-file", required_argument, nullptr,
     OPT_SSL_HTTPS_CLIENT_CERT},
    {"ssl-https-client-certificate-type", required_argument, nullptr,
     OPT_SSL_HTTPS_CLIENT_CERT_TYPE},
    {"ssl-https-private-key-file", required_argument, nullptr,
     OPT_SSL_HTTPS_PRIVATE_KEY},
    {"ssl-https-private-key-type", required_argument, nullptr,
     OPT_SSL_HTTPS_PRIVATE_KEY_TYPE},
    {"shape", required_argument, nullptr, OPT_SHAPE},
    {"num-of-sequences", required_argument, nullptr,
     OPT_NUM_OF_SEQUENCES},
    {"data-directory", required_argument, nullptr, OPT_DATA_DIRECTORY},
    {"grpc-compression-algorithm", required_argument, nullptr,
     OPT_GRPC_COMPRESSION},
    {"model-signature-name", required_argument, nullptr,
     OPT_MODEL_SIGNATURE_NAME},
    {"bls-composing-models", required_argument, nullptr,
     OPT_BLS_COMPOSING_MODELS},
    {"triton-server-directory", required_argument, nullptr,
     OPT_TRITON_SERVER_DIRECTORY},
    {"model-repository", required_argument, nullptr,
     OPT_MODEL_REPOSITORY},
    {nullptr, 0, nullptr, 0},
};

bool
ParseRange(
    const std::string& arg, double* start, double* end, double* step,
    std::string* error)
{
  // start[:end[:step]]
  *end = 0;
  *step = 1;
  std::istringstream ss(arg);
  std::string tok;
  int i = 0;
  while (std::getline(ss, tok, ':')) {
    double v = atof(tok.c_str());
    if (i == 0) {
      *start = *end = v;
    } else if (i == 1) {
      *end = v;
    } else if (i == 2) {
      *step = v;
    } else {
      *error = "too many fields in range '" + arg + "'";
      return false;
    }
    ++i;
  }
  if (i == 0) {
    *error = "empty range";
    return false;
  }
  if (*step <= 0) {
    *error = "range step must be positive";
    return false;
  }
  return true;
}

}  // namespace

std::string
CLParser::Usage()
{
  return
      "Usage: perf_analyzer -m <model> [options]\n"
      "  -m/--model-name <name>          model to profile (required)\n"
      "  -x/--model-version <ver>        model version\n"
      "  -u/--url <host:port>            server url (default "
      "localhost:8000)\n"
      "  --service-kind <kind>           triton_http (default) | triton_grpc |\n"
      "                                  tpuserver_inproc (in-process, no network) |\n"
      "                                  tfserving (REST predict) | torchserve\n"
      "  --server-src <path>             tpuserver python tree for tpuserver_inproc\n"
      "  --server-zoo <set>              default | vision (tpuserver_inproc models)\n"
      "  -v/--verbose                    verbose output\n"
      "  -a/--async                      async request issuance\n"
      "  -b/--batch-size <n>             batch size (default 1)\n"
      "  -z/--zero-input                 zero-filled input data\n"
      "  --input-data <file.json>        JSON request payloads\n"
      "  --concurrency-range <s:e:st>    sweep concurrency\n"
      "  -c/--concurrency <n>            single concurrency level\n"
      "  --request-rate-range <s:e:st>   sweep request rate\n"
      "  --request-rate <r>              single request rate\n"
      "  --request-distribution <d>      constant|poisson\n"
      "  --request-intervals <file>      custom interval schedule (usec "
      "per line)\n"
      "  -p/--measurement-interval <ms>  window length (default 5000)\n"
      "  --measurement-mode <mode>       time_windows|count_windows\n"
      "  --measurement-request-count <n> requests per count window\n"
      "  --stability-percentage <pct>    stability threshold (default "
      "10)\n"
      "  --max-trials <n>                max windows per level\n"
      "  --sequence-length <n>           drive sequence models\n"
      "  --sequence-length-variation <p> +- pct sequence length\n"
      "  --shared-memory <type>          none|system|xla\n"
      "  --output-shared-memory-size <n> output region bytes\n"
      "  -l/--latency-threshold <ms>     stop the sweep when latency "
      "exceeds\n"
      "  --binary-search                 binary (not linear) concurrency/"
      "rate search\n"
      "  --percentile <n>                use p<n> latency for stability "
      "and -l\n"
      "  --warmup-request-count <n>      discarded warmup requests per "
      "level\n"
      "  --streaming                     issue over a gRPC bidi stream\n"
      "  --start-sequence-id <n>         first sequence id\n"
      "  --sequence-id-range <n>         sequence id pool size\n"
      "  --string-length <n>             synthetic BYTES element length\n"
      "  --string-data <s>               fixed BYTES element value\n"
      "  --trace-file <path>             forward trace settings to server\n"
      "  --trace-level <lvl>             TIMESTAMPS|TENSORS|OFF\n"
      "  --trace-rate <n>                trace 1/n requests\n"
      "  --trace-count <n>               stop tracing after n\n"
      "  --log-frequency <n>             trace log flush frequency\n"
      "  --collect-metrics               scrape server Prometheus metrics\n"
      "  --metrics-url <url>             metrics endpoint (default "
      "<url>/metrics)\n"
      "  --metrics-interval <ms>         scrape interval (default 1000)\n"
      "  --verbose-csv                   extra CSV columns\n"
      "  --enable-mpi                    multi-process measurement barrier\n"
      "  -f/--latency-report-file <csv>  CSV report path\n"
      "  --random-seed <n>               data/schedule seed\n"
      "  --num-threads/--max-threads <n> rate-mode sender threads\n"
      "  --shape <name:d1,d2,...>        fix a dynamic input shape "
      "(repeatable)\n"
      "  --num-of-sequences <n>          concurrent sequence streams "
      "(default 4)\n"
      "  --data-directory <dir>          raw input files <dir>/<INPUT>\n"
      "  --grpc-compression-algorithm <a> none|gzip|deflate\n"
      "  --model-signature-name <name>   TF-Serving signature (default "
      "serving_default)\n"
      "  --bls-composing-models <m1,m2>  report stats for these "
      "composing models\n"
      "  --triton-server-directory <dir> alias of --server-src\n"
      "  --model-repository <dir|zoo>    in-process model set\n"
      "  --ssl-grpc-use-ssl              TLS for the gRPC channel\n"
      "  --ssl-grpc-root-certifications-file <pem>\n"
      "  --ssl-grpc-private-key-file <pem>\n"
      "  --ssl-grpc-certificate-chain-file <pem>\n"
      "  --ssl-https-verify-peer <0|1>   verify server cert chain\n"
      "  --ssl-https-verify-host <0|2>   verify cert matches host\n"
      "  --ssl-https-ca-certificates-file <pem>\n"
      "  --ssl-https-client-certificate-file <pem>\n"
      "  --ssl-https-client-certificate-type <PEM>\n"
      "  --ssl-https-private-key-file <pem>\n"
      "  --ssl-https-private-key-type <PEM>\n";
}

bool
CLParser::Parse(
    int argc, char** argv, PerfAnalyzerParameters* params,
    std::string* error)
{
  optind = 1;  // reset for repeated calls (tests)
  int opt;
  while ((opt = getopt_long(
              argc, argv, "hvam:x:u:b:p:c:f:zi:l:t:", kLongOptions,
              nullptr)) != -1) {
    switch (opt) {
      case 'h':
        params->usage_requested = true;
        return true;
      case 'v':
        params->verbose = true;
        break;
      case 'a':
        params->async = true;
        break;
      case 1999:  // --sync
        params->async = false;
        break;
      case 'm':
        params->model_name = optarg;
        break;
      case 'x':
        params->model_version = optarg;
        break;
      case 'u':
        params->url = optarg;
        params->url_specified = true;
        break;
      case 'i':
        // -i selects the wire; for the triton pair it maps directly to
        // the backend kind (in either flag order), for other kinds
        // (e.g. tfserving) the backend consults protocol_grpc
        if (strcmp(optarg, "http") == 0 || strcmp(optarg, "HTTP") == 0) {
          params->protocol_grpc = false;
          if (params->kind == BackendKind::TRITON_GRPC) {
            params->kind = BackendKind::TRITON_HTTP;
          }
        } else if (
            strcmp(optarg, "grpc") == 0 || strcmp(optarg, "gRPC") == 0) {
          params->protocol_grpc = true;
          if (params->kind == BackendKind::TRITON_HTTP) {
            params->kind = BackendKind::TRITON_GRPC;
          }
        } else {
          *error = std::string("unknown protocol ") + optarg;
          return false;
        }
        break;
      case 'b':
        params->batch_size = atoi(optarg);
        if (params->batch_size < 1) {
          *error = "batch size must be >= 1";
          return false;
        }
        break;
      case 'z':
        params->zero_input = true;
        break;
      case 'c':
        params->concurrency_start = params->concurrency_end =
            (size_t)atoi(optarg);
        break;
      case 2000: {  // --request-rate
        params->request_rate_start = params->request_rate_end =
            atof(optarg);
        break;
      }
      case 'p':
        params->measurement_window_ms = (uint64_t)atoll(optarg);
        break;
      case 'f':
        params->latency_report_file = optarg;
        break;
      case OPT_CONCURRENCY_RANGE: {
        double s, e, st;
        if (!ParseRange(optarg, &s, &e, &st, error)) {
          return false;
        }
        params->concurrency_start = (size_t)s;
        params->concurrency_end = (size_t)e;
        params->concurrency_step = (size_t)st;
        break;
      }
      case OPT_REQUEST_RATE_RANGE: {
        if (!ParseRange(
                optarg, &params->request_rate_start,
                &params->request_rate_end, &params->request_rate_step,
                error)) {
          return false;
        }
        break;
      }
      case OPT_REQUEST_DISTRIBUTION:
        if (strcmp(optarg, "poisson") == 0) {
          params->request_distribution = Distribution::POISSON;
        } else if (strcmp(optarg, "constant") == 0) {
          params->request_distribution = Distribution::CONSTANT;
        } else {
          *error = std::string("unknown request distribution ") + optarg;
          return false;
        }
        break;
      case OPT_REQUEST_INTERVALS:
        params->request_intervals_path = optarg;
        break;
      case OPT_MEASUREMENT_MODE:
        if (strcmp(optarg, "count_windows") == 0) {
          params->count_windows = true;
        } else if (strcmp(optarg, "time_windows") == 0) {
          params->count_windows = false;
        } else {
          *error = std::string("unknown measurement mode ") + optarg;
          return false;
        }
        break;
      case OPT_MEASUREMENT_REQUEST_COUNT:
        params->measurement_request_count = (uint64_t)atoll(optarg);
        break;
      case OPT_STABILITY_PCT:
        params->stability_threshold_pct = atof(optarg);
        break;
      case OPT_MAX_TRIALS:
        params->max_trials = (size_t)atoi(optarg);
        break;
      case OPT_INPUT_DATA:
        params->input_data_path = optarg;
        break;
      case OPT_SEQUENCE_LENGTH:
        params->use_sequences = true;
        params->sequence_length = (size_t)atoi(optarg);
        break;
      case OPT_SEQUENCE_LENGTH_VARIATION:
        params->sequence_length_variation = atof(optarg);
        break;
      case OPT_SHARED_MEMORY:
        if (strcmp(optarg, "system") == 0) {
          params->shared_memory = SharedMemoryType::SYSTEM;
        } else if (strcmp(optarg, "xla") == 0) {
          params->shared_memory = SharedMemoryType::XLA;
        } else if (strcmp(optarg, "none") == 0) {
          params->shared_memory = SharedMemoryType::NONE;
        } else {
          *error = std::string("unknown shared memory type ") + optarg;
          return false;
        }
        break;
      case OPT_OUTPUT_SHM_SIZE:
        params->output_shm_size = (size_t)atoll(optarg);
        break;
      case OPT_SEED:
        params->seed = (uint32_t)atoi(optarg);
        break;
      case OPT_NUM_THREADS:
      case 2001:  // --max-threads (reference alias)
        params->num_threads = (size_t)atoi(optarg);
        break;
      case 'l':
        params->latency_threshold_ms = (uint64_t)atoll(optarg);
        break;
      case 't':  // legacy concurrency alias (reference -t)
        params->concurrency_start = params->concurrency_end =
            (size_t)atoi(optarg);
        break;
      case OPT_BINARY_SEARCH:
        params->binary_search = true;
        break;
      case OPT_PERCENTILE: {
        int p = atoi(optarg);
        if (p < 1 || p > 99) {
          *error = "--percentile must be in [1, 99]";
          return false;
        }
        params->percentile = (size_t)p;
        break;
      }
      case OPT_WARMUP_REQUEST_COUNT:
        params->warmup_request_count = (size_t)atoll(optarg);
        break;
      case OPT_STREAMING:
        params->streaming = true;
        break;
      case OPT_START_SEQUENCE_ID:
        params->start_sequence_id = (uint64_t)atoll(optarg);
        break;
      case OPT_SEQUENCE_ID_RANGE:
        params->sequence_id_range = (uint64_t)atoll(optarg);
        break;
      case OPT_STRING_LENGTH:
        params->string_length = (size_t)atoll(optarg);
        break;
      case OPT_STRING_DATA:
        params->string_data = optarg;
        break;
      case OPT_TRACE_FILE:
        params->trace_file = optarg;
        break;
      case OPT_TRACE_LEVEL:
        params->trace_level = optarg;
        break;
      case OPT_TRACE_RATE:
        params->trace_rate = (uint64_t)atoll(optarg);
        break;
      case OPT_TRACE_COUNT:
        params->trace_count = (uint64_t)atoll(optarg);
        break;
      case OPT_LOG_FREQUENCY:
        params->log_frequency = (uint64_t)atoll(optarg);
        break;
      case OPT_COLLECT_METRICS:
        params->collect_metrics = true;
        break;
      case OPT_METRICS_URL:
        params->metrics_url = optarg;
        break;
      case OPT_METRICS_INTERVAL:
        params->metrics_interval_ms = (uint64_t)atoll(optarg);
        break;
      case OPT_VERBOSE_CSV:
        params->verbose_csv = true;
        break;
      case OPT_ENABLE_MPI:
        params->enable_mpi = true;
        break;
      case OPT_SERVICE_KIND:
        if (strcmp(optarg, "triton") == 0) {
          // generic kind: honor whichever protocol -i chose, in
          // either flag order
          params->kind = params->protocol_grpc
                             ? BackendKind::TRITON_GRPC
                             : BackendKind::TRITON_HTTP;
        } else if (strcmp(optarg, "triton_http") == 0) {
          params->kind = BackendKind::TRITON_HTTP;
          params->protocol_grpc = false;
        } else if (strcmp(optarg, "triton_grpc") == 0) {
          params->kind = BackendKind::TRITON_GRPC;
          params->protocol_grpc = true;
        } else if (
            strcmp(optarg, "tpuserver_inproc") == 0 ||
            strcmp(optarg, "triton_c_api") == 0) {
          // in-process serving (role of reference triton_c_api mode)
          params->kind = BackendKind::IN_PROCESS;
        } else if (strcmp(optarg, "tfserving") == 0) {
          params->kind = BackendKind::TFSERVING;
        } else if (strcmp(optarg, "torchserve") == 0) {
          params->kind = BackendKind::TORCHSERVE;
        } else {
          *error = std::string("unsupported service kind ") + optarg;
          return false;
        }
        break;
      case OPT_SERVER_SRC:
        params->server_src = optarg;
        break;
      case OPT_SERVER_ZOO:
        if (strcmp(optarg, "default") == 0 ||
            strcmp(optarg, "vision") == 0) {
          params->server_zoo = optarg;
        } else {
          *error = std::string("unsupported server zoo ") + optarg;
          return false;
        }
        break;
      case OPT_SSL_GRPC_USE_SSL:
        params->ssl_grpc_use_ssl = true;
        break;
      case OPT_SSL_GRPC_ROOT_CERTS:
        params->ssl_grpc_root_certifications_file = optarg;
        break;
      case OPT_SSL_GRPC_PRIVATE_KEY:
        params->ssl_grpc_private_key_file = optarg;
        break;
      case OPT_SSL_GRPC_CERT_CHAIN:
        params->ssl_grpc_certificate_chain_file = optarg;
        break;
      case OPT_SSL_HTTPS_VERIFY_PEER:
        params->ssl_https_verify_peer = atol(optarg);
        break;
      case OPT_SSL_HTTPS_VERIFY_HOST:
        params->ssl_https_verify_host = atol(optarg);
        break;
      case OPT_SSL_HTTPS_CA_CERTS:
        params->ssl_https_ca_certificates_file = optarg;
        break;
      case OPT_SSL_HTTPS_CLIENT_CERT:
        params->ssl_https_client_certificate_file = optarg;
        break;
      case OPT_SSL_HTTPS_CLIENT_CERT_TYPE:
        params->ssl_https_client_certificate_type = optarg;
        if (params->ssl_https_client_certificate_type != "PEM") {
          *error = "only PEM client certificates are supported";
          return false;
        }
        break;
      case OPT_SSL_HTTPS_PRIVATE_KEY:
        params->ssl_https_private_key_file = optarg;
        break;
      case OPT_SSL_HTTPS_PRIVATE_KEY_TYPE:
        params->ssl_https_private_key_type = optarg;
        if (params->ssl_https_private_key_type != "PEM") {
          *error = "only PEM private keys are supported";
          return false;
        }
        break;
      case OPT_SHAPE: {
        // NAME:d1,d2,...
        std::string arg = optarg;
        auto colon = arg.rfind(':');
        if (colon == std::string::npos || colon == 0) {
          *error = "--shape expects NAME:d1,d2,...";
          return false;
        }
        std::vector<int64_t> dims;
        std::istringstream ds(arg.substr(colon + 1));
        std::string d;
        while (std::getline(ds, d, ',')) {
          if (d.empty() ||
              d.find_first_not_of("0123456789") != std::string::npos) {
            *error =
                "--shape dimensions must be positive integers, got '" +
                d + "'";
            return false;
          }
          int64_t v = atoll(d.c_str());
          if (v <= 0) {
            *error = "--shape dimensions must be >= 1";
            return false;
          }
          dims.push_back(v);
        }
        if (dims.empty()) {
          *error = "--shape expects at least one dimension";
          return false;
        }
        params->input_shapes.emplace_back(
            arg.substr(0, colon), std::move(dims));
        break;
      }
      case OPT_NUM_OF_SEQUENCES:
        params->num_of_sequences = (size_t)atoi(optarg);
        params->num_of_sequences_given = true;
        if (params->num_of_sequences == 0) {
          *error = "--num-of-sequences must be > 0";
          return false;
        }
        break;
      case OPT_DATA_DIRECTORY:
        params->data_directory = optarg;
        break;
      case OPT_GRPC_COMPRESSION:
        if (strcmp(optarg, "gzip") == 0 || strcmp(optarg, "deflate") == 0 ||
            strcmp(optarg, "none") == 0) {
          params->grpc_compression_algorithm = optarg;
        } else {
          *error = std::string("unsupported compression algorithm ") +
                   optarg + " (expected none|gzip|deflate)";
          return false;
        }
        break;
      case OPT_MODEL_SIGNATURE_NAME:
        params->model_signature_name = optarg;
        break;
      case OPT_BLS_COMPOSING_MODELS: {
        std::istringstream ms(optarg);
        std::string name;
        while (std::getline(ms, name, ',')) {
          if (!name.empty()) {
            params->bls_composing_models.push_back(name);
          }
        }
        break;
      }
      case OPT_TRITON_SERVER_DIRECTORY:
        // reference name for the in-process server install path; here
        // the tpuserver python tree (alias of --server-src)
        params->server_src = optarg;
        break;
      case OPT_MODEL_REPOSITORY: {
        // reference name for the in-process model repository; the
        // tpuserver analogue is the model-zoo selector — accept a zoo
        // name or a path whose last component names one
        std::string repo = optarg;
        auto slash = repo.find_last_not_of('/');
        repo = repo.substr(0, slash + 1);
        slash = repo.rfind('/');
        std::string base =
            slash == std::string::npos ? repo : repo.substr(slash + 1);
        if (base == "default" || base == "vision") {
          params->server_zoo = base;
        } else {
          *error =
              "--model-repository must name a tpuserver zoo "
              "(default|vision), got '" + repo + "'";
          return false;
        }
        break;
      }
      default:
        *error = "unknown option";
        return false;
    }
  }
  if (!params->usage_requested && params->model_name.empty()) {
    *error = "-m/--model-name is required";
    return false;
  }
  if (!params->url_specified && params->kind == BackendKind::TRITON_GRPC) {
    params->url = "localhost:8001";
  }
  if (params->kind == BackendKind::IN_PROCESS &&
      params->server_src.empty()) {
    *error =
        "--service-kind tpuserver_inproc requires --server-src "
        "(path of the tpuserver python tree)";
    return false;
  }
  if (params->request_rate_start > 0 && params->concurrency_start > 1) {
    *error =
        "cannot use concurrency and request rate modes together";
    return false;
  }
  if (params->num_of_sequences_given &&
      params->concurrency_end > params->num_of_sequences) {
    // each concurrency worker owns a sequence slot; fewer slots than
    // workers would interleave two workers' requests under one
    // sequence id (out-of-order within a sequence)
    *error =
        "--num-of-sequences (" +
        std::to_string(params->num_of_sequences) +
        ") must be >= the maximum concurrency (" +
        std::to_string(params->concurrency_end) + ")";
    return false;
  }
  if (params->sequence_id_range != 0 &&
      params->sequence_id_range < params->num_of_sequences) {
    // a wrapping pool smaller than the live stream count would hand the
    // same id to two concurrent sequences, silently corrupting state
    *error =
        "--sequence-id-range (" +
        std::to_string(params->sequence_id_range) +
        ") must be >= --num-of-sequences (" +
        std::to_string(params->num_of_sequences) + ")";
    return false;
  }
  if (params->binary_search) {
    if (params->latency_threshold_ms == 0) {
      *error = "--binary-search requires --latency-threshold";
      return false;
    }
    bool has_range = params->concurrency_end > params->concurrency_start ||
                     params->request_rate_end > params->request_rate_start;
    if (!has_range) {
      *error =
          "--binary-search requires a range (--concurrency-range or "
          "--request-rate-range with end > start)";
      return false;
    }
  }
  if (params->streaming && params->kind != BackendKind::TRITON_GRPC) {
    *error = "--streaming requires -i grpc";
    return false;
  }
  return true;
}

}  // namespace pa
