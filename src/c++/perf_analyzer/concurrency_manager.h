// Concurrency mode: keep N requests in flight (reference
// concurrency_manager.{h,cc} + concurrency_worker.{h,cc}).
//
// Each concurrency slot is one worker thread driving a sync InferContext
// loop (the async-multiplexed variant of the reference collapses to this
// on a thread-per-slot design; slots are cheap at the scales a single
// host drives).

#pragma once

#include <condition_variable>

#include "load_manager.h"

namespace pa {

class ConcurrencyManager : public LoadManager {
 public:
  using LoadManager::LoadManager;

  // Reconfigure to `level` in-flight requests (reference
  // ChangeConcurrencyLevel, concurrency_manager.h:90).
  tc::Error ChangeConcurrencyLevel(size_t level)
  {
    StopWorkers();
    // finish any open sequences before the level switch
    if (sequence_manager_ != nullptr) {
      for (auto& flags : sequence_manager_->CompleteOngoing()) {
        auto ctx = MakeContext(0);
        BackendInferRequest req = ctx->BuildRequest();
        req.sequence_id = flags.sequence_id;
        req.sequence_start = false;
        req.sequence_end = true;
        BackendInferResult result;
        backend_->Infer(&result, req);
      }
    }
    for (size_t slot = 0; slot < level; ++slot) {
      auto ctx = MakeContext(slot);
      bool use_async = config_.async;
      bool use_stream = config_.streaming;
      bool decoupled = config_.decoupled;
      auto tracker = stream_tracker_;
      threads_.emplace_back(
          [this, ctx, use_async, use_stream, decoupled, tracker] {
        while (!stop_.load(std::memory_order_relaxed)) {
          if (use_stream) {
            // one outstanding request per slot over the shared stream
            ctx->SendStreamRequest(tracker, decoupled);
            sent_requests_++;
            while (ctx->Inflight() > 0 &&
                   !stop_.load(std::memory_order_relaxed)) {
              std::this_thread::sleep_for(std::chrono::microseconds(50));
            }
          } else if (use_async) {
            // one outstanding request per slot via the async client path
            ctx->SendAsyncRequest();
            sent_requests_++;
            while (ctx->Inflight() > 0 &&
                   !stop_.load(std::memory_order_relaxed)) {
              std::this_thread::sleep_for(std::chrono::microseconds(50));
            }
          } else {
            ctx->SendSyncRequest();
            sent_requests_++;
          }
        }
      });
    }
    return tc::Error::Success;
  }
};

}  // namespace pa
