#include "mpi_utils.h"

#include <dlfcn.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>
#include <cstring>
#include <vector>

namespace pa {

namespace {

// persistent peer sockets for the TCP barrier (rank 0: one per peer;
// other ranks: the single connection to rank 0)
std::vector<int> g_peer_fds;

tc::Error
ReadByte(int fd)
{
  char b;
  ssize_t n;
  do {
    n = ::read(fd, &b, 1);
  } while (n < 0 && errno == EINTR);
  if (n != 1) {
    return tc::Error("coordination peer disconnected");
  }
  return tc::Error::Success;
}

tc::Error
WriteByte(int fd)
{
  char b = 1;
  ssize_t n;
  do {
    n = ::send(fd, &b, 1, MSG_NOSIGNAL);
  } while (n < 0 && errno == EINTR);
  if (n != 1) {
    return tc::Error("coordination peer disconnected");
  }
  return tc::Error::Success;
}

}  // namespace

MPIDriver::~MPIDriver()
{
  Finalize();
}

tc::Error
MPIDriver::Init()
{
  if (!enabled_) {
    return tc::Error::Success;
  }
  // Prefer MPI under mpirun (OMPI_COMM_WORLD_SIZE / PMI_SIZE set by the
  // launcher); else the TCP env contract.
  if (std::getenv("OMPI_COMM_WORLD_SIZE") != nullptr ||
      std::getenv("PMI_SIZE") != nullptr) {
    tc::Error err = InitLibMpi();
    if (err.IsOk()) {
      return tc::Error::Success;
    }
    // fall through to TCP when libmpi is unusable
  }
  if (std::getenv("PA_COORD_SIZE") != nullptr) {
    return InitTcp();
  }
  return tc::Error(
      "--enable-mpi requires an MPI launcher (mpirun) with libmpi, or "
      "the TCP coordination env: PA_COORD_RANK, PA_COORD_SIZE, "
      "PA_COORD_ADDR=host:port");
}

tc::Error
MPIDriver::InitLibMpi()
{
  lib_ = dlopen("libmpi.so", RTLD_NOW | RTLD_GLOBAL);
  if (lib_ == nullptr) {
    lib_ = dlopen("libmpi.so.40", RTLD_NOW | RTLD_GLOBAL);
  }
  if (lib_ == nullptr) {
    return tc::Error("libmpi not found");
  }
  auto init = reinterpret_cast<int (*)(void*, void*)>(dlsym(lib_, "MPI_Init"));
  auto comm_rank = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(lib_, "MPI_Comm_rank"));
  auto comm_size = reinterpret_cast<int (*)(void*, int*)>(
      dlsym(lib_, "MPI_Comm_size"));
  mpi_barrier_ =
      reinterpret_cast<int (*)(void*)>(dlsym(lib_, "MPI_Barrier"));
  // OpenMPI ABI: MPI_Comm is a pointer and MPI_COMM_WORLD a data symbol.
  // (MPICH's integer-handle ABI would need a different call shape; on
  // hosts without OpenMPI the TCP barrier below is the supported path.)
  mpi_comm_world_ = dlsym(lib_, "ompi_mpi_comm_world");
  if (init == nullptr || comm_rank == nullptr || comm_size == nullptr ||
      mpi_barrier_ == nullptr || mpi_comm_world_ == nullptr) {
    return tc::Error("libmpi missing required symbols (OpenMPI ABI)");
  }
  if (init(nullptr, nullptr) != 0) {
    return tc::Error("MPI_Init failed");
  }
  comm_rank(mpi_comm_world_, &rank_);
  comm_size(mpi_comm_world_, &world_size_);
  using_mpi_ = true;
  active_ = world_size_ > 1;
  return tc::Error::Success;
}

tc::Error
MPIDriver::InitTcp()
{
  const char* rank_env = std::getenv("PA_COORD_RANK");
  const char* size_env = std::getenv("PA_COORD_SIZE");
  const char* addr_env = std::getenv("PA_COORD_ADDR");
  if (rank_env == nullptr || size_env == nullptr || addr_env == nullptr) {
    return tc::Error(
        "TCP coordination needs PA_COORD_RANK, PA_COORD_SIZE and "
        "PA_COORD_ADDR");
  }
  rank_ = atoi(rank_env);
  world_size_ = atoi(size_env);
  coord_addr_ = addr_env;
  if (world_size_ < 2) {
    active_ = false;
    return tc::Error::Success;
  }
  std::string host = coord_addr_;
  int port = 0;
  auto colon = host.rfind(':');
  if (colon == std::string::npos) {
    return tc::Error("PA_COORD_ADDR must be host:port");
  }
  port = atoi(host.c_str() + colon + 1);
  host = host.substr(0, colon);

  if (rank_ == 0) {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(port);
    if (bind(listen_fd_, (struct sockaddr*)&addr, sizeof(addr)) != 0 ||
        listen(listen_fd_, world_size_) != 0) {
      return tc::Error(
          "coordination bind/listen failed on port " + std::to_string(port));
    }
    for (int i = 1; i < world_size_; ++i) {
      int fd = accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) {
        return tc::Error("coordination accept failed");
      }
      int nd = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
      g_peer_fds.push_back(fd);
    }
  } else {
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    if (getaddrinfo(
            host.c_str(), std::to_string(port).c_str(), &hints, &res) != 0) {
      return tc::Error("coordination resolve failed for " + host);
    }
    int fd = -1;
    // retry for up to ~10 s: rank 0 may not be listening yet
    for (int attempt = 0; attempt < 100 && fd < 0; ++attempt) {
      for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
        fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) {
          continue;
        }
        if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          break;
        }
        close(fd);
        fd = -1;
      }
      if (fd < 0) {
        usleep(100000);
      }
    }
    freeaddrinfo(res);
    if (fd < 0) {
      return tc::Error("unable to reach coordination rank 0 at " +
                       coord_addr_);
    }
    int nd = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &nd, sizeof(nd));
    g_peer_fds.push_back(fd);
  }
  active_ = true;
  return tc::Error::Success;
}

tc::Error
MPIDriver::Barrier()
{
  if (!active_) {
    return tc::Error::Success;
  }
  if (using_mpi_) {
    if (mpi_barrier_(mpi_comm_world_) != 0) {
      return tc::Error("MPI_Barrier failed");
    }
    return tc::Error::Success;
  }
  return TcpBarrier();
}

tc::Error
MPIDriver::TcpBarrier()
{
  ++barrier_seq_;
  if (rank_ == 0) {
    // gather: one byte from every peer; release: one byte back
    for (int fd : g_peer_fds) {
      tc::Error err = ReadByte(fd);
      if (!err.IsOk()) {
        return err;
      }
    }
    for (int fd : g_peer_fds) {
      tc::Error err = WriteByte(fd);
      if (!err.IsOk()) {
        return err;
      }
    }
  } else {
    tc::Error err = WriteByte(g_peer_fds[0]);
    if (!err.IsOk()) {
      return err;
    }
    err = ReadByte(g_peer_fds[0]);
    if (!err.IsOk()) {
      return err;
    }
  }
  return tc::Error::Success;
}

void
MPIDriver::Finalize()
{
  if (using_mpi_ && lib_ != nullptr) {
    auto finalize = reinterpret_cast<int (*)()>(dlsym(lib_, "MPI_Finalize"));
    if (finalize != nullptr) {
      finalize();
    }
    using_mpi_ = false;
  }
  for (int fd : g_peer_fds) {
    close(fd);
  }
  g_peer_fds.clear();
  if (listen_fd_ >= 0) {
    close(listen_fd_);
    listen_fd_ = -1;
  }
  active_ = false;
}

}  // namespace pa
