// Measurement machinery: windows, stability detection, percentiles,
// server-stat deltas (reference inference_profiler.{h,cc}:97-1097).

#pragma once

#include <functional>
#include <memory>

#include "load_manager.h"

namespace pa {

struct ClientSideStats {
  uint64_t request_count = 0;
  uint64_t delayed_request_count = 0;
  uint64_t failed_request_count = 0;
  uint64_t response_count = 0;  // > request_count for decoupled streams
  double infer_per_sec = 0.0;
  uint64_t avg_latency_ns = 0;
  uint64_t p50_ns = 0;
  uint64_t p90_ns = 0;
  uint64_t p95_ns = 0;
  uint64_t p99_ns = 0;
  uint64_t std_ns = 0;
  // p<N> when the profiler runs with --percentile N, else avg latency;
  // the value stability checks and -l compare against
  uint64_t stability_latency_ns = 0;
  // share of worker wall-time not spent inside requests (reference
  // "overhead pct"): client-side bookkeeping between requests
  double overhead_pct = 0.0;
};

struct ServerSideStats {
  uint64_t inference_count = 0;
  uint64_t execution_count = 0;
  uint64_t queue_ns = 0;
  uint64_t compute_input_ns = 0;
  uint64_t compute_infer_ns = 0;
  uint64_t compute_output_ns = 0;
  uint64_t success_count = 0;
};

// One stable measurement at a load level (reference PerfStatus,
// inference_profiler.h:97-162).
struct PerfStatus {
  size_t concurrency = 0;
  double request_rate = 0.0;
  ClientSideStats client_stats;
  ServerSideStats server_stats;
  // per-composing-model server stats for ensembles (reference
  // inference_profiler.cc:868-1097)
  std::map<std::string, ServerSideStats> composing_server_stats;
  // scraped Prometheus metrics averaged over the measurement
  // (metrics_manager.h); empty unless --collect-metrics
  std::map<std::string, double> metrics;
  bool on_sequence_model = false;
  bool stabilized = false;
};

struct ProfilerConfig {
  uint64_t measurement_window_ms = 5000;
  // count-based windows (reference --measurement-mode count_windows)
  bool count_windows = false;
  uint64_t measurement_request_count = 50;
  size_t max_trials = 10;
  double stability_threshold_pct = 10.0;
  // stability/threshold latency metric: p<N> when nonzero, else average
  size_t percentile = 0;
  // requests discarded before the first window of each level
  size_t warmup_request_count = 0;
  // extra models to collect server-side stat deltas for, merged with
  // the ensemble's auto-derived composing models (reference
  // --bls-composing-models: BLS children are invisible in the config)
  std::vector<std::string> extra_composing_models;
  bool verbose = false;
};

class InferenceProfiler {
 public:
  InferenceProfiler(
      std::shared_ptr<ClientBackend> backend,
      std::shared_ptr<ModelParser> parser, LoadManager* manager,
      const ProfilerConfig& config)
      : backend_(std::move(backend)), parser_(std::move(parser)),
        manager_(manager), config_(config)
  {
  }

  // Measure at the current load level until 3 consecutive windows agree
  // within the stability threshold on both throughput and avg latency
  // (reference DetermineStability, inference_profiler.cc:780-833), or
  // max_trials windows pass.
  tc::Error ProfileCurrentLevel(PerfStatus* status);

  // Compute client stats from a window of records (public for unit tests;
  // the reference exposes the same via friend-test hooks).  `percentile`
  // selects the stability latency metric (0 = average).
  static ClientSideStats SummarizeRecords(
      const std::vector<RequestRecord>& records, uint64_t window_ns,
      size_t percentile = 0);

  // True when the last `window_count` windows agree with the final
  // window within `threshold_pct` on BOTH throughput and the stability
  // latency metric (reference DetermineStability,
  // inference_profiler.cc:780-833).  Public/static for unit tests.
  static bool DetermineStability(
      const std::vector<ClientSideStats>& windows, double threshold_pct,
      size_t window_count = 3);

  // Optional Prometheus scraper; when set, per-measurement averages are
  // attached to PerfStatus::metrics.
  void SetMetricsManager(std::shared_ptr<class MetricsManager> metrics)
  {
    metrics_ = std::move(metrics);
  }

 private:
  tc::Error QueryServerStats(
      ServerSideStats* stats, const std::string& model_name);

  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  LoadManager* manager_;
  ProfilerConfig config_;
  std::shared_ptr<class MetricsManager> metrics_;
  size_t sent_in_window_ = 0;
};

}  // namespace pa
