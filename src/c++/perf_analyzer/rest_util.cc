#include "rest_util.h"

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstring>

namespace pa {

namespace {

int
ConnectTo(const std::string& host, int port, std::string* error)
{
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  int rc =
      getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints, &res);
  if (rc != 0) {
    *error = "failed to resolve " + host + ": " + gai_strerror(rc);
    return -1;
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      continue;
    }
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    *error = "unable to connect to " + host + ":" + std::to_string(port);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

tc::Error
SendAll(int fd, const std::string& data)
{
  size_t sent = 0;
  while (sent < data.size()) {
    ssize_t n = ::send(
        fd, data.data() + sent, data.size() - sent, MSG_NOSIGNAL);
    if (n <= 0) {
      return tc::Error("send failed");
    }
    sent += (size_t)n;
  }
  return tc::Error::Success;
}

std::string
BuildRequest(
    const std::string& host, const std::string& method,
    const std::string& path, const std::string& body,
    const std::string& content_type, bool keep_alive)
{
  std::string request = method + " " + path + " HTTP/1.1\r\nHost: " +
                        host + "\r\nConnection: " +
                        (keep_alive ? "keep-alive" : "close") + "\r\n";
  if (method == "POST") {
    request += "Content-Type: " +
               (content_type.empty() ? "application/json" : content_type) +
               "\r\nContent-Length: " + std::to_string(body.size()) +
               "\r\n";
  }
  request += "\r\n";
  if (method == "POST") {
    request += body;
  }
  return request;
}

// parse status + headers + body; returns false when the response must
// terminate the connection (no Content-Length framing)
tc::Error
ReadResponse(
    int fd, long* http_code, std::string* body, bool* reusable)
{
  *reusable = false;
  std::string buf;
  size_t header_end;
  while (true) {
    header_end = buf.find("\r\n\r\n");
    if (header_end != std::string::npos) {
      break;
    }
    char tmp[16384];
    ssize_t n = ::recv(fd, tmp, sizeof(tmp), 0);
    if (n <= 0) {
      return tc::Error("connection closed while reading response");
    }
    buf.append(tmp, (size_t)n);
  }
  size_t line_end = buf.find("\r\n");
  std::string status_line = buf.substr(0, line_end);
  size_t sp = status_line.find(' ');
  *http_code =
      sp == std::string::npos
          ? 0
          : strtol(status_line.c_str() + sp + 1, nullptr, 10);
  // scan headers for content-length / connection
  bool have_length = false;
  size_t content_length = 0;
  bool close_after = false;
  size_t pos = line_end + 2;
  while (pos < header_end) {
    size_t eol = buf.find("\r\n", pos);
    std::string line = buf.substr(pos, eol - pos);
    pos = eol + 2;
    size_t colon = line.find(':');
    if (colon == std::string::npos) {
      continue;
    }
    std::string key = line.substr(0, colon);
    for (auto& c : key) {
      c = (char)tolower((unsigned char)c);
    }
    size_t vstart = colon + 1;
    while (vstart < line.size() && line[vstart] == ' ') {
      ++vstart;
    }
    std::string value = line.substr(vstart);
    if (key == "content-length") {
      have_length = true;
      content_length = (size_t)strtoull(value.c_str(), nullptr, 10);
    } else if (key == "connection") {
      for (auto& c : value) {
        c = (char)tolower((unsigned char)c);
      }
      close_after = value.find("close") != std::string::npos;
    }
  }
  body->assign(buf.substr(header_end + 4));
  if (have_length) {
    while (body->size() < content_length) {
      char tmp[16384];
      size_t want = content_length - body->size();
      ssize_t n = ::recv(
          fd, tmp, want < sizeof(tmp) ? want : sizeof(tmp), 0);
      if (n <= 0) {
        return tc::Error("connection closed while reading body");
      }
      body->append(tmp, (size_t)n);
    }
    *reusable = !close_after;
  } else {
    // no framing info: read to close
    char tmp[16384];
    ssize_t n;
    while ((n = ::recv(fd, tmp, sizeof(tmp), 0)) > 0) {
      body->append(tmp, (size_t)n);
    }
    *reusable = false;
  }
  return tc::Error::Success;
}

}  // namespace

// ---------------------------------------------------------------------------

RestClient::RestClient(const std::string& host, int port)
    : host_(host), port_(port)
{
}

RestClient::~RestClient()
{
  Close();
}

void
RestClient::Close()
{
  if (fd_ >= 0) {
    close(fd_);
    fd_ = -1;
  }
}

tc::Error
RestClient::Connect()
{
  std::string error;
  fd_ = ConnectTo(host_, port_, &error);
  if (fd_ < 0) {
    return tc::Error(error);
  }
  return tc::Error::Success;
}

tc::Error
RestClient::Request(
    const std::string& method, const std::string& path,
    const std::string& body, const std::string& content_type,
    long* http_code, std::string* response_body)
{
  std::string request =
      BuildRequest(host_, method, path, body, content_type, true);
  for (int attempt = 0; attempt < 2; ++attempt) {
    bool reused = fd_ >= 0;
    if (!reused) {
      tc::Error err = Connect();
      if (!err.IsOk()) {
        return err;
      }
    }
    tc::Error err = SendAll(fd_, request);
    if (err.IsOk()) {
      bool reusable = false;
      err = ReadResponse(fd_, http_code, response_body, &reusable);
      if (err.IsOk()) {
        if (!reusable) {
          Close();
        }
        return tc::Error::Success;
      }
    }
    Close();
    if (!reused) {  // fresh connection failed: report, don't retry
      return err;
    }
    // stale keep-alive connection: retry once on a fresh one
  }
  return tc::Error("request failed after reconnect");
}

tc::Error
RestClientPool::Request(
    const std::string& method, const std::string& path,
    const std::string& body, const std::string& content_type,
    long* http_code, std::string* response_body)
{
  std::unique_ptr<RestClient> client;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!idle_.empty()) {
      client = std::move(idle_.back());
      idle_.pop_back();
    }
  }
  if (client == nullptr) {
    client.reset(new RestClient(host_, port_));
  }
  tc::Error err = client->Request(
      method, path, body, content_type, http_code, response_body);
  {
    std::lock_guard<std::mutex> lk(mu_);
    idle_.push_back(std::move(client));
  }
  return err;
}

RestDispatchPool::RestDispatchPool(int workers)
{
  for (int i = 0; i < workers; ++i) {
    workers_.emplace_back(&RestDispatchPool::Worker, this);
  }
}

RestDispatchPool::~RestDispatchPool()
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    exiting_ = true;
  }
  cv_.notify_all();
  for (auto& w : workers_) {
    w.join();
  }
}

void
RestDispatchPool::Enqueue(std::function<void()> job)
{
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push_back(std::move(job));
  }
  cv_.notify_one();
}

void
RestDispatchPool::Worker()
{
  while (true) {
    std::function<void()> job;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return exiting_ || !queue_.empty(); });
      if (exiting_ && queue_.empty()) {
        return;
      }
      job = std::move(queue_.front());
      queue_.pop_front();
    }
    job();
  }
}

// ---------------------------------------------------------------------------

tc::Error
RestRequest(
    const std::string& host, int port, const std::string& method,
    const std::string& path, const std::string& body,
    const std::string& content_type, long* http_code,
    std::string* response_body)
{
  std::string error;
  int fd = ConnectTo(host, port, &error);
  if (fd < 0) {
    return tc::Error(error);
  }
  std::string request =
      BuildRequest(host, method, path, body, content_type, false);
  tc::Error err = SendAll(fd, request);
  if (err.IsOk()) {
    bool reusable = false;
    err = ReadResponse(fd, http_code, response_body, &reusable);
  }
  close(fd);
  return err;
}

void
SplitHostPort(
    const std::string& url, int default_port, std::string* host, int* port)
{
  std::string u = url;
  auto scheme = u.find("://");
  if (scheme != std::string::npos) {
    u = u.substr(scheme + 3);
  }
  auto slash = u.find('/');
  if (slash != std::string::npos) {
    u = u.substr(0, slash);
  }
  auto colon = u.rfind(':');
  if (colon == std::string::npos) {
    *host = u;
    *port = default_port;
  } else {
    *host = u.substr(0, colon);
    *port = atoi(u.c_str() + colon + 1);
  }
}

}  // namespace pa
