// CPython-embedding implementation of the in-process backend (role of
// reference triton_loader.cc: dlopen + symbol binding + in-process
// serve).  All Python access goes through a JSON+bytes bridge module so
// the C++ side needs no numpy/jax API knowledge.

#include "tpuserver_loader.h"

#include <Python.h>

#include <iostream>
#include <mutex>

#include "tjson.h"
#include <sstream>

namespace pa {

namespace {

// Bridge functions injected into the embedded interpreter.  The C++ side
// only ever passes/receives str and bytes objects.
const char kBridgeSource[] = R"PYBRIDGE(
import json

import numpy as np


_core = None


def _pa_setup(include_vision):
    global _core
    from tpuserver.core import InferenceServer
    from tpuserver.models import default_models, serving_models

    models = default_models()
    if include_vision:
        models += serving_models(include_bert=False, include_llama=False)
    _core = InferenceServer(models)
    return "ok"


def _pa_model_metadata(name, version):
    return json.dumps(_core.model_metadata(name, version))


def _pa_model_config(name, version):
    return json.dumps(_core.model_config(name, version))


def _pa_model_statistics(name):
    return json.dumps(_core.model_statistics(name))


def _pa_register_system_shm_sized(name, key, byte_size):
    _core.register_system_shm(name, key, 0, int(byte_size))
    return "ok"


def _pa_unregister_system_shm(name):
    _core.unregister_system_shm(name)
    return "ok"


def _pa_register_xla_shm_sized(name, raw_handle, byte_size, device_ordinal):
    _core.register_xla_shm(
        name, raw_handle, int(device_ordinal), int(byte_size))
    return "ok"


def _pa_unregister_xla_shm(name):
    _core.unregister_xla_shm(name)
    return "ok"


def _pa_infer(meta_json, raw_blobs):
    from tpuserver.core import InferRequest, RequestedOutput
    from tritonclient.utils import (
        deserialize_bytes_tensor,
        serialize_byte_tensor,
        triton_to_np_dtype,
    )

    meta = json.loads(meta_json)
    inputs = {}
    cursor = 0
    for t in meta["inputs"]:
        if t.get("shm_region"):
            inputs[t["name"]] = _core.read_shm_input(
                t["shm_region"], t.get("shm_byte_size", 0),
                t.get("shm_offset", 0), t["datatype"], t["shape"],
            )
        else:
            raw = raw_blobs[cursor]
            cursor += 1
            if t["datatype"] == "BYTES":
                arr = deserialize_bytes_tensor(raw).reshape(t["shape"])
            else:
                arr = np.frombuffer(
                    raw, dtype=triton_to_np_dtype(t["datatype"])
                ).reshape(t["shape"])
            inputs[t["name"]] = arr
    requested = None
    if meta.get("outputs"):
        requested = [
            RequestedOutput(
                o["name"],
                shm_region=o.get("shm_region"),
                shm_byte_size=o.get("shm_byte_size", 0),
                shm_offset=o.get("shm_offset", 0),
            )
            for o in meta["outputs"]
        ]
    parameters = dict(meta.get("parameters", {}))
    request = InferRequest(
        meta["model_name"], meta.get("model_version", ""),
        meta.get("id", ""), inputs, requested, parameters,
    )
    resp = _core.infer(request)
    out_meta = []
    blobs = []
    for spec, array, delivery in resp.outputs:
        entry = {
            "name": spec["name"],
            "datatype": spec["datatype"],
            "shape": spec["shape"],
        }
        if array is None:  # delivered via shared memory
            entry["shm"] = True
        else:
            if spec["datatype"] == "BYTES":
                serialized = serialize_byte_tensor(
                    np.asarray(array, dtype=object)
                )
                blobs.append(
                    serialized.item() if serialized.size > 0 else b""
                )
            else:
                blobs.append(np.ascontiguousarray(array).tobytes())
        out_meta.append(entry)
    return json.dumps({"id": resp.id, "outputs": out_meta}), blobs
)PYBRIDGE";

std::mutex init_mu;
PyObject* bridge_dict = nullptr;  // borrowed module dict, lives forever

std::string
PyErrToString()
{
  PyObject *type, *value, *traceback;
  PyErr_Fetch(&type, &value, &traceback);
  PyErr_NormalizeException(&type, &value, &traceback);
  std::string message = "python error";
  if (value != nullptr) {
    PyObject* str = PyObject_Str(value);
    if (str != nullptr) {
      const char* utf8 = PyUnicode_AsUTF8(str);
      if (utf8 != nullptr) {
        message = utf8;
      }
      Py_DECREF(str);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(traceback);
  return message;
}

// Call a bridge function with already-built argument tuple; returns the
// result object or an Error (GIL must be held).
tc::Error
CallBridge(const char* fn_name, PyObject* args, PyObject** out)
{
  PyObject* fn = PyDict_GetItemString(bridge_dict, fn_name);  // borrowed
  if (fn == nullptr) {
    return tc::Error(std::string("bridge function missing: ") + fn_name);
  }
  PyObject* result = PyObject_CallObject(fn, args);
  if (result == nullptr) {
    return tc::Error(
        std::string(fn_name) + " failed: " + PyErrToString());
  }
  *out = result;
  return tc::Error::Success;
}

// string-in/string-out bridge call helper
tc::Error
CallBridgeStr(
    const char* fn_name, const std::vector<std::string>& args,
    std::string* out)
{
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* tuple = PyTuple_New(args.size());
  for (size_t i = 0; i < args.size(); ++i) {
    PyObject* str = PyUnicode_FromString(args[i].c_str());
    if (str == nullptr) {  // e.g. argv bytes that are not valid UTF-8
      PyErr_Clear();
      Py_DECREF(tuple);
      PyGILState_Release(gil);
      return tc::Error(
          std::string(fn_name) + ": argument is not valid UTF-8");
    }
    PyTuple_SetItem(tuple, i, str);
  }
  PyObject* result = nullptr;
  tc::Error err = CallBridge(fn_name, tuple, &result);
  Py_DECREF(tuple);
  if (err.IsOk()) {
    const char* utf8 = PyUnicode_AsUTF8(result);
    if (out != nullptr && utf8 != nullptr) {
      *out = utf8;
    }
    Py_DECREF(result);
  }
  PyGILState_Release(gil);
  return err;
}

// quoted+escaped JSON string literal (shared tjson escaper)
std::string
Quoted(const std::string& in)
{
  std::string out;
  tc::json::EscapeTo(in, &out);
  return out;
}

}  // namespace

TpuServerLoader*
TpuServerLoader::GetSingleton()
{
  static TpuServerLoader loader;
  return &loader;
}

tc::Error
TpuServerLoader::Create(const Options& options)
{
  std::lock_guard<std::mutex> lk(init_mu);
  TpuServerLoader* loader = GetSingleton();
  if (loader->initialized_) {
    return tc::Error::Success;
  }
  tc::Error err = loader->InitPython(options);
  if (err.IsOk()) {
    loader->initialized_ = true;
  }
  return err;
}

tc::Error
TpuServerLoader::InitPython(const Options& options)
{
  Py_InitializeEx(0);

  // sys.path: prepend the tpuserver/tritonclient source tree.  Also
  // re-assert JAX_PLATFORMS from the process environment: interpreter
  // startup hooks (site) may override it, and the operator's choice of
  // platform must win inside the embedded runtime too.
  {
    std::ostringstream src;
    src << "import sys\n"
        << "sys.path.insert(0, " << Quoted(options.server_src)
        << ")\n";
    const char* jax_platforms = getenv("JAX_PLATFORMS");
    if (jax_platforms != nullptr) {
      src << "import os\n"
          << "os.environ[\"JAX_PLATFORMS\"] = "
          << Quoted(jax_platforms) << "\n";
    }
    if (PyRun_SimpleString(src.str().c_str()) != 0) {
      return tc::Error("unable to set up sys.path for tpuserver");
    }
  }

  PyObject* module = PyImport_AddModule("__pa_bridge__");  // borrowed
  if (module == nullptr) {
    return tc::Error("unable to create bridge module");
  }
  bridge_dict = PyModule_GetDict(module);  // borrowed
  // builtins so the bridge source can import/def
  PyDict_SetItemString(
      bridge_dict, "__builtins__", PyEval_GetBuiltins());
  PyObject* run = PyRun_String(
      kBridgeSource, Py_file_input, bridge_dict, bridge_dict);
  if (run == nullptr) {
    return tc::Error("bridge source failed: " + PyErrToString());
  }
  Py_DECREF(run);

  PyObject* args = PyTuple_New(1);
  PyTuple_SetItem(args, 0, PyBool_FromLong(options.include_vision));
  PyObject* result = nullptr;
  tc::Error err = CallBridge("_pa_setup", args, &result);
  Py_DECREF(args);
  if (!err.IsOk()) {
    return err;
  }
  Py_DECREF(result);
  if (options.verbose) {
    std::cout << "tpuserver in-process core up (src=" << options.server_src
              << ")" << std::endl;
  }
  // release the GIL so worker threads can take it per call
  PyEval_SaveThread();
  return tc::Error::Success;
}

tc::Error
TpuServerLoader::ServerReady(bool* ready)
{
  *ready = initialized_;
  return tc::Error::Success;
}

tc::Error
TpuServerLoader::ModelMetadata(
    std::string* metadata_json, const std::string& model_name,
    const std::string& model_version)
{
  return CallBridgeStr(
      "_pa_model_metadata", {model_name, model_version}, metadata_json);
}

tc::Error
TpuServerLoader::ModelConfig(
    std::string* config_json, const std::string& model_name,
    const std::string& model_version)
{
  return CallBridgeStr(
      "_pa_model_config", {model_name, model_version}, config_json);
}

tc::Error
TpuServerLoader::ModelStatistics(
    std::string* stats_json, const std::string& model_name)
{
  return CallBridgeStr("_pa_model_statistics", {model_name}, stats_json);
}

tc::Error
TpuServerLoader::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size)
{
  return CallBridgeStr(
      "_pa_register_system_shm_sized",
      {name, key, std::to_string(byte_size)}, nullptr);
}

tc::Error
TpuServerLoader::UnregisterSystemSharedMemory(const std::string& name)
{
  return CallBridgeStr("_pa_unregister_system_shm", {name}, nullptr);
}

tc::Error
TpuServerLoader::RegisterXlaSharedMemory(
    const std::string& name, const std::string& raw_handle,
    size_t byte_size, int device_ordinal)
{
  return CallBridgeStr(
      "_pa_register_xla_shm_sized",
      {name, raw_handle, std::to_string(byte_size),
       std::to_string(device_ordinal)},
      nullptr);
}

tc::Error
TpuServerLoader::UnregisterXlaSharedMemory(const std::string& name)
{
  return CallBridgeStr("_pa_unregister_xla_shm", {name}, nullptr);
}

tc::Error
TpuServerLoader::Infer(
    BackendInferResult* result, const BackendInferRequest& request)
{
  // request descriptor JSON
  std::ostringstream meta;
  meta << "{\"model_name\": " << Quoted(request.model_name)
       << ", \"model_version\": " << Quoted(request.model_version)
       << ", \"id\": " << Quoted(request.request_id);
  if (request.sequence_id != 0) {
    meta << ", \"parameters\": {\"sequence_id\": " << request.sequence_id
         << ", \"sequence_start\": "
         << (request.sequence_start ? "true" : "false")
         << ", \"sequence_end\": "
         << (request.sequence_end ? "true" : "false") << "}";
  }
  meta << ", \"inputs\": [";
  bool first = true;
  for (const auto& input : request.inputs) {
    if (!first) {
      meta << ", ";
    }
    first = false;
    meta << "{\"name\": " << Quoted(input.name)
         << ", \"datatype\": " << Quoted(input.datatype) << ", \"shape\": [";
    for (size_t i = 0; i < input.shape.size(); ++i) {
      meta << (i ? ", " : "") << input.shape[i];
    }
    meta << "]";
    if (!input.shm_region.empty()) {
      meta << ", \"shm_region\": " << Quoted(input.shm_region)
           << ", \"shm_byte_size\": " << input.shm_byte_size
           << ", \"shm_offset\": " << input.shm_offset;
    }
    meta << "}";
  }
  meta << "], \"outputs\": [";
  first = true;
  for (const auto& name : request.requested_outputs) {
    if (!first) {
      meta << ", ";
    }
    first = false;
    meta << "{\"name\": " << Quoted(name) << "}";
  }
  meta << "]}";

  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* blobs = PyList_New(0);
  for (const auto& input : request.inputs) {
    if (input.shm_region.empty()) {
      PyObject* bytes = PyBytes_FromStringAndSize(
          (const char*)input.data.data(), input.data.size());
      PyList_Append(blobs, bytes);
      Py_DECREF(bytes);
    }
  }
  PyObject* args = PyTuple_New(2);
  PyTuple_SetItem(args, 0, PyUnicode_FromString(meta.str().c_str()));
  PyTuple_SetItem(args, 1, blobs);  // steals blobs ref
  PyObject* py_result = nullptr;
  tc::Error err = CallBridge("_pa_infer", args, &py_result);
  Py_DECREF(args);
  if (!err.IsOk()) {
    PyGILState_Release(gil);
    result->status = err;
    return err;
  }

  // (json_str, [bytes, ...])
  PyObject* meta_obj = PyTuple_GetItem(py_result, 0);   // borrowed
  PyObject* blobs_out = PyTuple_GetItem(py_result, 1);  // borrowed
  const char* meta_utf8 = PyUnicode_AsUTF8(meta_obj);
  std::string out_meta = meta_utf8 ? meta_utf8 : "{}";

  // parse the descriptor; blobs align with non-shm outputs in order
  result->outputs.clear();
  result->request_id = request.request_id;
  result->status = tc::Error::Success;
  std::string parse_error;
  tc::json::ValuePtr doc = tc::json::Parse(out_meta, &parse_error);
  if (doc == nullptr) {
    err = tc::Error("bad infer response descriptor: " + parse_error);
    Py_DECREF(py_result);
    PyGILState_Release(gil);
    result->status = err;
    return err;
  }
  size_t blob_index = 0;
  tc::json::ValuePtr outputs = doc->Get("outputs");
  if (outputs != nullptr) {
    for (const auto& entry : outputs->Elements()) {
      const std::string& name = entry->Get("name")->AsString();
      bool is_shm =
          entry->Has("shm") && entry->Get("shm")->AsBool();
      std::vector<uint8_t> data;
      if (!is_shm && blob_index < (size_t)PyList_Size(blobs_out)) {
        PyObject* blob = PyList_GetItem(blobs_out, blob_index++);
        char* buf;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(blob, &buf, &len) == 0) {
          data.assign((uint8_t*)buf, (uint8_t*)buf + len);
        }
      }
      result->outputs[name] = std::move(data);
    }
  }
  Py_DECREF(py_result);
  PyGILState_Release(gil);
  return tc::Error::Success;
}

}  // namespace pa
