// Shared enums + helpers (reference src/c++/perf_analyzer/perf_utils.h:56-155).

#pragma once

#include <atomic>
#include <cstdint>
#include <random>
#include <string>
#include <vector>

namespace pa {

enum class BackendKind {
  TRITON_HTTP,
  TRITON_GRPC,
  IN_PROCESS,
  TFSERVING,
  TORCHSERVE,
  MOCK,
};
enum class SharedMemoryType { NONE, SYSTEM, XLA };
enum class Distribution { POISSON, CONSTANT };

// Two-stage SIGINT support: set by the signal handler, polled by the
// profiler loops so the current measurement drains and the report still
// writes (reference perf_analyzer.cc:39-53).
extern std::atomic<bool> early_exit;

// nanosecond steady-clock timestamp
uint64_t NowNs();

// bytes per element for a wire datatype; -1 for BYTES (variable)
int64_t ByteSize(const std::string& datatype);

// total element count of a shape (dynamic dims treated as 1)
int64_t ElementCount(const std::vector<int64_t>& shape);

// Inter-request interval generator (reference perf_utils.h:152-155):
// POISSON draws exponential gaps around the target rate, CONSTANT is the
// fixed reciprocal.
class ScheduleDistribution {
 public:
  ScheduleDistribution(Distribution dist, double rate_per_sec, uint32_t seed)
      : dist_(dist), rate_(rate_per_sec), rng_(seed),
        exp_(rate_per_sec > 0 ? rate_per_sec : 1.0)
  {
  }

  // next inter-request gap in nanoseconds
  uint64_t NextGapNs()
  {
    if (rate_ <= 0) {
      return 0;
    }
    if (dist_ == Distribution::CONSTANT) {
      return (uint64_t)(1e9 / rate_);
    }
    return (uint64_t)(exp_(rng_) * 1e9);
  }

 private:
  Distribution dist_;
  double rate_;
  std::mt19937 rng_;
  std::exponential_distribution<double> exp_;
};

}  // namespace pa
