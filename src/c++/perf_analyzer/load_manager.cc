#include "load_manager.h"

#include <cstring>

#include "shm_utils.h"

namespace pa {

namespace {
const char kShmKey[] = "/pa_input_data";
const char kShmRegion[] = "pa_input_data";
}  // namespace

tc::Error
LoadManager::SetupSystemShm()
{
  // one region holding every input's step-0 payload back to back
  // (reference InferDataManagerShm::CreateMemoryRegion)
  auto layout = std::make_shared<ShmLayout>();
  layout->region_name = kShmRegion;
  size_t total = 0;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    tc::Error err = data_loader_->GetInputData(input.name, 0, 0, &data);
    if (!err.IsOk()) {
      return err;
    }
    layout->inputs[input.name] = {total, data->size()};
    total += data->size();
  }
  if (total == 0) {
    return tc::Error("no input data to place in shared memory");
  }
  tc::Error err = tc::CreateSharedMemoryRegion(kShmKey, total, &shm_fd_);
  if (!err.IsOk()) {
    return err;
  }
  err = tc::MapSharedMemory(shm_fd_, 0, total, &shm_base_);
  if (!err.IsOk()) {
    return err;
  }
  shm_total_ = total;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    data_loader_->GetInputData(input.name, 0, 0, &data);
    auto& slot = layout->inputs[input.name];
    memcpy((uint8_t*)shm_base_ + slot.first, data->data(), slot.second);
  }
  backend_->UnregisterSystemSharedMemory(kShmRegion);
  err = backend_->RegisterSystemSharedMemory(kShmRegion, kShmKey, total);
  if (!err.IsOk()) {
    return err;
  }
  shm_layout_ = layout;
  return tc::Error::Success;
}

void
LoadManager::TeardownSystemShm()
{
  if (shm_layout_ != nullptr) {
    backend_->UnregisterSystemSharedMemory(kShmRegion);
    shm_layout_.reset();
  }
  if (shm_base_ != nullptr) {
    tc::UnmapSharedMemory(shm_base_, shm_total_);
    shm_base_ = nullptr;
  }
  if (shm_fd_ >= 0) {
    tc::CloseSharedMemory(shm_fd_);
    tc::UnlinkSharedMemoryRegion(kShmKey);
    shm_fd_ = -1;
  }
}

}  // namespace pa
