#include "load_manager.h"

#include <unistd.h>

#include <cstring>

#include "shm_utils.h"

namespace pa {

namespace {
const char kShmKey[] = "/pa_input_data";
const char kShmRegion[] = "pa_input_data";
const char kXlaShmKey[] = "/xlashm_pa_input";
const char kXlaShmRegion[] = "pa_xla_input_data";

// standard base64 (the raw xla-shm handle is base64'd JSON, mirroring the
// reference's base64'd cudaIpcMemHandle_t, cuda_shared_memory.cc:98-127)
std::string
Base64Encode(const std::string& in)
{
  static const char kTable[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve((in.size() + 2) / 3 * 4);
  size_t i = 0;
  while (i + 3 <= in.size()) {
    uint32_t v = ((uint8_t)in[i] << 16) | ((uint8_t)in[i + 1] << 8) |
                 (uint8_t)in[i + 2];
    out.push_back(kTable[(v >> 18) & 63]);
    out.push_back(kTable[(v >> 12) & 63]);
    out.push_back(kTable[(v >> 6) & 63]);
    out.push_back(kTable[v & 63]);
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16;
    out.push_back(kTable[(v >> 18) & 63]);
    out.push_back(kTable[(v >> 12) & 63]);
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = ((uint8_t)in[i] << 16) | ((uint8_t)in[i + 1] << 8);
    out.push_back(kTable[(v >> 18) & 63]);
    out.push_back(kTable[(v >> 12) & 63]);
    out.push_back(kTable[(v >> 6) & 63]);
    out += "=";
  }
  return out;
}
}  // namespace

tc::Error
LoadManager::SetupSystemShm()
{
  // one region holding every input's step-0 payload back to back
  // (reference InferDataManagerShm::CreateMemoryRegion)
  auto layout = std::make_shared<ShmLayout>();
  layout->region_name = kShmRegion;
  size_t total = 0;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    tc::Error err = data_loader_->GetInputData(input.name, 0, 0, &data);
    if (!err.IsOk()) {
      return err;
    }
    layout->inputs[input.name] = {total, data->size()};
    total += data->size();
  }
  if (total == 0) {
    return tc::Error("no input data to place in shared memory");
  }
  tc::Error err = tc::CreateSharedMemoryRegion(kShmKey, total, &shm_fd_);
  if (!err.IsOk()) {
    return err;
  }
  err = tc::MapSharedMemory(shm_fd_, 0, total, &shm_base_);
  if (!err.IsOk()) {
    return err;
  }
  shm_total_ = total;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    data_loader_->GetInputData(input.name, 0, 0, &data);
    auto& slot = layout->inputs[input.name];
    memcpy((uint8_t*)shm_base_ + slot.first, data->data(), slot.second);
  }
  backend_->UnregisterSystemSharedMemory(kShmRegion);
  err = backend_->RegisterSystemSharedMemory(kShmRegion, kShmKey, total);
  if (!err.IsOk()) {
    return err;
  }
  shm_layout_ = layout;
  return tc::Error::Success;
}

tc::Error
LoadManager::SetupXlaShm()
{
  // Same input layout as the system-shm path, but the region registers
  // through the XLA plane: this process creates the region's host
  // staging window and serializes an XlaShmHandle-compatible raw handle
  // {uuid, shm_key, byte_size, device_ordinal}; the server's
  // attach_from_raw_handle opens the window cross-process and stages
  // tensors to TPU HBM on use (tritonclient/utils/xla_shared_memory).
  auto layout = std::make_shared<ShmLayout>();
  layout->region_name = kXlaShmRegion;
  size_t total = 0;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    tc::Error err = data_loader_->GetInputData(input.name, 0, 0, &data);
    if (!err.IsOk()) {
      return err;
    }
    layout->inputs[input.name] = {total, data->size()};
    total += data->size();
  }
  if (total == 0) {
    return tc::Error("no input data to place in xla shared memory");
  }
  tc::Error err = tc::CreateSharedMemoryRegion(kXlaShmKey, total, &shm_fd_);
  if (!err.IsOk()) {
    return err;
  }
  err = tc::MapSharedMemory(shm_fd_, 0, total, &shm_base_);
  if (!err.IsOk()) {
    return err;
  }
  shm_total_ = total;
  for (const auto& input : parser_->Inputs()) {
    const std::vector<uint8_t>* data = nullptr;
    data_loader_->GetInputData(input.name, 0, 0, &data);
    auto& slot = layout->inputs[input.name];
    memcpy((uint8_t*)shm_base_ + slot.first, data->data(), slot.second);
  }
  std::string handle_json =
      std::string("{\"uuid\": \"pa") + std::to_string(getpid()) +
      "\", \"shm_key\": \"" + kXlaShmKey +
      "\", \"byte_size\": " + std::to_string(total) +
      ", \"device_ordinal\": " +
      std::to_string(config_.xla_device_ordinal) + "}";
  backend_->UnregisterXlaSharedMemory(kXlaShmRegion);
  err = backend_->RegisterXlaSharedMemory(
      kXlaShmRegion, Base64Encode(handle_json), total,
      config_.xla_device_ordinal);
  if (!err.IsOk()) {
    return err;
  }
  xla_shm_registered_ = true;
  shm_layout_ = layout;
  return tc::Error::Success;
}

void
LoadManager::TeardownXlaShm()
{
  if (xla_shm_registered_) {
    backend_->UnregisterXlaSharedMemory(kXlaShmRegion);
    xla_shm_registered_ = false;
    shm_layout_.reset();
    if (shm_base_ != nullptr) {
      tc::UnmapSharedMemory(shm_base_, shm_total_);
      shm_base_ = nullptr;
    }
    if (shm_fd_ >= 0) {
      tc::CloseSharedMemory(shm_fd_);
      tc::UnlinkSharedMemoryRegion(kXlaShmKey);
      shm_fd_ = -1;
    }
  }
}

void
LoadManager::TeardownSystemShm()
{
  if (xla_shm_registered_) {
    return;  // region fields belong to the XLA plane (TeardownXlaShm)
  }
  if (shm_layout_ != nullptr) {
    backend_->UnregisterSystemSharedMemory(kShmRegion);
    shm_layout_.reset();
  }
  if (shm_base_ != nullptr) {
    tc::UnmapSharedMemory(shm_base_, shm_total_);
    shm_base_ = nullptr;
  }
  if (shm_fd_ >= 0) {
    tc::CloseSharedMemory(shm_fd_);
    tc::UnlinkSharedMemoryRegion(kShmKey);
    shm_fd_ = -1;
  }
}

}  // namespace pa
