#include "report_writer.h"

#include <algorithm>
#include <cstdio>
#include <fstream>
#include <sstream>

namespace pa {

void
ReportWriter::WriteSummary(
    const std::vector<PerfStatus>& results, bool concurrency_mode)
{
  for (const auto& status : results) {
    const auto& c = status.client_stats;
    if (concurrency_mode) {
      printf("Request concurrency: %zu\n", status.concurrency);
    } else {
      printf("Request rate: %.2f\n", status.request_rate);
    }
    printf("  Client:\n");
    printf("    Request count: %llu\n",
           (unsigned long long)c.request_count);
    printf("    Throughput: %.4g infer/sec\n", c.infer_per_sec);
    if (c.delayed_request_count > 0) {
      printf("    Delayed Request Count: %llu\n",
             (unsigned long long)c.delayed_request_count);
    }
    if (c.failed_request_count > 0) {
      printf("    Failed request count: %llu\n",
             (unsigned long long)c.failed_request_count);
    }
    printf("    Avg latency: %llu usec (standard deviation %llu usec)\n",
           (unsigned long long)(c.avg_latency_ns / 1000),
           (unsigned long long)(c.std_ns / 1000));
    printf("    p50 latency: %llu usec\n",
           (unsigned long long)(c.p50_ns / 1000));
    printf("    p90 latency: %llu usec\n",
           (unsigned long long)(c.p90_ns / 1000));
    printf("    p95 latency: %llu usec\n",
           (unsigned long long)(c.p95_ns / 1000));
    printf("    p99 latency: %llu usec\n",
           (unsigned long long)(c.p99_ns / 1000));
    if (c.response_count > c.request_count) {
      printf("    Response count: %llu (decoupled stream)\n",
             (unsigned long long)c.response_count);
    }
    if (c.overhead_pct > 0) {
      printf("    Client overhead: %.1f%%\n", c.overhead_pct);
    }
    const auto& s = status.server_stats;
    if (s.inference_count > 0) {
      uint64_t n = s.success_count > 0 ? s.success_count : 1;
      printf("  Server:\n");
      printf("    Inference count: %llu\n",
             (unsigned long long)s.inference_count);
      printf("    Execution count: %llu\n",
             (unsigned long long)s.execution_count);
      printf(
          "    Avg request latency: queue %llu usec, compute input %llu "
          "usec, compute infer %llu usec, compute output %llu usec\n",
          (unsigned long long)(s.queue_ns / n / 1000),
          (unsigned long long)(s.compute_input_ns / n / 1000),
          (unsigned long long)(s.compute_infer_ns / n / 1000),
          (unsigned long long)(s.compute_output_ns / n / 1000));
    }
    for (const auto& kv : status.composing_server_stats) {
      const auto& cs = kv.second;
      uint64_t n = cs.success_count > 0 ? cs.success_count : 1;
      printf("  Composing model %s:\n", kv.first.c_str());
      printf("    Inference count: %llu\n",
             (unsigned long long)cs.inference_count);
      printf(
          "    Avg request latency: queue %llu usec, compute infer %llu "
          "usec\n",
          (unsigned long long)(cs.queue_ns / n / 1000),
          (unsigned long long)(cs.compute_infer_ns / n / 1000));
    }
    if (!status.metrics.empty()) {
      printf("  Server metrics (avg over measurement):\n");
      for (const auto& kv : status.metrics) {
        printf("    %s: %g\n", kv.first.c_str(), kv.second);
      }
    }
    printf("\n");
  }
}

std::string
ReportWriter::GenerateCsv(
    const std::vector<PerfStatus>& results, bool concurrency_mode,
    bool verbose)
{
  // union of scraped metric names across levels, for stable columns
  std::vector<std::string> metric_names;
  if (verbose) {
    for (const auto& status : results) {
      for (const auto& kv : status.metrics) {
        if (std::find(metric_names.begin(), metric_names.end(), kv.first) ==
            metric_names.end()) {
          metric_names.push_back(kv.first);
        }
      }
    }
  }
  std::ostringstream out;
  out << (concurrency_mode ? "Concurrency" : "Request Rate")
      << ",Inferences/Second,Client Send,"
      << "Network+Server Send/Recv,Server Queue,Server Compute Input,"
      << "Server Compute Infer,Server Compute Output,Client Recv,"
      << "p50 latency,p90 latency,p95 latency,p99 latency";
  if (verbose) {
    out << ",Avg latency,Client Overhead Pct,Responses/Second";
    for (const auto& name : metric_names) {
      out << "," << name;
    }
  }
  out << "\n";
  for (const auto& status : results) {
    const auto& c = status.client_stats;
    const auto& s = status.server_stats;
    uint64_t n = s.success_count > 0 ? s.success_count : 1;
    uint64_t server_total_us = (s.queue_ns + s.compute_input_ns +
                                s.compute_infer_ns + s.compute_output_ns) /
                               n / 1000;
    uint64_t avg_us = c.avg_latency_ns / 1000;
    uint64_t network_us =
        avg_us > server_total_us ? avg_us - server_total_us : 0;
    if (concurrency_mode) {
      out << status.concurrency;
    } else {
      out << status.request_rate;
    }
    out << "," << c.infer_per_sec << ",0," << network_us << ","
        << (s.queue_ns / n / 1000) << "," << (s.compute_input_ns / n / 1000)
        << "," << (s.compute_infer_ns / n / 1000) << ","
        << (s.compute_output_ns / n / 1000) << ",0,"
        << (c.p50_ns / 1000) << "," << (c.p90_ns / 1000) << ","
        << (c.p95_ns / 1000) << "," << (c.p99_ns / 1000);
    if (verbose) {
      double responses_per_sec =
          c.request_count > 0
              ? c.infer_per_sec * ((double)c.response_count /
                                   (double)c.request_count)
              : 0.0;
      out << "," << avg_us << "," << c.overhead_pct << ","
          << responses_per_sec;
      for (const auto& name : metric_names) {
        auto it = status.metrics.find(name);
        out << ",";
        if (it != status.metrics.end()) {
          out << it->second;
        }
      }
    }
    out << "\n";
  }
  return out.str();
}

tc::Error
ReportWriter::WriteCsvFile(
    const std::string& path, const std::vector<PerfStatus>& results,
    bool concurrency_mode, bool verbose)
{
  std::ofstream f(path);
  if (!f) {
    return tc::Error("unable to open csv file " + path);
  }
  f << GenerateCsv(results, concurrency_mode, verbose);
  return tc::Error::Success;
}

}  // namespace pa
