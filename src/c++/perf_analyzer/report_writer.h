// stdout summary + CSV export (reference report_writer.cc:73-260).

#pragma once

#include <string>
#include <vector>

#include "inference_profiler.h"

namespace pa {

class ReportWriter {
 public:
  // Print the reference-style per-level summary block.
  static void WriteSummary(
      const std::vector<PerfStatus>& results, bool concurrency_mode);

  // CSV with the reference's column schema
  // (docs/measurements_metrics.md:103).
  static std::string GenerateCsv(
      const std::vector<PerfStatus>& results, bool concurrency_mode);

  static tc::Error WriteCsvFile(
      const std::string& path, const std::vector<PerfStatus>& results,
      bool concurrency_mode);
};

}  // namespace pa
