// stdout summary + CSV export (reference report_writer.cc:73-260).

#pragma once

#include <string>
#include <vector>

#include "inference_profiler.h"

namespace pa {

class ReportWriter {
 public:
  // Print the reference-style per-level summary block.
  static void WriteSummary(
      const std::vector<PerfStatus>& results, bool concurrency_mode);

  // CSV with the reference's column schema
  // (docs/measurements_metrics.md:103); verbose adds avg latency,
  // overhead pct, response throughput and any scraped metric columns
  // (reference --verbose-csv).
  static std::string GenerateCsv(
      const std::vector<PerfStatus>& results, bool concurrency_mode,
      bool verbose = false);

  static tc::Error WriteCsvFile(
      const std::string& path, const std::vector<PerfStatus>& results,
      bool concurrency_mode, bool verbose = false);
};

}  // namespace pa
