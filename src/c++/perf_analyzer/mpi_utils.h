// Multi-process measurement coordination (reference mpi_utils.h:32-83):
// barriers around Profile so N perf_analyzer processes measure the same
// interval.  Two transports:
//
// - dlopen'd libmpi (when present and launched under mpirun): the
//   reference's design — MPI_Init/Barrier/Finalize resolved at runtime so
//   the binary carries no MPI link dependency.
// - TCP fallback: a tiny rank-0-hosted barrier server, configured via
//   PA_COORD_RANK / PA_COORD_SIZE / PA_COORD_ADDR environment variables
//   (idiomatic on TPU pod VMs, where MPI is typically absent and the
//   JAX-style coordinator-address pattern is the norm).

#pragma once

#include <string>

#include "common.h"

namespace pa {

class MPIDriver {
 public:
  explicit MPIDriver(bool enabled) : enabled_(enabled) {}
  ~MPIDriver();

  // Resolve the transport (libmpi else TCP env) and initialize.
  tc::Error Init();

  bool IsMPIRun() const { return active_; }
  int Rank() const { return rank_; }
  int WorldSize() const { return world_size_; }

  // Block until every process reaches the barrier.
  tc::Error Barrier();

  void Finalize();

 private:
  tc::Error InitLibMpi();
  tc::Error InitTcp();
  tc::Error TcpBarrier();

  bool enabled_ = false;
  bool active_ = false;
  bool using_mpi_ = false;
  int rank_ = 0;
  int world_size_ = 1;

  // libmpi symbols
  void* lib_ = nullptr;
  int (*mpi_barrier_)(void*) = nullptr;
  void* mpi_comm_world_ = nullptr;

  // tcp coordination
  std::string coord_addr_;
  int listen_fd_ = -1;  // rank 0 only
  uint64_t barrier_seq_ = 0;
};

}  // namespace pa
