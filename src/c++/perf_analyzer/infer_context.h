// Per-context request issuance + timestamp accounting
// (reference infer_context.{h,cc}:43-260, load_worker pieces).

#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <vector>

#include <map>

#include "client_backend.h"
#include "data_loader.h"
#include "model_parser.h"
#include "sequence_manager.h"

namespace pa {

// System-shm layout shared by the load manager and its contexts: where
// each input's step-0 payload lives inside the registered region
// (reference infer_data_manager_shm.h:56-123).
struct ShmLayout {
  std::string region_name;
  // input name -> (offset, byte_size)
  std::map<std::string, std::pair<size_t, size_t>> inputs;
};

// One completed request's timing record.
struct RequestRecord {
  uint64_t start_ns = 0;
  uint64_t end_ns = 0;
  bool success = false;
  bool delayed = false;  // rate mode: fired behind schedule
};

// Shared between a worker thread and the profiler (reference
// infer_context.h:43-64).
struct ThreadStat {
  std::mutex mu;
  std::vector<RequestRecord> records;
  tc::Error status = tc::Error::Success;
  std::atomic<size_t> inflight{0};
};

class InferContext {
 public:
  InferContext(
      std::shared_ptr<ClientBackend> backend,
      std::shared_ptr<ModelParser> parser,
      std::shared_ptr<DataLoader> data_loader,
      std::shared_ptr<SequenceManager> sequence_manager,
      std::shared_ptr<ThreadStat> thread_stat, int batch_size,
      size_t seq_slot = 0,
      std::shared_ptr<const ShmLayout> shm_layout = nullptr)
      : backend_(std::move(backend)), parser_(std::move(parser)),
        data_loader_(std::move(data_loader)),
        sequence_manager_(std::move(sequence_manager)),
        thread_stat_(std::move(thread_stat)), batch_size_(batch_size),
        seq_slot_(seq_slot), shm_layout_(std::move(shm_layout))
  {
  }

  // Build the request for the context's current step (+sequence position).
  BackendInferRequest BuildRequest();

  // Synchronous send; records timing into the thread stat.
  void SendSyncRequest();

  // Asynchronous send; completion recorded on the backend's thread.
  void SendAsyncRequest(bool delayed = false);

  size_t Inflight() const { return thread_stat_->inflight.load(); }

 private:
  void Record(uint64_t start_ns, uint64_t end_ns, bool ok, bool delayed);

  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  std::shared_ptr<DataLoader> data_loader_;
  std::shared_ptr<SequenceManager> sequence_manager_;
  std::shared_ptr<ThreadStat> thread_stat_;
  int batch_size_;
  size_t seq_slot_ = 0;
  std::shared_ptr<const ShmLayout> shm_layout_;
  size_t step_ = 0;
  uint64_t request_counter_ = 0;
};

}  // namespace pa
