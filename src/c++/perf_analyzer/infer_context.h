// Per-context request issuance + timestamp accounting
// (reference infer_context.{h,cc}:43-260, load_worker pieces).

#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <vector>

#include <map>

#include "client_backend.h"
#include "data_loader.h"
#include "model_parser.h"
#include "sequence_manager.h"

namespace pa {

// System-shm layout shared by the load manager and its contexts: where
// each input's step-0 payload lives inside the registered region
// (reference infer_data_manager_shm.h:56-123).
struct ShmLayout {
  std::string region_name;
  // input name -> (offset, byte_size)
  std::map<std::string, std::pair<size_t, size_t>> inputs;
};

// One completed request's timing record.
struct RequestRecord {
  uint64_t start_ns = 0;
  uint64_t end_ns = 0;
  bool success = false;
  bool delayed = false;  // rate mode: fired behind schedule
  // responses received for this request: 1 for unary, >= 1 on decoupled
  // streams (0 is treated as 1 for compatibility)
  uint64_t response_count = 0;
};

// Shared between a worker thread and the profiler (reference
// infer_context.h:43-64).
struct ThreadStat {
  std::mutex mu;
  std::vector<RequestRecord> records;
  tc::Error status = tc::Error::Success;
  std::atomic<size_t> inflight{0};
};

// Correlates stream responses (which arrive on the backend's stream
// callback, identified only by request id) back to the issuing context's
// timing state.  One tracker per load manager; installed as the backend
// stream callback by StartStream.
class StreamTracker {
 public:
  struct Pending {
    uint64_t start_ns = 0;
    bool delayed = false;
    uint64_t response_count = 0;
    std::shared_ptr<ThreadStat> thread_stat;
  };

  void Register(const std::string& id, Pending pending)
  {
    std::lock_guard<std::mutex> lk(mu_);
    pending_.emplace(id, std::move(pending));
  }

  // Stream callback body: route one response; on the final response the
  // request record is written to the owning thread's stats.
  void OnResponse(BackendInferResult&& result)
  {
    std::shared_ptr<ThreadStat> stat;
    RequestRecord record;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = pending_.find(result.request_id);
      if (it == pending_.end()) {
        return;  // response for a request from a previous level
      }
      auto& p = it->second;
      p.response_count++;
      if (!result.final_response && result.status.IsOk()) {
        return;  // intermediate decoupled response
      }
      record = {p.start_ns, NowNs(), result.status.IsOk(), p.delayed,
                p.response_count};
      stat = p.thread_stat;
      pending_.erase(it);
    }
    {
      std::lock_guard<std::mutex> lk(stat->mu);
      if (!result.status.IsOk()) {
        stat->status = result.status;
      }
      stat->records.push_back(record);
    }
    stat->inflight--;
  }

  size_t PendingCount()
  {
    std::lock_guard<std::mutex> lk(mu_);
    return pending_.size();
  }

  // Drop a pending entry (send-failure path: no response will come).
  void Remove(const std::string& id)
  {
    std::lock_guard<std::mutex> lk(mu_);
    pending_.erase(id);
  }

 private:
  std::mutex mu_;
  std::map<std::string, Pending> pending_;
};

class InferContext {
 public:
  InferContext(
      std::shared_ptr<ClientBackend> backend,
      std::shared_ptr<ModelParser> parser,
      std::shared_ptr<DataLoader> data_loader,
      std::shared_ptr<SequenceManager> sequence_manager,
      std::shared_ptr<ThreadStat> thread_stat, int batch_size,
      size_t seq_slot = 0,
      std::shared_ptr<const ShmLayout> shm_layout = nullptr)
      : backend_(std::move(backend)), parser_(std::move(parser)),
        data_loader_(std::move(data_loader)),
        sequence_manager_(std::move(sequence_manager)),
        thread_stat_(std::move(thread_stat)), batch_size_(batch_size),
        seq_slot_(seq_slot), shm_layout_(std::move(shm_layout))
  {
  }

  // Build the request for the context's current step (+sequence position).
  BackendInferRequest BuildRequest();

  // Synchronous send; records timing into the thread stat.
  void SendSyncRequest();

  // Asynchronous send; completion recorded on the backend's thread.
  void SendAsyncRequest(bool delayed = false);

  // Stream send: issues over the backend's bidi stream; completion is
  // routed through the tracker on the stream callback.
  void SendStreamRequest(
      const std::shared_ptr<StreamTracker>& tracker,
      bool decoupled, bool delayed = false);

  size_t Inflight() const { return thread_stat_->inflight.load(); }

 private:
  void Record(uint64_t start_ns, uint64_t end_ns, bool ok, bool delayed);

  std::shared_ptr<ClientBackend> backend_;
  std::shared_ptr<ModelParser> parser_;
  std::shared_ptr<DataLoader> data_loader_;
  std::shared_ptr<SequenceManager> sequence_manager_;
  std::shared_ptr<ThreadStat> thread_stat_;
  int batch_size_;
  size_t seq_slot_ = 0;
  std::shared_ptr<const ShmLayout> shm_layout_;
  size_t step_ = 0;
};

}  // namespace pa
