// Request-rate mode: fire requests on a precomputed schedule
// (reference request_rate_manager.{h,cc}, rate_schedule.h,
// request_rate_worker.cc:102-119).

#pragma once

#include <cmath>

#include "load_manager.h"

namespace pa {

class RequestRateManager : public LoadManager {
 public:
  RequestRateManager(
      std::shared_ptr<ClientBackend> backend,
      std::shared_ptr<ModelParser> parser, const LoadManagerConfig& config,
      Distribution distribution = Distribution::CONSTANT,
      size_t num_threads = 2)
      : LoadManager(std::move(backend), std::move(parser), config),
        distribution_(distribution), num_threads_(num_threads)
  {
  }

  // Rebuild the schedule for `rate` requests/sec and restart workers
  // (reference ChangeRequestRate / GenerateSchedule).
  tc::Error ChangeRequestRate(double rate)
  {
    StopWorkers();
    GenerateSchedule(rate);
    StartWorkers();
    return tc::Error::Success;
  }

  // For CustomLoadManager: replay explicit inter-request intervals.
  tc::Error SetScheduleFromIntervals(
      const std::vector<uint64_t>& intervals_ns)
  {
    StopWorkers();
    schedule_ = intervals_ns;
    StartWorkers();
    return tc::Error::Success;
  }

  const std::vector<uint64_t>& Schedule() const { return schedule_; }

 protected:
  void GenerateSchedule(double rate)
  {
    // one cycle of gaps, replayed round-robin (reference RateSchedule)
    schedule_.clear();
    ScheduleDistribution dist(distribution_, rate, config_.seed);
    size_t entries = (size_t)std::max(8.0, std::ceil(rate));
    for (size_t i = 0; i < entries; ++i) {
      schedule_.push_back(dist.NextGapNs());
    }
  }

  void StartWorkers()
  {
    // worker w fires schedule slots w, w+N, w+2N... against its own
    // context (async so one slow response can't stall the schedule)
    start_ns_ = NowNs();
    for (size_t w = 0; w < num_threads_; ++w) {
      auto ctx = MakeContext(w);
      threads_.emplace_back([this, ctx, w] {
        uint64_t next = start_ns_;
        size_t slot = 0;
        // accumulate gaps for slots below our first
        for (size_t i = 0; i < w && !schedule_.empty(); ++i) {
          next += schedule_[slot % schedule_.size()];
          ++slot;
        }
        while (!stop_.load(std::memory_order_relaxed)) {
          uint64_t now = NowNs();
          bool delayed = now > next + 2000000;  // >2ms behind schedule
          if (now < next) {
            // SleepIfNecessary (reference request_rate_worker.cc:102)
            std::this_thread::sleep_for(
                std::chrono::nanoseconds(next - now));
          }
          if (stop_.load(std::memory_order_relaxed)) {
            break;
          }
          ctx->SendAsyncRequest(delayed);
          sent_requests_++;
          for (size_t i = 0; i < num_threads_ && !schedule_.empty();
               ++i) {
            next += schedule_[slot % schedule_.size()];
            ++slot;
          }
        }
      });
    }
  }

  Distribution distribution_;
  size_t num_threads_;
  std::vector<uint64_t> schedule_;
  uint64_t start_ns_ = 0;
};

//==============================================================================
// Custom-interval mode: replay a user-supplied intervals file
// (reference custom_load_manager.{h,cc}).
class CustomLoadManager : public RequestRateManager {
 public:
  using RequestRateManager::RequestRateManager;

  tc::Error InitCustomIntervals(const std::string& intervals_text)
  {
    // file of one interval per line, in microseconds
    std::vector<uint64_t> intervals;
    size_t pos = 0;
    while (pos < intervals_text.size()) {
      size_t eol = intervals_text.find('\n', pos);
      if (eol == std::string::npos) {
        eol = intervals_text.size();
      }
      std::string line = intervals_text.substr(pos, eol - pos);
      pos = eol + 1;
      if (line.empty()) {
        continue;
      }
      intervals.push_back((uint64_t)strtoull(line.c_str(), nullptr, 10) *
                          1000ull);
    }
    if (intervals.empty()) {
      return tc::Error("no intervals found in custom intervals data");
    }
    return SetScheduleFromIntervals(intervals);
  }
};

}  // namespace pa
