#include "client_backend.h"

#include <google/protobuf/util/json_util.h>

#include "grpc_client.h"
#include "http_client.h"
#ifdef PA_ENABLE_INPROC
#include "tpuserver_loader.h"
#endif

namespace pa {

// Triton-HTTP backend: wraps the client library
// (reference client_backend/triton/triton_client_backend.{h,cc}).
class TritonHttpBackend : public ClientBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    auto* b = new TritonHttpBackend();
    // a CA/cert/key or disabled-verify setting only engages when the
    // URL carries the https scheme (reference curl semantics)
    tc::Error err = tc::InferenceServerHttpClient::Create(
        &b->client_, config.url, config.verbose, config.concurrency,
        config.http_ssl);
    if (!err.IsOk()) {
      delete b;
      return err;
    }
    backend->reset(b);
    return tc::Error::Success;
  }

  tc::Error ServerReady(bool* ready) override
  {
    return client_->IsServerReady(ready);
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) override
  {
    return client_->ModelMetadata(
        metadata_json, model_name, model_version);
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) override
  {
    return client_->ModelConfig(config_json, model_name, model_version);
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) override
  {
    return client_->ModelInferenceStatistics(stats_json, model_name);
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    std::vector<std::unique_ptr<tc::InferInput>> owned_inputs;
    std::vector<std::unique_ptr<tc::InferRequestedOutput>> owned_outputs;
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    tc::Error err =
        BuildRequest(request, &owned_inputs, &owned_outputs, &inputs,
                     &outputs);
    if (!err.IsOk()) {
      return err;
    }
    tc::InferOptions options(request.model_name);
    FillOptions(request, &options);
    tc::InferResult* raw_result = nullptr;
    err = client_->Infer(&raw_result, options, inputs, outputs);
    if (!err.IsOk()) {
      return err;
    }
    Convert(raw_result, request, result);
    delete raw_result;
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback, const BackendInferRequest& request) override
  {
    // buffers must outlive the wire write: own them in shared state bound
    // into the completion lambda
    auto owned_inputs =
        std::make_shared<std::vector<std::unique_ptr<tc::InferInput>>>();
    auto owned_outputs = std::make_shared<
        std::vector<std::unique_ptr<tc::InferRequestedOutput>>>();
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    tc::Error err = BuildRequest(
        request, owned_inputs.get(), owned_outputs.get(), &inputs,
        &outputs);
    if (!err.IsOk()) {
      return err;
    }
    tc::InferOptions options(request.model_name);
    FillOptions(request, &options);
    // only the output names are needed at completion; don't copy the
    // (possibly large) input payloads into the lambda
    std::vector<std::string> output_names = request.requested_outputs;
    return client_->AsyncInfer(
        [callback, owned_inputs, owned_outputs,
         output_names](tc::InferResult* raw_result) {
          BackendInferResult result;
          ConvertOutputs(raw_result, output_names, &result);
          delete raw_result;
          callback(std::move(result));
        },
        options, inputs, outputs);
  }

  tc::Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key,
      size_t byte_size) override
  {
    return client_->RegisterSystemSharedMemory(name, key, byte_size);
  }
  tc::Error UnregisterSystemSharedMemory(const std::string& name) override
  {
    return client_->UnregisterSystemSharedMemory(name);
  }
  tc::Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal) override
  {
    return client_->RegisterXlaSharedMemory(
        name, raw_handle, byte_size, device_ordinal);
  }
  tc::Error UnregisterXlaSharedMemory(const std::string& name) override
  {
    return client_->UnregisterXlaSharedMemory(name);
  }

  tc::Error UpdateTraceSettings(
      const std::map<std::string, std::vector<std::string>>& settings)
      override
  {
    std::string response;
    return client_->UpdateTraceSettings(&response, "", settings);
  }

 private:
  static void FillOptions(
      const BackendInferRequest& request, tc::InferOptions* options)
  {
    options->model_version_ = request.model_version;
    options->request_id_ = request.request_id;
    options->sequence_id_ = request.sequence_id;
    options->sequence_start_ = request.sequence_start;
    options->sequence_end_ = request.sequence_end;
  }

  static tc::Error BuildRequest(
      const BackendInferRequest& request,
      std::vector<std::unique_ptr<tc::InferInput>>* owned_inputs,
      std::vector<std::unique_ptr<tc::InferRequestedOutput>>* owned_outputs,
      std::vector<tc::InferInput*>* inputs,
      std::vector<const tc::InferRequestedOutput*>* outputs)
  {
    for (const auto& in : request.inputs) {
      tc::InferInput* input;
      tc::Error err =
          tc::InferInput::Create(&input, in.name, in.shape, in.datatype);
      if (!err.IsOk()) {
        return err;
      }
      owned_inputs->emplace_back(input);
      if (!in.shm_region.empty()) {
        input->SetSharedMemory(
            in.shm_region, in.shm_byte_size, in.shm_offset);
      } else {
        input->AppendRaw(in.data.data(), in.data.size());
      }
      inputs->push_back(input);
    }
    for (const auto& name : request.requested_outputs) {
      tc::InferRequestedOutput* output;
      tc::Error err = tc::InferRequestedOutput::Create(&output, name);
      if (!err.IsOk()) {
        return err;
      }
      owned_outputs->emplace_back(output);
      outputs->push_back(output);
    }
    return tc::Error::Success;
  }

  static void Convert(
      tc::InferResult* raw, const BackendInferRequest& request,
      BackendInferResult* result)
  {
    ConvertOutputs(raw, request.requested_outputs, result);
  }

  static void ConvertOutputs(
      tc::InferResult* raw, const std::vector<std::string>& output_names,
      BackendInferResult* result)
  {
    result->status = raw->RequestStatus();
    raw->Id(&result->request_id);
    if (!result->status.IsOk()) {
      return;
    }
    for (const auto& name : output_names) {
      const uint8_t* buf;
      size_t len;
      if (raw->RawData(name, &buf, &len).IsOk()) {
        result->outputs[name].assign(buf, buf + len);
      }
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client_;
};

// Triton-gRPC backend: wraps the gRPC client library (role of the gRPC
// path in reference client_backend/triton/triton_client_backend.{h,cc}).
// Metadata/config/statistics come back as protobuf and are converted to
// JSON so the model parser sees one format for both protocols.
class TritonGrpcBackend : public ClientBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    auto* b = new TritonGrpcBackend();
    tc::Error err = tc::InferenceServerGrpcClient::Create(
        &b->client_, config.url, config.verbose, config.grpc_use_ssl,
        config.grpc_ssl);
    if (err.IsOk() && !config.grpc_compression.empty()) {
      err = b->client_->SetInferCompression(config.grpc_compression);
    }
    if (!err.IsOk()) {
      delete b;
      return err;
    }
    backend->reset(b);
    return tc::Error::Success;
  }

  tc::Error ServerReady(bool* ready) override
  {
    return client_->IsServerReady(ready);
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) override
  {
    inference::ModelMetadataResponse metadata;
    tc::Error err =
        client_->ModelMetadata(&metadata, model_name, model_version);
    if (!err.IsOk()) {
      return err;
    }
    return ToJson(metadata, metadata_json);
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) override
  {
    inference::ModelConfigResponse config;
    tc::Error err = client_->ModelConfig(&config, model_name, model_version);
    if (!err.IsOk()) {
      return err;
    }
    // the parser expects the bare config object, not the RPC wrapper
    return ToJson(config.config(), config_json);
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) override
  {
    inference::ModelStatisticsResponse stats;
    tc::Error err = client_->ModelInferenceStatistics(&stats, model_name);
    if (!err.IsOk()) {
      return err;
    }
    return ToJson(stats, stats_json);
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    std::vector<std::unique_ptr<tc::InferInput>> owned_inputs;
    std::vector<std::unique_ptr<tc::InferRequestedOutput>> owned_outputs;
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    tc::Error err = BuildRequest(
        request, &owned_inputs, &owned_outputs, &inputs, &outputs);
    if (!err.IsOk()) {
      return err;
    }
    tc::InferOptions options(request.model_name);
    FillOptions(request, &options);
    tc::InferResult* raw_result = nullptr;
    err = client_->Infer(&raw_result, options, inputs, outputs);
    if (!err.IsOk()) {
      return err;
    }
    ConvertOutputs(raw_result, request.requested_outputs, result);
    delete raw_result;
    return tc::Error::Success;
  }

  tc::Error AsyncInfer(
      BackendCallback callback, const BackendInferRequest& request) override
  {
    auto owned_inputs =
        std::make_shared<std::vector<std::unique_ptr<tc::InferInput>>>();
    auto owned_outputs = std::make_shared<
        std::vector<std::unique_ptr<tc::InferRequestedOutput>>>();
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    tc::Error err = BuildRequest(
        request, owned_inputs.get(), owned_outputs.get(), &inputs, &outputs);
    if (!err.IsOk()) {
      return err;
    }
    tc::InferOptions options(request.model_name);
    FillOptions(request, &options);
    std::vector<std::string> output_names = request.requested_outputs;
    return client_->AsyncInfer(
        [callback, owned_inputs, owned_outputs,
         output_names](tc::InferResult* raw_result) {
          BackendInferResult result;
          ConvertOutputs(raw_result, output_names, &result);
          delete raw_result;
          callback(std::move(result));
        },
        options, inputs, outputs);
  }

  tc::Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key,
      size_t byte_size) override
  {
    return client_->RegisterSystemSharedMemory(name, key, byte_size);
  }
  tc::Error UnregisterSystemSharedMemory(const std::string& name) override
  {
    return client_->UnregisterSystemSharedMemory(name);
  }
  tc::Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal) override
  {
    return client_->RegisterXlaSharedMemory(
        name, raw_handle, byte_size, device_ordinal);
  }
  tc::Error UnregisterXlaSharedMemory(const std::string& name) override
  {
    return client_->UnregisterXlaSharedMemory(name);
  }

  tc::Error StartStream(BackendCallback stream_callback) override
  {
    return client_->StartStream(
        [stream_callback](tc::InferResult* raw_result) {
          auto* grpc_result = static_cast<tc::InferResultGrpc*>(raw_result);
          BackendInferResult result;
          result.status = raw_result->RequestStatus();
          raw_result->Id(&result.request_id);
          result.final_response = grpc_result->IsFinalResponse();
          delete raw_result;
          stream_callback(std::move(result));
        },
        /*enable_stats=*/false);
  }

  tc::Error StopStream() override { return client_->StopStream(); }

  tc::Error StreamInfer(const BackendInferRequest& request) override
  {
    std::vector<std::unique_ptr<tc::InferInput>> owned_inputs;
    std::vector<std::unique_ptr<tc::InferRequestedOutput>> owned_outputs;
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    tc::Error err = BuildRequest(
        request, &owned_inputs, &owned_outputs, &inputs, &outputs);
    if (!err.IsOk()) {
      return err;
    }
    tc::InferOptions options(request.model_name);
    FillOptions(request, &options);
    options.triton_enable_empty_final_response_ =
        request.enable_empty_final_response;
    // AsyncStreamInfer serializes the request before returning, so the
    // stack-owned input buffers are safe to release afterwards
    return client_->AsyncStreamInfer(options, inputs, outputs);
  }

  tc::Error UpdateTraceSettings(
      const std::map<std::string, std::vector<std::string>>& settings)
      override
  {
    inference::TraceSettingResponse response;
    return client_->UpdateTraceSettings(&response, "", settings);
  }

 private:
  static tc::Error ToJson(
      const google::protobuf::Message& message, std::string* json)
  {
    google::protobuf::util::JsonPrintOptions options;
    options.preserve_proto_field_names = true;
    json->clear();
    auto status =
        google::protobuf::util::MessageToJsonString(message, json, options);
    if (!status.ok()) {
      return tc::Error("protobuf -> json conversion failed");
    }
    return tc::Error::Success;
  }

  static void FillOptions(
      const BackendInferRequest& request, tc::InferOptions* options)
  {
    options->model_version_ = request.model_version;
    options->request_id_ = request.request_id;
    options->sequence_id_ = request.sequence_id;
    options->sequence_start_ = request.sequence_start;
    options->sequence_end_ = request.sequence_end;
  }

  static tc::Error BuildRequest(
      const BackendInferRequest& request,
      std::vector<std::unique_ptr<tc::InferInput>>* owned_inputs,
      std::vector<std::unique_ptr<tc::InferRequestedOutput>>* owned_outputs,
      std::vector<tc::InferInput*>* inputs,
      std::vector<const tc::InferRequestedOutput*>* outputs)
  {
    for (const auto& in : request.inputs) {
      tc::InferInput* input;
      tc::Error err =
          tc::InferInput::Create(&input, in.name, in.shape, in.datatype);
      if (!err.IsOk()) {
        return err;
      }
      owned_inputs->emplace_back(input);
      if (!in.shm_region.empty()) {
        input->SetSharedMemory(in.shm_region, in.shm_byte_size, in.shm_offset);
      } else {
        input->AppendRaw(in.data.data(), in.data.size());
      }
      inputs->push_back(input);
    }
    for (const auto& name : request.requested_outputs) {
      tc::InferRequestedOutput* output;
      tc::Error err = tc::InferRequestedOutput::Create(&output, name);
      if (!err.IsOk()) {
        return err;
      }
      owned_outputs->emplace_back(output);
      outputs->push_back(output);
    }
    return tc::Error::Success;
  }

  static void ConvertOutputs(
      tc::InferResult* raw, const std::vector<std::string>& output_names,
      BackendInferResult* result)
  {
    result->status = raw->RequestStatus();
    raw->Id(&result->request_id);
    if (!result->status.IsOk()) {
      return;
    }
    for (const auto& name : output_names) {
      const uint8_t* buf;
      size_t len;
      if (raw->RawData(name, &buf, &len).IsOk()) {
        result->outputs[name].assign(buf, buf + len);
      }
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client_;
};

#ifdef PA_ENABLE_INPROC
// In-process backend: serves through the embedded tpuserver runtime,
// no sockets (role of reference triton_c_api backend; like it, issue
// is synchronous — AsyncInfer completes inline,
// reference docs/benchmarking.md:92-98).
class InProcessBackend : public ClientBackend {
 public:
  static tc::Error Create(
      std::shared_ptr<ClientBackend>* backend,
      const BackendFactoryConfig& config)
  {
    TpuServerLoader::Options options;
    options.server_src = config.server_src;
    options.include_vision = config.inproc_vision;
    options.verbose = config.verbose;
    tc::Error err = TpuServerLoader::Create(options);
    if (!err.IsOk()) {
      return err;
    }
    backend->reset(new InProcessBackend());
    return tc::Error::Success;
  }

  tc::Error ServerReady(bool* ready) override
  {
    return TpuServerLoader::GetSingleton()->ServerReady(ready);
  }

  tc::Error ModelMetadata(
      std::string* metadata_json, const std::string& model_name,
      const std::string& model_version) override
  {
    return TpuServerLoader::GetSingleton()->ModelMetadata(
        metadata_json, model_name, model_version);
  }

  tc::Error ModelConfig(
      std::string* config_json, const std::string& model_name,
      const std::string& model_version) override
  {
    return TpuServerLoader::GetSingleton()->ModelConfig(
        config_json, model_name, model_version);
  }

  tc::Error ModelStatistics(
      std::string* stats_json, const std::string& model_name) override
  {
    return TpuServerLoader::GetSingleton()->ModelStatistics(
        stats_json, model_name);
  }

  tc::Error Infer(
      BackendInferResult* result,
      const BackendInferRequest& request) override
  {
    return TpuServerLoader::GetSingleton()->Infer(result, request);
  }

  tc::Error AsyncInfer(
      BackendCallback callback,
      const BackendInferRequest& request) override
  {
    BackendInferResult result;
    tc::Error err =
        TpuServerLoader::GetSingleton()->Infer(&result, request);
    if (!err.IsOk()) {
      result.status = err;
    }
    callback(std::move(result));
    return tc::Error::Success;
  }

  tc::Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key,
      size_t byte_size) override
  {
    return TpuServerLoader::GetSingleton()->RegisterSystemSharedMemory(
        name, key, byte_size);
  }
  tc::Error UnregisterSystemSharedMemory(const std::string& name) override
  {
    return TpuServerLoader::GetSingleton()->UnregisterSystemSharedMemory(
        name);
  }
  tc::Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal) override
  {
    return TpuServerLoader::GetSingleton()->RegisterXlaSharedMemory(
        name, raw_handle, byte_size, device_ordinal);
  }
  tc::Error UnregisterXlaSharedMemory(const std::string& name) override
  {
    return TpuServerLoader::GetSingleton()->UnregisterXlaSharedMemory(
        name);
  }
};
#endif  // PA_ENABLE_INPROC

// REST backends (rest_backends.cc)
tc::Error CreateTFServeBackend(
    std::shared_ptr<ClientBackend>* backend,
    const BackendFactoryConfig& config);
tc::Error CreateTorchServeBackend(
    std::shared_ptr<ClientBackend>* backend,
    const BackendFactoryConfig& config);

tc::Error
ClientBackendFactory::Create(
    std::shared_ptr<ClientBackend>* backend,
    const BackendFactoryConfig& config)
{
  switch (config.kind) {
    case BackendKind::TRITON_HTTP:
      return TritonHttpBackend::Create(backend, config);
    case BackendKind::TRITON_GRPC:
      return TritonGrpcBackend::Create(backend, config);
    case BackendKind::IN_PROCESS:
#ifdef PA_ENABLE_INPROC
      return InProcessBackend::Create(backend, config);
#else
      return tc::Error(
          "in-process backend not built (libpython development files "
          "were unavailable at build time)");
#endif
    case BackendKind::TFSERVING:
      return CreateTFServeBackend(backend, config);
    case BackendKind::TORCHSERVE:
      return CreateTorchServeBackend(backend, config);
    case BackendKind::MOCK:
      return tc::Error(
          "mock backend is constructed directly in tests");
  }
  return tc::Error("unknown backend kind");
}

}  // namespace pa
