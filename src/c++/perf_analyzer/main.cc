// perf_analyzer entry point (reference main.cc:31-46): two-stage SIGINT —
// first Ctrl-C requests a graceful drain, second aborts.

#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <iostream>

#include "perf_analyzer.h"

namespace {

void
SignalHandler(int)
{
  if (pa::early_exit.load()) {
    _exit(130);
  }
  pa::early_exit.store(true);
  fprintf(stderr, "\nsignal received: finishing current measurement "
                  "(Ctrl-C again to abort)\n");
}

}  // namespace

int
main(int argc, char** argv)
{
  pa::PerfAnalyzerParameters params;
  std::string error;
  if (!pa::CLParser::Parse(argc, argv, &params, &error)) {
    std::cerr << "error: " << error << "\n" << pa::CLParser::Usage();
    return 1;
  }
  if (params.usage_requested) {
    std::cout << pa::CLParser::Usage();
    return 0;
  }
  signal(SIGINT, SignalHandler);

  pa::PerfAnalyzer analyzer(params);
  tc::Error err = analyzer.CreateAnalyzerObjects();
  if (!err.IsOk()) {
    std::cerr << "error: " << err << std::endl;
    return 1;
  }
  err = analyzer.Profile();
  if (!err.IsOk()) {
    std::cerr << "error: " << err << std::endl;
    return 1;
  }
  err = analyzer.WriteReport();
  if (!err.IsOk()) {
    std::cerr << "error: " << err << std::endl;
    return 1;
  }
  return 0;
}
